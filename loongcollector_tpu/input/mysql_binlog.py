"""service_canal — MySQL binlog (row-based replication) ingest.

Reference: plugins/input/canal/input_canal.go (go-mysql canal wrap).  The
wire protocol lives in input/binlog_protocol.py; this plugin runs the
replication thread: connect → auth → request checksum passthrough →
resolve the start position (config StartBinName/StartBinLogPos or SHOW
MASTER STATUS) → COM_REGISTER_SLAVE → COM_BINLOG_DUMP → decode the event
stream, emitting one pipeline event per row change with the reference's
field layout: _host_, _db_, _table_, _event_ (row_insert/row_update/
row_delete/ddl), _id_, _gtid_, _filename_, _offset_, column fields, and
_old_<col> for update before-images (input_canal.go:211-215, 348-390).
"""

from __future__ import annotations

import re
import socket
import struct
import threading
import time
from typing import Any, Dict, List, Optional

from ..models import PipelineEventGroup
from ..pipeline.plugin.interface import Input, PluginContext
from ..utils.logger import get_logger
from . import binlog_protocol as bp

log = get_logger("canal")


def _to_bytes(v) -> bytes:
    if v is None:
        return b"null"
    if isinstance(v, bytes):
        return v
    if isinstance(v, float):
        return repr(v).encode()
    return str(v).encode()


class InputCanal(Input):
    name = "service_canal"

    def __init__(self) -> None:
        super().__init__()
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self._sock: Optional[socket.socket] = None
        # replication state (exposed for tests)
        self.checkpoint_file = ""
        self.checkpoint_pos = 0
        self._gtid = ""
        self._counter = 0

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.host = config.get("Host", "127.0.0.1")
        self.port = int(config.get("Port", 3306))
        self.user = config.get("User", "root")
        self.password = config.get("Password", "")
        self.server_id = int(config.get("ServerID", 125))
        self.start_bin_name = config.get("StartBinName", "")
        self.start_bin_pos = int(config.get("StartBinLogPos", 0))
        self.enable_ddl = bool(config.get("EnableDDL", False))
        self.enable_xid = bool(config.get("EnableXID", False))
        self.enable_gtid = bool(config.get("EnableGTID", True))
        self.enable_insert = bool(config.get("EnableInsert", True))
        self.enable_update = bool(config.get("EnableUpdate", True))
        self.enable_delete = bool(config.get("EnableDelete", True))
        self.include = [re.compile(p) for p in
                        config.get("IncludeTables") or []]
        self.exclude = [re.compile(p) for p in
                        config.get("ExcludeTables") or []]
        return True

    def start(self) -> bool:
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="canal-replication")
        self._thread.start()
        return True

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        self._running = False
        sock = self._sock
        if sock is not None:
            try:
                sock.close()            # unblocks the read
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        return True

    # -- replication session -------------------------------------------------

    def _loop(self) -> None:
        backoff = 1.0
        while self._running:
            try:
                self._replicate_once()
                backoff = 1.0
            except Exception as e:  # noqa: BLE001 — a malformed event
                # (struct/decode errors included) must reconnect, not
                # silently kill the replication thread
                if not self._running:
                    return
                log.warning("binlog replication error: %r (reconnecting)", e)
                deadline = time.monotonic() + min(backoff, 10.0)
                backoff = min(backoff * 2, 10.0)
                while self._running and time.monotonic() < deadline:
                    time.sleep(0.1)

    def _query(self, sock: socket.socket, sql: str):
        bp.write_packet(sock, 0, bytes([bp.COM_QUERY]) + sql.encode())
        return bp.read_result_set(sock)

    def _replicate_once(self) -> None:
        sock = socket.create_connection((self.host, self.port), timeout=10)
        self._sock = sock
        try:
            sock.settimeout(30)
            seq, greeting = bp.read_packet(sock)
            salt, plugin, _caps = bp.parse_handshake(greeting)
            bp.write_packet(sock, seq + 1, bp.build_auth_response(
                self.user, self.password, salt))
            _, resp = bp.read_packet(sock)
            bp.check_ok(resp)
            if resp and resp[0] == 0xFE:
                raise bp.MySQLError(
                    f"server requires auth plugin switch "
                    f"({resp[1:].split(chr(0).encode())[0].decode(errors='replace')})")
            # checksum passthrough: tell the master we can read CRC32 tails,
            # and learn the strip width UP FRONT — the artificial first
            # ROTATE arrives checksummed BEFORE any FORMAT_DESCRIPTION
            # could reveal the algorithm
            checksum = 0
            try:
                self._query(sock,
                            "SET @master_binlog_checksum= "
                            "@@global.binlog_checksum")
                _, rows = self._query(
                    sock, "SHOW GLOBAL VARIABLES LIKE 'binlog_checksum'")
                if rows and rows[0] and (rows[0][-1] or b"").upper() \
                        == b"CRC32":
                    checksum = 4
            except bp.MySQLError:
                pass                     # pre-5.6 master
            binfile, pos = self.start_bin_name, self.start_bin_pos
            if self.checkpoint_file:     # resume after reconnect
                binfile, pos = self.checkpoint_file, self.checkpoint_pos
            if not binfile:
                _, rows = self._query(sock, "SHOW MASTER STATUS")
                if not rows:
                    raise bp.MySQLError("SHOW MASTER STATUS returned nothing"
                                        " (binlog disabled?)")
                binfile = (rows[0][0] or b"").decode()
                pos = int(rows[0][1] or b"4")
            pos = max(pos, 4)
            # COM_REGISTER_SLAVE
            payload = bytes([bp.COM_REGISTER_SLAVE])
            payload += struct.pack("<I", self.server_id)
            payload += b"\x00" * 3       # empty hostname/user/password
            payload += struct.pack("<H", 0)
            payload += struct.pack("<II", 0, 0)
            bp.write_packet(sock, 0, payload)
            _, resp = bp.read_packet(sock)
            bp.check_ok(resp)
            # COM_BINLOG_DUMP
            payload = bytes([bp.COM_BINLOG_DUMP])
            payload += struct.pack("<IHI", pos, 0, self.server_id)
            payload += binfile.encode()
            bp.write_packet(sock, 0, payload)
            self.checkpoint_file, self.checkpoint_pos = binfile, pos
            self._stream(sock, checksum)
        finally:
            self._sock = None
            try:
                sock.close()
            except OSError:
                pass

    def _stream(self, sock: socket.socket, checksum: int = 0) -> None:
        tables: Dict[int, bp.TableMap] = {}
        while self._running:
            _, payload = bp.read_packet(sock)
            if not payload:
                continue
            if payload[0] == 0xFF:
                bp.check_ok(payload)
            if payload[0] == 0xFE and len(payload) < 9:
                raise bp.MySQLError("binlog stream EOF")
            body = payload[1:]
            hdr = bp.EventHeader(body)
            data = body[bp.HEADER_LEN:]
            if hdr.type_code == bp.EV_FORMAT_DESCRIPTION:
                # checksum algorithm byte sits before the event's own CRC;
                # authoritative over the pre-dump variable probe
                checksum = 4 if len(data) > 5 and data[-5] == 1 else 0
                continue
            if checksum and hdr.type_code != bp.EV_FORMAT_DESCRIPTION:
                data = data[:-checksum]
            if hdr.type_code == bp.EV_ROTATE:
                _pos, name = bp.parse_rotate(data)
                self.checkpoint_file = name
                self.checkpoint_pos = max(_pos, 4)
                continue
            if hdr.log_pos:
                self.checkpoint_pos = hdr.log_pos
            if hdr.type_code == bp.EV_GTID:
                self._gtid = bp.parse_gtid(data)
            elif hdr.type_code == bp.EV_TABLE_MAP:
                tm = bp.TableMap(data)
                tables[tm.table_id] = tm
            elif hdr.type_code in (bp.EV_WRITE_ROWS_V1, bp.EV_WRITE_ROWS_V2,
                                   bp.EV_UPDATE_ROWS_V1,
                                   bp.EV_UPDATE_ROWS_V2,
                                   bp.EV_DELETE_ROWS_V1,
                                   bp.EV_DELETE_ROWS_V2):
                ev = bp.parse_rows_event(hdr.type_code, data, tables)
                if ev is not None:
                    self._emit_rows(hdr, ev)
            elif hdr.type_code == bp.EV_QUERY and self.enable_ddl:
                schema, query = bp.parse_query(data)
                if query.strip().upper() not in ("BEGIN", "COMMIT"):
                    self._emit_ddl(hdr, schema, query)
            elif hdr.type_code == bp.EV_XID and self.enable_xid:
                self._emit_xid(hdr, struct.unpack_from("<Q", data, 0)[0])

    # -- emission ------------------------------------------------------------

    def _want_table(self, schema: str, table: str) -> bool:
        full = f"{schema}.{table}"
        for rx in self.exclude:
            if rx.search(full):
                return False
        if not self.include:
            return True
        return any(rx.search(full) for rx in self.include)

    def _meta_fields(self, hdr) -> Dict[bytes, bytes]:
        self._counter += 1
        out = {
            b"_host_": self.host.encode(),
            b"_id_": str(self._counter).encode(),
            b"_filename_": self.checkpoint_file.encode(),
            b"_offset_": str(self.checkpoint_pos).encode(),
        }
        if self.enable_gtid:
            out[b"_gtid_"] = self._gtid.encode()
        return out

    def _push(self, fields_list: List[Dict[bytes, bytes]], ts: int) -> None:
        pqm = self.context.process_queue_manager
        if pqm is None or not fields_list:
            return
        group = PipelineEventGroup()
        sb = group.source_buffer
        for fields in fields_list:
            ev = group.add_log_event(ts or int(time.time()))
            for k, v in fields.items():
                ev.set_content(sb.copy_string(k), sb.copy_string(v))
        group.set_tag(b"__source__", b"canal")
        while self._running and not pqm.push_queue(
                self.context.process_queue_key, group):
            time.sleep(0.01)

    def _emit_rows(self, hdr, ev: bp.RowsEvent) -> None:
        if ev.action == "insert" and not self.enable_insert:
            return
        if ev.action == "update" and not self.enable_update:
            return
        if ev.action == "delete" and not self.enable_delete:
            return
        tm = ev.table
        if not self._want_table(tm.schema, tm.table):
            return
        names = tm.col_names or [f"col_{i}"
                                 for i in range(len(tm.col_types))]
        out: List[Dict[bytes, bytes]] = []
        for row in ev.rows:
            fields = self._meta_fields(hdr)
            fields[b"_db_"] = tm.schema.encode()
            fields[b"_table_"] = tm.table.encode()
            fields[b"_event_"] = f"row_{ev.action}".encode()
            if ev.action == "update":
                before, after = row
                for ci, v in after.items():
                    fields[names[ci].encode()] = _to_bytes(v)
                for ci, v in before.items():
                    fields[b"_old_" + names[ci].encode()] = _to_bytes(v)
            else:
                for ci, v in row.items():
                    fields[names[ci].encode()] = _to_bytes(v)
            out.append(fields)
        self._push(out, hdr.timestamp)

    def _emit_ddl(self, hdr, schema: str, query: str) -> None:
        fields = self._meta_fields(hdr)
        fields[b"_db_"] = schema.encode()
        fields[b"_event_"] = b"ddl"
        fields[b"ErrorCode"] = b"0"
        fields[b"_query_"] = query.encode()
        self._push([fields], hdr.timestamp)

    def _emit_xid(self, hdr, xid: int) -> None:
        fields = self._meta_fields(hdr)
        fields[b"_event_"] = b"xid"
        fields[b"_xid_"] = str(xid).encode()
        self._push([fields], hdr.timestamp)
