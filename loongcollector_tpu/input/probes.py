"""Network probe inputs: HTTP checker, nginx stub_status, netping.

Reference:
  * plugins/input/http/input_http.go — metric_http: periodic request per
    address, emitting _method_/_address_/_result_/_http_response_code_/
    _response_time_ms_ (+ optional body match and content).
  * plugins/input/nginx/input_nginx.go — ngx_http_stub_status_module
    counters (active/accepts/handled/requests/reading/writing/waiting).
  * plugins/input/netping/netping.go — icmp ping / tcping / httping with
    min/max/avg RTT summaries.  ICMP uses an unprivileged SOCK_DGRAM
    socket where the kernel allows it (ping_group_range) and degrades to
    counting failures otherwise.
"""

from __future__ import annotations

import os
import re
import select
import socket
import ssl
import struct
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

from ..models import PipelineEventGroup
from ..pipeline.plugin.interface import PluginContext
from ..utils.logger import get_logger
from .polling_base import PollingInput

log = get_logger("probes")


def _push(ctx, group: PipelineEventGroup, source: bytes) -> None:
    group.set_tag(b"__source__", source)
    pqm = ctx.process_queue_manager
    if pqm is not None and len(group):
        pqm.push_queue(ctx.process_queue_key, group)


def _put(group, ev, key: str, val) -> None:
    sb = group.source_buffer
    ev.set_content(sb.copy_string(key.encode()),
                   sb.copy_string(str(val).encode()))


# --------------------------------------------------------------- metric_http


class InputHTTPResponse(PollingInput):
    """metric_http (plugins/input/http/input_http.go)."""

    name = "metric_http"

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.addresses = [str(a) for a in config.get("Addresses") or []]
        self.address_path = str(config.get("AddressPath", ""))
        if not self.addresses and not self.address_path:
            self.addresses = ["http://localhost"]
        self.method = str(config.get("Method", "GET")).upper()
        self.body = str(config.get("Body", ""))
        self.headers = {str(k): str(v)
                        for k, v in (config.get("Headers") or {}).items()}
        self.timeout_s = max(int(config.get("ResponseTimeoutMs", 5000)),
                             100) / 1000.0
        self.per_addr_sleep = int(config.get("PerAddressSleepMs", 0)) / 1000.0
        self.include_body = bool(config.get("IncludeBody", False))
        self.insecure = bool(config.get("InsecureSkipVerify", False))
        match = config.get("ResponseStringMatch")
        self._match = re.compile(match) if match else None
        self.interval = int(config.get("IntervalMs", 60000)) / 1000.0
        return True

    def _load_addresses(self) -> List[str]:
        if self.address_path:
            try:
                with open(self.address_path, encoding="utf-8") as f:
                    lines = [l.strip() for l in f if l.strip()]
                if lines:
                    return lines
            except OSError as e:
                log.warning("metric_http: AddressPath unreadable: %s", e)
        return self.addresses

    def _probe(self, addr: str) -> Dict[str, Any]:
        if "://" not in addr:
            addr = "http://" + addr
        out: Dict[str, Any] = {"_method_": self.method, "_address_": addr,
                               "_result_": "failed",
                               "_http_response_code_": 0,
                               "_response_time_ms_": 0}
        req = urllib.request.Request(
            addr, data=self.body.encode() if self.body else None,
            headers=self.headers, method=self.method)
        ctx = ssl._create_unverified_context() if self.insecure else None
        t0 = time.perf_counter()
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s,
                                        context=ctx) as resp:
                body = resp.read()
                out["_http_response_code_"] = resp.status
                out["_result_"] = "success"
        except urllib.error.HTTPError as e:
            body = e.read()
            out["_http_response_code_"] = e.code
            out["_result_"] = "success"     # got a response — HTTP-level OK
        except (OSError, ValueError) as e:
            reason = getattr(e, "reason", e)   # URLError wraps the cause
            timed_out = isinstance(reason, (socket.timeout, TimeoutError))
            out["_result_"] = "timeout" if timed_out else "failed"
            return out
        out["_response_time_ms_"] = round(
            (time.perf_counter() - t0) * 1000, 2)
        if self._match is not None:
            ok = self._match.search(body.decode("utf-8", "replace"))
            out["_result_match_"] = "yes" if ok else "no"
            if not ok:
                out["_result_"] = "mismatch"
        if self.include_body:
            out["content"] = body.decode("utf-8", "replace")[:512 * 1024]
        return out

    def poll_once(self) -> None:
        group = PipelineEventGroup()
        now = int(time.time())
        for addr in self._load_addresses():
            fields = self._probe(addr)
            ev = group.add_log_event(now)
            for k, v in fields.items():
                _put(group, ev, k, v)
            if self.per_addr_sleep:
                time.sleep(self.per_addr_sleep)
        _push(self.context, group, b"http_probe")


# --------------------------------------------------------- nginx stub_status

_NGINX_RE = re.compile(
    rb"Active connections:\s*(\d+)\s*.*?"
    rb"(\d+)\s+(\d+)\s+(\d+)\s*"
    rb"Reading:\s*(\d+)\s*Writing:\s*(\d+)\s*Waiting:\s*(\d+)", re.S)


def parse_stub_status(body: bytes) -> Optional[Dict[str, str]]:
    m = _NGINX_RE.search(body)
    if not m:
        return None
    keys = ("active", "accepts", "handled", "requests",
            "reading", "writing", "waiting")
    return {k: m.group(i + 1).decode() for i, k in enumerate(keys)}


class InputNginxStatus(PollingInput):
    """metric_nginx_status (plugins/input/nginx/input_nginx.go)."""

    name = "metric_nginx_status"

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.urls = [str(u) for u in config.get("Urls") or []]
        self.timeout_s = max(int(config.get("ResponseTimeoutMs", 5000)),
                             100) / 1000.0
        self.insecure = bool(config.get("SkipInsecureVerify", False))
        self.interval = int(config.get("IntervalMs", 30000)) / 1000.0
        return bool(self.urls)

    def poll_once(self) -> None:
        group = PipelineEventGroup()
        now = int(time.time())
        for u in self.urls:
            try:
                ctx = (ssl._create_unverified_context()
                       if self.insecure else None)
                with urllib.request.urlopen(u, timeout=self.timeout_s,
                                            context=ctx) as resp:
                    fields = parse_stub_status(resp.read())
            except (OSError, ValueError) as e:
                log.warning("nginx_status %s: %s", u, e)
                continue
            if fields is None:
                log.warning("nginx_status %s: unparseable body", u)
                continue
            ev = group.add_log_event(now)
            parsed = urllib.parse.urlparse(u)
            _put(group, ev, "server", parsed.hostname or "")
            _put(group, ev, "port", parsed.port or 80)
            for k, v in fields.items():
                _put(group, ev, k, v)
        _push(self.context, group, b"nginx_status")


# ------------------------------------------------------------------- netping


def _icmp_ping(target: str, count: int, timeout_s: float
               ) -> Tuple[int, List[float]]:
    """Unprivileged ICMP echo (SOCK_DGRAM). Returns (sent, rtts_ms)."""
    try:
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM,
                             socket.getprotobyname("icmp"))
    except (OSError, PermissionError):
        return 0, []
    rtts: List[float] = []
    try:
        sock.settimeout(timeout_s)
        try:
            addr = (socket.gethostbyname(target), 0)
        except OSError:
            return count, []       # unresolvable target = all probes failed
        for seq in range(count):
            payload = struct.pack("!d", time.perf_counter()) + b"loong"
            header = struct.pack("!BBHHH", 8, 0, 0, os.getpid() & 0xFFFF,
                                 seq)
            csum = _icmp_checksum(header + payload)
            packet = struct.pack("!BBHHH", 8, 0, csum,
                                 os.getpid() & 0xFFFF, seq) + payload
            t0 = time.perf_counter()
            try:
                sock.sendto(packet, addr)
                ready = select.select([sock], [], [], timeout_s)
                if not ready[0]:
                    continue
                data, _ = sock.recvfrom(1024)
                rtts.append((time.perf_counter() - t0) * 1000)
            except OSError:
                continue
    finally:
        sock.close()
    return count, rtts


def _icmp_checksum(data: bytes) -> int:
    if len(data) % 2:
        data += b"\x00"
    s = sum(struct.unpack(f"!{len(data)//2}H", data))
    s = (s >> 16) + (s & 0xFFFF)
    s += s >> 16
    return ~s & 0xFFFF


def _tcp_ping(target: str, port: int, count: int, timeout_s: float
              ) -> Tuple[int, List[float]]:
    rtts: List[float] = []
    for _ in range(count):
        t0 = time.perf_counter()
        try:
            s = socket.create_connection((target, port), timeout=timeout_s)
            rtts.append((time.perf_counter() - t0) * 1000)
            s.close()
        except OSError:
            continue
    return count, rtts


class InputNetPing(PollingInput):
    """metric_input_netping (plugins/input/netping/netping.go): ICMP /
    tcping / httping probes emitting success counts + RTT summaries."""

    name = "metric_input_netping"

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.interval = min(max(int(config.get("IntervalSeconds", 60)), 5),
                            86400)
        self.timeout_s = min(max(int(config.get("TimeoutSeconds", 5)), 1),
                             30)
        self.icmp = list(config.get("ICMPConfigs") or [])
        self.tcp = list(config.get("TCPConfigs") or [])
        self.http = list(config.get("HTTPConfigs") or [])
        return bool(self.icmp or self.tcp or self.http)

    @staticmethod
    def _summary(ev, group, sent: int, rtts: List[float]) -> None:
        _put(group, ev, "total", sent)
        _put(group, ev, "success", len(rtts))
        _put(group, ev, "failed", sent - len(rtts))
        if rtts:
            avg = sum(rtts) / len(rtts)
            _put(group, ev, "min_rtt_ms", round(min(rtts), 3))
            _put(group, ev, "max_rtt_ms", round(max(rtts), 3))
            _put(group, ev, "avg_rtt_ms", round(avg, 3))
            var = sum((r - avg) ** 2 for r in rtts) / len(rtts)
            _put(group, ev, "stddev_rtt_ms", round(var ** 0.5, 3))

    def poll_once(self) -> None:
        group = PipelineEventGroup()
        now = int(time.time())
        for cfg in self.icmp:
            count = int(cfg.get("count", cfg.get("Count", 3)))
            target = str(cfg.get("target", cfg.get("Target", "")))
            sent, rtts = _icmp_ping(target, count, self.timeout_s)
            ev = group.add_log_event(now)
            _put(group, ev, "type", "ping")
            _put(group, ev, "target", target)
            if sent == 0:
                _put(group, ev, "error", "icmp socket unavailable")
            self._summary(ev, group, sent, rtts)
        for cfg in self.tcp:
            count = int(cfg.get("count", cfg.get("Count", 3)))
            target = str(cfg.get("target", cfg.get("Target", "")))
            port = int(cfg.get("port", cfg.get("Port", 80)))
            sent, rtts = _tcp_ping(target, port, count, self.timeout_s)
            ev = group.add_log_event(now)
            _put(group, ev, "type", "tcping")
            _put(group, ev, "target", f"{target}:{port}")
            self._summary(ev, group, sent, rtts)
        for cfg in self.http:
            target = str(cfg.get("target", cfg.get("Target", "")))
            method = str(cfg.get("method", cfg.get("Method", "GET")))
            expect_code = int(cfg.get("expect_code",
                                      cfg.get("ExpectCode", 0)))
            ev = group.add_log_event(now)
            _put(group, ev, "type", "httping")
            _put(group, ev, "target", target)
            t0 = time.perf_counter()
            try:
                req = urllib.request.Request(target, method=method.upper())
                with urllib.request.urlopen(req,
                                            timeout=self.timeout_s) as resp:
                    body = resp.read()
                    code = resp.status
            except urllib.error.HTTPError as e:
                body = b""
                code = e.code
            except (OSError, ValueError):
                _put(group, ev, "success", 0)
                _put(group, ev, "failed", 1)
                continue
            rt_ms = round((time.perf_counter() - t0) * 1000, 2)
            ok = (code == expect_code) if expect_code else (code < 400)
            expect_body = str(cfg.get("expect_response_contains",
                                      cfg.get("ExpectResponseContains", "")))
            if ok and expect_body:
                ok = expect_body.encode() in body
            _put(group, ev, "success", 1 if ok else 0)
            _put(group, ev, "failed", 0 if ok else 1)
            _put(group, ev, "http_rt_ms", rt_ms)
            _put(group, ev, "http_response_code", code)
            _put(group, ev, "http_response_size", len(body))
        _push(self.context, group, b"netping")
