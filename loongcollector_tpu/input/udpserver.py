"""service_udp_server — generic UDP ingest through a pluggable decoder.

Reference: plugins/input/udpserver/input_udp.go (datagram → decoder
extension → collector) and shared_udp_server.go (one socket fan-out to
many pipelines keyed by a dispatch tag — jmxfetch's statsd channel,
manager.go:173).

The decoder is either a Format name handled by `decode_payload` below
(influxdb / statsd / json / raw) or an `ext_default_decoder` instance
resolved from the pipeline's extension registry.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..models import PipelineEventGroup
from ..pipeline.plugin.interface import Input, PluginContext
from ..utils.logger import get_logger

log = get_logger("udpserver")


def decode_payload(fmt: str, data: bytes) -> Optional[PipelineEventGroup]:
    """One datagram → one event group (or None when nothing decoded).

    Non-raw formats delegate to the shared per-format parser
    (http_server.parse_body — same code path as the HTTP ingest and
    ext_default_decoder); only "raw" differs, because a datagram is one
    message rather than a line stream."""
    group = PipelineEventGroup()
    if fmt == "raw":                       # one event per datagram
        ev = group.add_log_event(int(time.time()))
        ev.set_content(b"content", group.source_buffer.copy_string(data))
        return group
    from .http_server import parse_body
    try:
        n = parse_body(fmt, data, group)
    except ValueError:
        return None
    return group if n else None


class UDPServer:
    """Datagram loop shared by the plain input and the shared dispatcher."""

    def __init__(self, address: str, fmt: str,
                 sink: Callable[[PipelineEventGroup], None],
                 max_buffer_size: int = 65535,
                 decoder_ext=None):
        host, _, port = address.rpartition(":")
        self.host = host.replace("udp://", "") or "0.0.0.0"
        self.port = int(port)
        self.fmt = fmt
        self.sink = sink
        self.max_buffer_size = max_buffer_size
        self.decoder_ext = decoder_ext
        self._sock: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self._running = False

    def start(self) -> bool:
        try:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._sock.bind((self.host, self.port))
            self._sock.settimeout(0.2)
        except OSError as e:
            log.error("udp bind %s:%d failed: %s", self.host, self.port, e)
            return False
        if self.port == 0:                 # ephemeral: report what we got
            self.port = self._sock.getsockname()[1]
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="udp-server")
        self._thread.start()
        return True

    def stop(self) -> None:
        self._running = False
        if self._thread:
            self._thread.join(timeout=2)
            self._thread = None
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _loop(self) -> None:
        # snapshot: stop() nulls self._sock after a timed-out join, and the
        # loop must exit quietly instead of dying on AttributeError
        sock = self._sock
        while self._running:
            try:
                data, _ = sock.recvfrom(self.max_buffer_size)
            except socket.timeout:
                continue
            except OSError:
                return
            if not data:
                continue
            try:
                if self.decoder_ext is not None:
                    for g in self.decoder_ext.decode(data) or []:
                        self.sink(g)
                else:
                    g = decode_payload(self.fmt, data)
                    if g is not None:
                        self.sink(g)
            except Exception:  # noqa: BLE001 — bad datagrams must not kill it
                log.exception("udp decode failed")


class SharedUDPServer:
    """One UDP socket, many pipelines: events route by a dispatch tag.

    The tag (reference `__labels__` cut, shared_udp_server.go:60-78) is a
    metric tag whose value picks the registered sink; statsd clients add
    it via dogstatsd #tags (jmxfetch configs set `jmxfetch_ilogtail`)."""

    def __init__(self, address: str, fmt: str, dispatch_key: str,
                 max_buffer_size: int = 65535):
        self.dispatch_key = dispatch_key.encode()
        self._sinks: Dict[bytes, Callable[[PipelineEventGroup], None]] = {}
        self._lock = threading.Lock()
        self.udp = UDPServer(address, fmt, self._dispatch,
                             max_buffer_size)

    @property
    def port(self) -> int:
        return self.udp.port

    def is_running(self) -> bool:
        return self.udp._running

    def start(self) -> bool:
        return self.udp.start()

    def stop(self) -> None:
        self.udp.stop()

    def register(self, key: str,
                 sink: Callable[[PipelineEventGroup], None]) -> None:
        with self._lock:
            self._sinks[key.encode()] = sink

    def unregister(self, key: str) -> None:
        with self._lock:
            self._sinks.pop(key.encode(), None)
            # callers stop the socket when the last sink leaves

    def sink_count(self) -> int:
        with self._lock:
            return len(self._sinks)

    def _dispatch(self, group: PipelineEventGroup) -> None:
        routed: Dict[bytes, List] = {}
        for ev in group.events:
            tags = getattr(ev, "tags", None)
            if not tags:
                continue
            tag = tags.pop(self.dispatch_key, None)
            if tag is None:
                continue
            routed.setdefault(tag.to_bytes(), []).append(ev)
        with self._lock:
            sinks = dict(self._sinks)
        for key, events in routed.items():
            sink = sinks.get(key)
            if sink is None:
                log.warning("no sink for dispatch tag %r", key)
                continue
            out = PipelineEventGroup(group.source_buffer)
            # derived groups inherit the parent's metadata — including the
            # loongslo ingest stamp, which must survive the re-route
            group.copy_meta_to(out)
            out.events.extend(events)
            sink(out)


class InputUDPServer(Input):
    """service_udp_server (plugins/input/udpserver/input_udp.go)."""

    name = "input_udp_server"

    def __init__(self) -> None:
        super().__init__()
        self.server: Optional[UDPServer] = None

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self._address = str(config.get("Address", "0.0.0.0:18889"))
        self._format = str(config.get("Format", "raw")).lower()
        self._max_buffer = int(config.get("MaxBufferSize", 65535))
        self._decoder_ref = config.get("Decoder", "")
        host, sep, port = self._address.replace("udp://", "").rpartition(":")
        if not sep or not port.isdigit():
            log.error("input_udp_server Address must be host:port, got %r",
                      self._address)
            return False
        return True

    def start(self) -> bool:
        pqm = self.context.process_queue_manager
        key = self.context.process_queue_key
        decoder_ext = (self.context.get_extension(str(self._decoder_ref))
                       if self._decoder_ref else None)

        def sink(group: PipelineEventGroup) -> None:
            group.set_tag(b"__source__", b"udp")
            pqm.push_queue(key, group)

        self.server = UDPServer(self._address, self._format, sink,
                                self._max_buffer, decoder_ext)
        return self.server.start()

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        if self.server:
            self.server.stop()
            self.server = None
        return True
