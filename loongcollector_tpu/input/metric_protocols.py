"""Influx line-protocol and (dog)statsd wire decoders.

Reference: pkg/protocol/decoder/influxdb/decoder.go (points → multi-value
metric events) and pkg/protocol/decoder/statsd/ (statsd datagrams), which
back `ext_default_decoder` Format "influxdb"/"statsd" and through it the
telegraf bridge (plugins/input/telegraf/) and jmxfetch statsd ingest
(plugins/input/jmxfetch/manager.go:173).

Both decoders emit MetricEvents: influx points keep their field set as a
multi-value metric named after the measurement; statsd lines become
single-value metrics with dogstatsd #tags.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from ..models import PipelineEventGroup

_PRECISION_NS = {"ns": 1, "n": 1, "us": 1_000, "u": 1_000, "ms": 1_000_000,
                 "s": 1_000_000_000, "m": 60 * 1_000_000_000,
                 "h": 3600 * 1_000_000_000}


def _unescape(s: str, specials: str) -> str:
    if "\\" not in s:
        return s
    out = []
    i = 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s) and s[i + 1] in specials + "\\":
            out.append(s[i + 1])
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _split_unescaped(s: str, sep: str) -> List[str]:
    """Split on `sep` outside backslash escapes and double quotes."""
    parts: List[str] = []
    cur: List[str] = []
    in_quote = False
    i = 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            cur.append(c)
            cur.append(s[i + 1])
            i += 2
            continue
        if c == '"':
            in_quote = not in_quote
        if c == sep and not in_quote:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(c)
        i += 1
    parts.append("".join(cur))
    return parts


def _parse_field_value(raw: str) -> Tuple[Optional[float], Optional[str]]:
    """→ (numeric, string): exactly one is non-None."""
    if not raw:
        return None, ""
    if raw[0] == '"':
        body = raw[1:-1] if raw.endswith('"') and len(raw) >= 2 else raw[1:]
        return None, body.replace('\\"', '"').replace("\\\\", "\\")
    if raw in ("t", "T", "true", "True", "TRUE"):
        return 1.0, None
    if raw in ("f", "F", "false", "False", "FALSE"):
        return 0.0, None
    if raw[-1] in "iu":           # 42i / 42u integer suffixes
        raw = raw[:-1]
    try:
        return float(raw), None
    except ValueError:
        return None, raw


def parse_influx_lines(body: bytes, group: PipelineEventGroup,
                       precision: str = "") -> int:
    """Influx line protocol → multi-value MetricEvents in `group`.

    Unparseable lines are skipped (the reference decoder rejects the whole
    batch; per-line skip keeps a telegraf stream alive across one bad
    point).  Returns the number of events added."""
    scale = _PRECISION_NS.get(precision or "ns", 1)
    sb = group.source_buffer
    n = 0
    now_ns = time.time_ns()
    for raw_line in body.splitlines():
        line = raw_line.strip()
        if not line or line.startswith(b"#"):
            continue
        try:
            text = line.decode("utf-8", "replace")
            # measurement[,tags] <space> fields [<space> timestamp]
            head_fields = _split_unescaped(text, " ")
            head_fields = [p for p in head_fields if p != ""]
            if len(head_fields) < 2:
                continue
            head = head_fields[0]
            fields_part = head_fields[1]
            ts_ns = now_ns
            if len(head_fields) >= 3:
                try:
                    ts_ns = int(head_fields[2]) * scale
                except ValueError:
                    pass
            tag_parts = _split_unescaped(head, ",")
            measurement = _unescape(tag_parts[0], ", ")
            tags: Dict[str, str] = {}
            for tp in tag_parts[1:]:
                kv = _split_unescaped(tp, "=")
                if len(kv) == 2:
                    tags[_unescape(kv[0], ",= ")] = _unescape(kv[1], ",= ")
            values: Dict[str, float] = {}
            str_fields: Dict[str, str] = {}
            for fp in _split_unescaped(fields_part, ","):
                kv = _split_unescaped(fp, "=")
                if len(kv) != 2:
                    continue
                key = _unescape(kv[0], ",= ")
                num, s = _parse_field_value(kv[1])
                if num is not None:
                    values[key] = num
                else:
                    str_fields[key] = s or ""
            if not values and not str_fields:
                continue
            ev = group.add_metric_event(int(ts_ns // 1_000_000_000))
            ev.timestamp_ns = ts_ns % 1_000_000_000
            ev.set_name(sb.copy_string(measurement.encode()))
            for k, v in tags.items():
                ev.set_tag(sb.copy_string(k.encode()),
                           sb.copy_string(v.encode()))
            if values:
                ev.set_multi_value(values)
            for k, v in str_fields.items():
                # string fields ride as tags prefixed per the reference's
                # typed-value channel (models.ValueTypeString)
                ev.set_tag(sb.copy_string(("_string_" + k).encode()),
                           sb.copy_string(v.encode()))
            n += 1
        except Exception:  # noqa: BLE001 # loonglint: disable=unledgered-drop
            # one bad point must not kill ingest; the reject happens while
            # the group is still being BUILT — pre-admit, so the event
            # never crossed the ledger's ingest boundary
            continue
    return n


def parse_statsd_packet(body: bytes, group: PipelineEventGroup) -> int:
    """(dog)statsd datagram → MetricEvents.

    `name:v[:v2...]|type[|@rate][|#k:v,k2]`; counters are scaled by
    1/sample-rate like every statsd server.  Returns events added."""
    sb = group.source_buffer
    now = int(time.time())
    n = 0
    for raw_line in body.splitlines():
        line = raw_line.strip()
        if not line:
            continue
        try:
            text = line.decode("utf-8", "replace")
            name_part, _, rest = text.partition(":")
            if not rest:
                continue
            sections = rest.split("|")
            value_part = sections[0]
            mtype = sections[1] if len(sections) > 1 else "g"
            rate = 1.0
            tags: Dict[str, str] = {}
            for extra in sections[2:]:
                if extra.startswith("@"):
                    try:
                        rate = float(extra[1:]) or 1.0
                    except ValueError:
                        pass
                elif extra.startswith("#"):
                    for t in extra[1:].split(","):
                        k, _, v = t.partition(":")
                        if k:
                            tags[k] = v
            for one in value_part.split(":"):
                if mtype == "s":          # set: cardinality marker
                    val = 1.0
                else:
                    try:
                        val = float(one)
                    except ValueError:
                        continue
                if mtype == "c" and rate > 0:
                    val = val / rate
                ev = group.add_metric_event(now)
                ev.set_name(sb.copy_string(name_part.encode()))
                ev.set_value(val)
                ev.set_tag(b"__statsd_type__", sb.copy_string(mtype.encode()))
                for k, v in tags.items():
                    ev.set_tag(sb.copy_string(k.encode()),
                               sb.copy_string(v.encode()))
                n += 1
        except Exception:  # noqa: BLE001 # loonglint: disable=unledgered-drop
            # malformed sample skipped mid-build: pre-admit, never ledgered
            continue
    return n
