"""Log file reader: chunked reads, rollback to last complete line, rotation
tracking by (dev, inode) + content signature.

Reference: core/file_server/reader/LogFileReader.cpp — ReadLog :964,
GetRawData/ReadUTF8 :1518,1647 (pread into an arena StringBuffer, align to
the last complete line and roll back the rest), GenerateEventGroup :2726
(ONE zero-copy RawEvent per chunk); signature-based rotation detection
(CheckFileSignature); DevInode tracking (common/DevInode.h).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Optional, Tuple

from ...models import EventGroupMetaKey, PipelineEventGroup, SourceBuffer

DEFAULT_CHUNK = 512 * 1024
SIGNATURE_SIZE = 1024


@dataclass
class DevInode:
    dev: int = 0
    inode: int = 0

    def valid(self) -> bool:
        return self.inode != 0

    def __hash__(self) -> int:
        return hash((self.dev, self.inode))


def get_dev_inode(path: str) -> DevInode:
    try:
        st = os.stat(path)
        return DevInode(st.st_dev, st.st_ino)
    except OSError:
        return DevInode()


@dataclass
class ReaderCheckpoint:
    path: str = ""
    offset: int = 0
    dev: int = 0
    inode: int = 0
    signature: str = ""
    signature_size: int = 0
    update_time: float = field(default_factory=time.time)


class LogFileReader:
    def __init__(self, path: str, chunk_size: int = DEFAULT_CHUNK):
        self.path = path
        self.chunk_size = chunk_size
        self.offset = 0
        self.dev_inode = DevInode()
        self.signature = b""
        self._fd: Optional[int] = None
        self.last_read_time = 0.0

    # -- lifecycle ----------------------------------------------------------

    def open(self) -> bool:
        try:
            self._fd = os.open(self.path, os.O_RDONLY)
        except OSError:
            self._fd = None
            return False
        st = os.fstat(self._fd)
        self.dev_inode = DevInode(st.st_dev, st.st_ino)
        return True

    def close(self) -> None:
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None

    @property
    def is_open(self) -> bool:
        return self._fd is not None

    # -- signature / rotation ----------------------------------------------

    def _read_signature(self) -> bytes:
        assert self._fd is not None
        return os.pread(self._fd, SIGNATURE_SIZE, 0)

    def check_signature(self) -> bool:
        """False ⇒ file was truncated/rotated in place: restart from 0."""
        if self._fd is None:
            return True
        if not self.signature:
            self.signature = self._read_signature()
            return True
        cur = os.pread(self._fd, len(self.signature), 0)
        if cur != self.signature:
            self.signature = self._read_signature()
            self.offset = 0
            return False
        if len(self.signature) < SIGNATURE_SIZE:
            # Prefix still matches but the file was first seen short: extend
            # the signature as the file grows, so copytruncate rotation of
            # files sharing a short common prefix is still detected.
            self.signature = self._read_signature()
        return True

    def restore(self, cp: ReaderCheckpoint) -> None:
        self.offset = cp.offset
        self.signature = bytes.fromhex(cp.signature) if cp.signature else b""

    def checkpoint(self) -> ReaderCheckpoint:
        return ReaderCheckpoint(
            path=self.path, offset=self.offset,
            dev=self.dev_inode.dev, inode=self.dev_inode.inode,
            signature=self.signature.hex(),
            signature_size=len(self.signature))

    # -- reading ------------------------------------------------------------

    def has_more(self) -> bool:
        if self._fd is None:
            return False
        try:
            size = os.fstat(self._fd).st_size
        except OSError:
            return False
        return size > self.offset

    def read(self, force_flush: bool = False
             ) -> Optional[PipelineEventGroup]:
        """One chunked read → event group with ONE RawEvent (zero-copy).

        Rolls back to the last '\\n' so only complete lines ship; if the
        chunk has no newline it ships whole only when force_flush or the
        chunk filled (oversized single line).
        """
        if self._fd is None and not self.open():
            return None
        if not self.check_signature():
            pass  # rotated in place: offset reset above, fall through
        fd = self._fd  # local copy: concurrent close() → EBADF, not TypeError
        if fd is None:
            return None
        try:
            size = os.fstat(fd).st_size
        except OSError:
            return None
        if size < self.offset:       # truncated
            self.offset = 0
        want = min(self.chunk_size, size - self.offset)
        if want <= 0:
            return None
        data = os.pread(fd, want, self.offset)
        if not data:
            return None
        filled = len(data) == self.chunk_size
        nl = data.rfind(b"\n")
        if nl >= 0:
            aligned = data[: nl + 1]      # roll back the partial tail line
        elif filled or force_flush:
            aligned = data                # oversized single line / final flush
        else:
            return None                   # wait for the line to complete
        read_offset = self.offset
        self.offset += len(aligned)
        self.last_read_time = time.monotonic()

        sb = SourceBuffer(capacity=len(aligned) + 256)
        view = sb.copy_string(aligned)
        group = PipelineEventGroup(sb)
        ev = group.add_raw_event(int(time.time()))
        ev.set_content(view)
        group.set_metadata(EventGroupMetaKey.LOG_FILE_PATH, self.path)
        group.set_metadata(EventGroupMetaKey.LOG_FILE_INODE,
                           str(self.dev_inode.inode))
        group.set_metadata(EventGroupMetaKey.LOG_FILE_DEV,
                           str(self.dev_inode.dev))
        group.set_metadata(EventGroupMetaKey.LOG_FILE_OFFSET, str(read_offset))
        group.set_metadata(EventGroupMetaKey.LOG_FILE_LENGTH, str(len(aligned)))
        return group
