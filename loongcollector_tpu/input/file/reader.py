"""Log file reader: chunked reads, rollback to last complete line (or last
complete multiline RECORD), rotation tracking by (dev, inode) + signature.

Reference: core/file_server/reader/LogFileReader.cpp — ReadLog :964,
GetRawData/ReadUTF8 :1518,1647 (pread into an arena StringBuffer, align to
the last complete line and roll back the rest), multiline-aware rollback to
the last complete record :2128-2180, GenerateEventGroup :2726 (ONE
zero-copy RawEvent per chunk); signature-based rotation detection
(CheckFileSignature); DevInode tracking (common/DevInode.h).

Multiline rollback is the cheap way to carry state across read chunks: the
held-back partial record simply STAYS IN THE FILE (offset doesn't advance),
so the next read re-delivers it intact — no buffer copies, no processor
state. Only when a record cannot be held (chunk-sized record, flush
timeout) does the reader ship a broken record, marking the group so
split_multiline's carry can stitch it downstream (SURVEY.md §5.7).
"""

from __future__ import annotations

import os
import re
import time
import zlib
from dataclasses import dataclass, field
from typing import Optional, Tuple

import numpy as np

from ... import chaos, trace
from ...models import (ColumnarLogs, EventGroupMetaKey, PipelineEventGroup,
                       SourceBuffer, columnar_enabled)
from ...runner import ack_watermark

DEFAULT_CHUNK = 512 * 1024
SIGNATURE_SIZE = 1024
ML_FLUSH_TIMEOUT_S = 5.0

FP_READ = chaos.register_point("file_input.read")


@dataclass
class DevInode:
    dev: int = 0
    inode: int = 0

    def valid(self) -> bool:
        return self.inode != 0

    def __hash__(self) -> int:
        return hash((self.dev, self.inode))


def get_dev_inode(path: str) -> DevInode:
    try:
        st = os.stat(path)
        return DevInode(st.st_dev, st.st_ino)
    except OSError:
        return DevInode()


@dataclass
class ReaderCheckpoint:
    path: str = ""
    offset: int = 0
    dev: int = 0
    inode: int = 0
    signature: str = ""
    signature_size: int = 0
    update_time: float = field(default_factory=time.time)


class LogFileReader:
    def __init__(self, path: str, chunk_size: int = DEFAULT_CHUNK,
                 multiline_start: Optional[str] = None,
                 multiline_end: Optional[str] = None,
                 ml_flush_timeout: float = ML_FLUSH_TIMEOUT_S,
                 encoding: str = "utf8",
                 presplit_lines: bool = False):
        self.path = path
        # loongcolumn: assemble the group COLUMNAR at read time — line
        # spans over the chunk's arena, computed by the same
        # split_chunk_spans pass the inner split processor runs (which
        # then no-ops on the already-columnar group).  Off by default —
        # the bare reader keeps the reference one-RawEvent-per-chunk
        # contract; the file-pipeline wiring (FileServer / static input)
        # opts in because THERE the inner split is always the default
        # '\n' splitter.
        self.presplit_lines = presplit_lines
        # "gbk" transcodes chunks to UTF-8 on read (reference ReadGBK,
        # LogFileReader.cpp:1807), holding a trailing partial multibyte
        # character in the file like the newline rollback does
        self.encoding = (encoding or "utf8").lower()
        self.chunk_size = chunk_size
        self.offset = 0
        self.dev_inode = DevInode()
        self.signature = b""
        self._fd: Optional[int] = None
        self.last_read_time = 0.0
        # multiline-aware rollback (start- or end-pattern anchored)
        self._ml_start = (re.compile(multiline_start.encode("latin-1"))
                          if multiline_start else None)
        self._ml_end = (re.compile(multiline_end.encode("latin-1"))
                        if multiline_end else None)
        self._ml_flush_timeout = ml_flush_timeout
        self._ml_hold_since = 0.0   # first time the current tail was held
        self._ml_hold_size = -1     # file size at that moment
        self._prev_partial = False  # last shipped chunk broke mid-record
        self._last_consumed = 0     # rollback_last() state
        self._last_prev_partial = False

    # -- lifecycle ----------------------------------------------------------

    def open(self) -> bool:
        try:
            self._fd = os.open(self.path, os.O_RDONLY)
        except OSError:
            self._fd = None
            return False
        st = os.fstat(self._fd)
        self.dev_inode = DevInode(st.st_dev, st.st_ino)
        return True

    def close(self) -> None:
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None

    @property
    def is_open(self) -> bool:
        return self._fd is not None

    # -- signature / rotation ----------------------------------------------

    def _read_signature(self) -> bytes:
        assert self._fd is not None
        return os.pread(self._fd, SIGNATURE_SIZE, 0)

    def check_signature(self) -> bool:
        """False ⇒ file was truncated/rotated in place: restart from 0."""
        if self._fd is None:
            return True
        if not self.signature:
            self.signature = self._read_signature()
            return True
        cur = os.pread(self._fd, len(self.signature), 0)
        if cur != self.signature:
            self.signature = self._read_signature()
            self.offset = 0
            # replaced content: any held multiline state belonged to the OLD
            # file — the first new chunk must not be marked as a continuation
            self._prev_partial = False
            self._ml_hold_size = -1
            return False
        if len(self.signature) < SIGNATURE_SIZE:
            # Prefix still matches but the file was first seen short: extend
            # the signature as the file grows, so copytruncate rotation of
            # files sharing a short common prefix is still detected.
            self.signature = self._read_signature()
        return True

    def restore(self, cp: ReaderCheckpoint) -> None:
        self.offset = cp.offset
        self.signature = bytes.fromhex(cp.signature) if cp.signature else b""

    def checkpoint(self) -> ReaderCheckpoint:
        return ReaderCheckpoint(
            path=self.path, offset=self.offset,
            dev=self.dev_inode.dev, inode=self.dev_inode.inode,
            signature=self.signature.hex(),
            signature_size=len(self.signature))

    # -- reading ------------------------------------------------------------

    def backlog(self) -> int:
        """Unread bytes (size - offset); 0 when unreadable or truncated."""
        if self._fd is None:
            return 0
        try:
            size = os.fstat(self._fd).st_size
        except OSError:
            return 0
        return max(0, size - self.offset)

    def has_more(self) -> bool:
        if self._fd is None:
            return False
        try:
            size = os.fstat(self._fd).st_size
        except OSError:
            return False
        # size < offset is TRUNCATION, not emptiness: read() must run so
        # the offset resets and the rewritten content ships — a file
        # copytruncate'd below the old offset would otherwise sit unread
        # until it regrew past it
        return size != self.offset

    def read(self, force_flush: bool = False
             ) -> Optional[PipelineEventGroup]:
        """One chunked read → event group with ONE RawEvent (zero-copy).

        Rolls back to the last '\\n' so only complete lines ship; if the
        chunk has no newline it ships whole only when force_flush or the
        chunk filled (oversized single line).
        """
        if self._fd is None and not self.open():
            return None
        if not self.check_signature():
            pass  # rotated in place: offset reset above, fall through
        fd = self._fd  # local copy: concurrent close() → EBADF, not TypeError
        if fd is None:
            return None
        try:
            # injected OSError = transient read failure (NFS hiccup,
            # rotated-away fd): this poll round yields nothing, the next
            # one re-reads from the unchanged offset — no bytes skipped
            chaos.faultpoint(FP_READ, exc=OSError)
            size = os.fstat(fd).st_size
        except OSError:
            return None
        if size < self.offset:       # truncated
            self.offset = 0
            self._prev_partial = False
            self._ml_hold_size = -1
        if (not force_flush and self._ml_hold_size == size
                and time.monotonic() - self._ml_hold_since
                < self._ml_flush_timeout):
            # still holding the same open record and nothing new arrived:
            # skip the pread + backward scan (the hold would re-run on the
            # same bytes every poll round otherwise)
            return None
        want = min(self.chunk_size, size - self.offset)
        if want <= 0:
            return None
        data = os.pread(fd, want, self.offset)
        if not data:
            return None
        filled = len(data) == self.chunk_size
        nl = data.rfind(b"\n")
        if nl >= 0:
            aligned = data[: nl + 1]      # roll back the partial tail line
        elif filled or force_flush:
            aligned = data                # oversized single line / final flush
        else:
            return None                   # wait for the line to complete

        # multiline-aware rollback: hold the trailing INCOMPLETE record in
        # the file (reference LogFileReader.cpp:2128-2180) so records never
        # split across chunks on the normal path
        partial_tail = False
        if (self._ml_start or self._ml_end) and not force_flush:
            ship = self._ml_align(aligned)
            if ship == 0 and filled:
                # a single record larger than a whole chunk: holding is
                # impossible, ship it broken and let the carry stitch it
                partial_tail = True
            elif ship < len(aligned):
                if filled:
                    # backlog catch-up: more bytes follow immediately; hold
                    # the open tail in the file (zero-copy carry), no clock
                    aligned = aligned[:ship]
                else:
                    now = time.monotonic()
                    if size != self._ml_hold_size:
                        # new bytes arrived since we started holding —
                        # restart the flush clock
                        self._ml_hold_size = size
                        self._ml_hold_since = now
                    if now - self._ml_hold_since >= self._ml_flush_timeout:
                        partial_tail = True   # flush the open record anyway
                    else:
                        aligned = aligned[:ship]
                        if not aligned:
                            return None
            else:
                self._ml_hold_size = -1
                if self._prev_partial and self._ml_end is None:
                    # start-mode chunk with no start line at all: these
                    # lines still continue the broken record — keep the
                    # stitch chain open for the carry downstream
                    partial_tail = True
        if partial_tail or force_flush:
            self._ml_hold_size = -1
        read_offset = self.offset
        src = aligned    # pre-transcode SOURCE bytes — what the crc covers
        if self.encoding == "gbk":
            aligned, consumed_src = self._transcode_gbk(aligned, force_flush)
            if not aligned:
                return None
        else:
            consumed_src = len(aligned)
        # crc of the consumed source span: loongcrash replay dedup verifies
        # re-read content identity, not just [offset, length) containment
        span_crc = zlib.crc32(src[:consumed_src])
        # snapshot for rollback_last(): a rejected queue push must restore
        # BOTH the offset and the multiline stitch state, or the re-read
        # chunk ships without its ML_CONTINUE marker
        self._last_consumed = consumed_src
        self._last_prev_partial = self._prev_partial
        self.offset += consumed_src
        self.last_read_time = time.monotonic()

        sb = SourceBuffer(capacity=len(aligned) + 256)
        view = sb.copy_string(aligned)
        group = PipelineEventGroup(sb)
        ts = int(time.time())
        if self.presplit_lines and columnar_enabled():
            # columnar group assembly (loongcolumn): the rows ARE line
            # spans over this chunk's arena from the moment the group
            # exists — the inner split processor no-ops downstream.
            # Shares split_chunk_spans with that processor, so the two
            # split implementations cannot diverge.  Gated on
            # columnar_enabled(): in dict mode the chunk must ship as a
            # RawEvent so the split/multiline chain runs its own course —
            # a presplit group would be materialized at the split
            # boundary and silently no-op the requires_columnar
            # multiline stage.
            from ...processor.split_log_string import split_chunk_spans
            offs, lens = split_chunk_spans(sb.as_array(), view.offset,
                                           view.length, ord("\n"))
            group.set_columns(ColumnarLogs(
                offsets=np.asarray(offs, dtype=np.int32),
                lengths=lens,
                timestamps=np.full(len(offs), ts, dtype=np.int64)))
        else:
            ev = group.add_raw_event(ts)
            ev.set_content(view)
        group.set_metadata(EventGroupMetaKey.LOG_FILE_PATH, self.path)
        group.set_metadata(EventGroupMetaKey.LOG_FILE_INODE,
                           str(self.dev_inode.inode))
        group.set_metadata(EventGroupMetaKey.LOG_FILE_DEV,
                           str(self.dev_inode.dev))
        group.set_metadata(EventGroupMetaKey.LOG_FILE_OFFSET, str(read_offset))
        # SOURCE bytes consumed (≠ content length under GBK transcode):
        # exactly-once ranges and back-pressure rollback index the raw file
        group.set_metadata(EventGroupMetaKey.LOG_FILE_LENGTH,
                           str(consumed_src))
        group.set_metadata(EventGroupMetaKey.LOG_FILE_CRC32, str(span_crc))
        # the span is now in flight: the acked-offset watermark owes it a
        # terminal ack before the checkpoint may advance past it
        ack_watermark.note_read(self.dev_inode.dev, self.dev_inode.inode,
                                read_offset, consumed_src, span_crc)
        # stitch markers for split_multiline's cross-group carry: this chunk
        # ends mid-record / continues the previous chunk's open record
        if partial_tail:
            group.set_metadata(EventGroupMetaKey.ML_PARTIAL_TAIL, "1")
        if self._prev_partial:
            group.set_metadata(EventGroupMetaKey.ML_CONTINUE, "1")
        self._prev_partial = partial_tail
        # span layer head: one timeline event per shipped chunk — the
        # input-read edge of the trace (offset/bytes are content-stable,
        # so a replayed soak produces the identical read sequence)
        if trace.is_active():
            trace.event("input.read", path=self.path,
                        offset=read_offset, nbytes=consumed_src)
        return group

    def rollback_last(self) -> None:
        """Undo the last read() (queue rejected the group): offset AND the
        multiline stitch chain return to their pre-read values."""
        self.offset -= getattr(self, "_last_consumed", 0)
        self._last_consumed = 0
        self._prev_partial = getattr(self, "_last_prev_partial",
                                     self._prev_partial)
        self._ml_hold_size = -1

    @staticmethod
    def _transcode_gbk(data: bytes, force_flush: bool
                       ) -> Tuple[bytes, int]:
        """GBK bytes → (utf-8 bytes, source bytes consumed).

        A partial multibyte character at the END stays in the file (next
        read completes it) unless force_flush; invalid bytes mid-stream
        are replaced (the reference tolerates mixed content rather than
        stalling the reader). Newline alignment upstream is GBK-safe:
        0x0A never appears as a trail byte — which also means a chunk
        ENDING at a newline cannot end mid-character, so only chunks cut
        elsewhere (filled mid-line) may hold bytes back.
        """
        can_hold = not force_flush and not data.endswith(b"\n")
        consumed = len(data)
        while True:
            try:
                text = data[:consumed].decode("gbk")
                break
            except UnicodeDecodeError as ue:
                if can_hold and ue.start >= consumed - 2 \
                        and ue.end >= consumed:
                    # dangling lead byte at the chunk end: hold it
                    consumed = ue.start
                    if consumed == 0:
                        return b"", 0
                    continue
                text = data[:consumed].decode("gbk", errors="replace")
                break
        return text.encode("utf-8"), consumed

    def _ml_align(self, data: bytes) -> int:
        """Bytes of `data` that form COMPLETE multiline records.

        End-pattern mode: a record closes at each end-matching line — ship
        through the last one. Start-pattern mode: the last start-matching
        line opens a still-growing record — ship everything before it.
        Scans backward so the common case (open record = a few tail lines)
        touches only those lines. Returns len(data) when nothing anchors
        (leading unmatched content ships and is handled downstream).
        """
        e = len(data)                 # exclusive end of the current line
        if self._ml_end is not None:
            while e > 0:
                s = data.rfind(b"\n", 0, e - 1) + 1
                line = data[s:e - 1] if data[e - 1:e] == b"\n" else data[s:e]
                if self._ml_end.fullmatch(line):
                    return e          # record closed here; tail is open
                e = s
            return 0                  # no closed record yet
        while e > 0:
            s = data.rfind(b"\n", 0, e - 1) + 1
            line = data[s:e - 1] if data[e - 1:e] == b"\n" else data[s:e]
            if self._ml_start.fullmatch(line):
                return s              # this start opens the (open) tail record
            e = s
        return len(data)
