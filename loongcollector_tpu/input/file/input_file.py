"""input_file — binds FileServer discovery to this pipeline and supplies the
line-split / multiline inner processors.

Reference: core/plugin/input/InputFile.cpp:213-250 — the input creates the
inner split processors (split_log_string or split_multiline per Multiline
config) and registers its discovery options with the file server.
"""

from __future__ import annotations

from typing import Any, Dict, List

from ...pipeline.plugin.interface import Input, PluginContext
from .file_server import FileServer
from .polling import FileDiscoveryConfig
from .reader import LogFileReader


class InputFile(Input):
    name = "input_file"

    def __init__(self) -> None:
        super().__init__()
        self.discovery: FileDiscoveryConfig = None  # type: ignore
        self.multiline: Dict[str, Any] = {}
        self.tail_existing = False
        self.config_name = ""

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        paths = config.get("FilePaths", [])
        if not paths:
            return False
        self.discovery = FileDiscoveryConfig(
            file_paths=list(paths),
            exclude_file_paths=config.get("ExcludeFilePaths"),
            exclude_files=config.get("ExcludeFiles"),
            exclude_dirs=config.get("ExcludeDirs"))
        self.multiline = config.get("Multiline", {}) or {}
        self.tail_existing = bool(config.get("TailingAllMatchedFiles",
                                             config.get("TailExisted", True)))
        # unique key per plugin instance: a pipeline may hold several
        # input_file plugins and each owns its own discovery registration
        self.config_name = f"{context.pipeline_name}#{id(self)}"
        return True

    def inner_processor_configs(self) -> List[Dict[str, Any]]:
        out = [{"Type": "processor_split_log_string_native"}]
        if self.multiline.get("StartPattern") or self.multiline.get("EndPattern"):
            out.append({"Type": "processor_split_multiline_log_string_native",
                        "Multiline": self.multiline})
        return out

    def start(self) -> bool:
        fs = FileServer.instance()
        fs.add_config(self.config_name, self.discovery,
                      self.context.process_queue_key,
                      tail_existing=self.tail_existing,
                      multiline_start=self.multiline.get("StartPattern"),
                      multiline_end=self.multiline.get("EndPattern"),
                      encoding=self.config.get("FileEncoding", "utf8"))
        fs.start()
        return True

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        FileServer.instance().remove_config(self.config_name)
        return True


class InputStaticFile(Input):
    """One-shot read of matching files (reference InputStaticFile — onetime
    jobs with checkpointed progress, core/file_server/StaticFileServer)."""

    name = "input_static_file_onetime"
    is_onetime = True

    def __init__(self) -> None:
        super().__init__()
        self.paths: List[str] = []

    def inner_processor_configs(self) -> List[Dict[str, Any]]:
        # static imports read raw chunks; they need the same line split the
        # tailing input gets
        return [{"Type": "processor_split_log_string_native"}]

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.paths = list(config.get("FilePaths", []))
        return bool(self.paths)

    def start(self) -> bool:
        import glob
        from ...runner.processor_runner import ProcessorRunner
        fs = FileServer.instance()
        for pattern in self.paths:
            for path in glob.glob(pattern, recursive="**" in pattern):
                reader = LogFileReader(path, presplit_lines=True)
                if not reader.open():
                    continue
                while True:
                    group = reader.read()
                    if group is None:
                        # ship the final partial line (no trailing newline)
                        group = reader.read(force_flush=True)
                        if group is None:
                            break
                    if fs.process_queue_manager is not None:
                        while not fs.process_queue_manager.push_queue(
                                self.context.process_queue_key, group):
                            import time
                            time.sleep(0.01)
                reader.close()
        return True
