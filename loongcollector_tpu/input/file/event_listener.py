"""Linux inotify event listener for the file server.

Reference: core/file_server/event_listener/EventListener_Linux.h — inotify
watches on log directories merged with the polling discovery into one event
stream. Polling remains the source of truth (discovery, rotation, network
filesystems where inotify is silent); inotify's job is LATENCY and idle
CPU: the file-server thread sleeps on the inotify fd instead of a fixed
interval, so an append wakes it immediately instead of next poll round.

ctypes straight onto libc — no external modules.
"""

from __future__ import annotations

import ctypes
import os
import select
import struct
import sys
from typing import Dict, List, Optional, Set, Tuple

IN_MODIFY = 0x00000002
IN_CLOSE_WRITE = 0x00000008
IN_MOVED_FROM = 0x00000040
IN_MOVED_TO = 0x00000080
IN_CREATE = 0x00000100
IN_DELETE = 0x00000200

_CHANGE_MASK = (IN_MODIFY | IN_CLOSE_WRITE | IN_MOVED_FROM | IN_MOVED_TO
                | IN_CREATE | IN_DELETE)
_DISCOVERY_MASK = IN_MOVED_FROM | IN_MOVED_TO | IN_CREATE | IN_DELETE

IN_NONBLOCK = 0x800
IN_CLOEXEC = 0x80000

_EVENT_HDR = struct.Struct("iIII")  # wd, mask, cookie, len


class InotifyListener:
    """Watches directories; wait() doubles as the poll sleep."""

    def __init__(self) -> None:
        if sys.platform != "linux":
            raise OSError("inotify is Linux-only")
        self._libc = ctypes.CDLL(None, use_errno=True)
        fd = self._libc.inotify_init1(IN_NONBLOCK | IN_CLOEXEC)
        if fd < 0:
            raise OSError(ctypes.get_errno(), "inotify_init1 failed")
        self._fd = fd
        self._wd_to_dir: Dict[int, str] = {}
        self._dir_to_wd: Dict[str, int] = {}

    # -- watch management ---------------------------------------------------

    def watch_dir(self, path: str) -> bool:
        if path in self._dir_to_wd:
            return True
        wd = self._libc.inotify_add_watch(
            self._fd, path.encode(), _CHANGE_MASK)
        if wd < 0:
            return False
        self._wd_to_dir[wd] = path
        self._dir_to_wd[path] = wd
        return True

    def unwatch_missing(self, live_dirs: Set[str]) -> None:
        for path in list(self._dir_to_wd):
            if path not in live_dirs:
                wd = self._dir_to_wd.pop(path)
                self._wd_to_dir.pop(wd, None)
                self._libc.inotify_rm_watch(self._fd, wd)

    @property
    def watched_dirs(self) -> Set[str]:
        return set(self._dir_to_wd)

    # -- event wait ---------------------------------------------------------

    def wait(self, timeout: float) -> List[Tuple[str, bool]]:
        """Sleep up to `timeout` or until filesystem events arrive.

        Returns [(path, needs_discovery)] — needs_discovery marks
        create/delete/rename events (file set changed); plain modifies
        only need a reader drain.
        """
        try:
            ready, _, _ = select.select([self._fd], [], [], timeout)
        except OSError:
            return []
        if not ready:
            return []
        out: List[Tuple[str, bool]] = []
        # drain everything queued (bounded reads; fd is non-blocking)
        for _ in range(16):
            try:
                buf = os.read(self._fd, 65536)
            except BlockingIOError:
                break
            except OSError:
                break
            pos = 0
            while pos + _EVENT_HDR.size <= len(buf):
                wd, mask, _cookie, nlen = _EVENT_HDR.unpack_from(buf, pos)
                pos += _EVENT_HDR.size
                name = buf[pos:pos + nlen].split(b"\0", 1)[0].decode(
                    "utf-8", "replace")
                pos += nlen
                d = self._wd_to_dir.get(wd)
                if d is None:
                    continue
                out.append((os.path.join(d, name) if name else d,
                            bool(mask & _DISCOVERY_MASK)))
            if len(buf) < 65536:
                break
        return out

    def close(self) -> None:
        try:
            os.close(self._fd)
        except OSError:
            pass
        self._wd_to_dir.clear()
        self._dir_to_wd.clear()


def create_listener() -> Optional[InotifyListener]:
    if os.environ.get("LOONG_DISABLE_INOTIFY"):
        return None
    try:
        return InotifyListener()
    except OSError:
        return None
