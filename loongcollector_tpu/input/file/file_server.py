"""FileServer: the file-input singleton runner.

Reference: core/file_server/FileServer.cpp facade +
file_server/event_handler/LogInput.cpp:357 (ProcessLoop — the single event
thread driving discovery, modify events and reader reads, with CPU-adaptive
flow control :156-203) and BlockedEventManager (requeue on back-pressure).

One thread: each round it (1) runs discovery for every registered config on
its interval, (2) stats known files for modification, (3) drains readers of
changed files into the process queues, honouring watermark back-pressure —
a blocked read retries next round without losing the reader's offset.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from ... import recovery
from ...monitor.alarms import AlarmLevel, AlarmManager, AlarmType
from ...runner import ack_watermark
from ...utils import flags
from ...utils.logger import get_logger
from .checkpoint import CheckPointManager
from .event_listener import create_listener
from .polling import FileDiscoveryConfig, PollingDirFile
from .reader import LogFileReader

log = get_logger("file_server")

DISCOVERY_INTERVAL_S = 1.0

# reference parity knobs (reader/LogFileReader.cpp:70 read_delay_alarm_duration,
# FileReaderOptions ReadDelayAlertThresholdBytes, EventHandler.cpp:342
# FILE_READER_EXCEED_ALARM reader-count ceiling)
flags.DEFINE_FLAG_INT64("read_delay_alarm_bytes",
                        "backlog bytes before READ_LOG_DELAY_ALARM",
                        200 * 1024 * 1024)
flags.DEFINE_FLAG_INT32("read_delay_alarm_duration",
                        "seconds between repeated read-delay alarms", 60)
flags.DEFINE_FLAG_INT32("max_file_reader_num",
                        "max simultaneously open log readers", 512)
flags.DEFINE_FLAG_INT32("checkpoint_dump_interval",
                        "checkpoint dump seconds", 5)
IDLE_SLEEP_S = 0.05
# with inotify the thread sleeps ON the fd, so the poll interval can relax:
# events wake it instantly and polling is only the discovery/rotation net
IDLE_SLEEP_INOTIFY_S = 0.25


class _ConfigState:
    def __init__(self, name: str, discovery: FileDiscoveryConfig,
                 queue_key: int, tail_existing: bool,
                 multiline_start: Optional[str] = None,
                 multiline_end: Optional[str] = None,
                 encoding: str = "utf8", chunk_size: Optional[int] = None):
        self.name = name
        self.poller = PollingDirFile(discovery)
        self.queue_key = queue_key
        self.readers: Dict[str, LogFileReader] = {}
        self.rotated: List[LogFileReader] = []  # old inodes still draining
        self.last_discovery = 0.0
        self.known: List[str] = []
        self.tail_existing = tail_existing
        self.first_round = True
        self.multiline_start = multiline_start
        self.multiline_end = multiline_end
        self.encoding = encoding
        self.chunk_size = chunk_size   # None = reader default (reference
                                       # ReadBufferSize config knob)
        self.pending: set = set()   # paths with bytes left after a drain
        # optional per-path group tags (container meta on stdio inputs):
        # callable(path) -> Dict[bytes, bytes] | None
        self.tag_provider = None

    def new_reader(self, path: str) -> LogFileReader:
        kwargs = {}
        if self.chunk_size:
            kwargs["chunk_size"] = self.chunk_size
        # presplit (loongcolumn): file-pipeline groups are columnar from
        # the read — the pipelines' inner split is always the default
        # '\n' splitter and no-ops downstream
        return LogFileReader(path, multiline_start=self.multiline_start,
                             multiline_end=self.multiline_end,
                             encoding=self.encoding, presplit_lines=True,
                             **kwargs)


class FileServer:
    _instance: Optional["FileServer"] = None
    _instance_lock = threading.Lock()

    def __init__(self) -> None:
        self._configs: Dict[str, _ConfigState] = {}
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self.process_queue_manager = None
        self.checkpoints = CheckPointManager()
        self._paused = False
        # CPU-adaptive flow control (reference LogInput::FlowControl,
        # event_handler/LogInput.cpp:156-203): 0..1 fraction of the agent's
        # CPU budget in use; high levels stretch the poll sleep
        self.cpu_level_provider = None
        # inotify merged with polling (EventListener_Linux.h); None on
        # non-Linux or when LOONG_DISABLE_INOTIFY is set
        self._listener = None
        self._dirty_paths: set = set()
        # False when any watch failed (max_user_watches, permission): the
        # poll interval stays tight so unwatched paths aren't slow-tailed
        self._watch_complete = False
        # BlockedEventManager analogue (reference event_handler/
        # BlockedEventManager.cpp + queue FeedbackInterface): a watermark-
        # rejected drain registers this server as the queue's feedback, so
        # the moment the runner pops the queue below its low watermark the
        # event thread wakes and resumes the blocked readers instead of
        # waiting out the poll sleep
        self._blocked_wake = threading.Event()
        self._feedback_keys: set = set()
        # path -> last alarm time (per-file alarm rate limiting)
        self._delay_alarms: Dict[str, float] = {}
        self._reader_limit_alarms: Dict[str, float] = {}

    @classmethod
    def instance(cls) -> "FileServer":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    # -- config registration (from InputFile plugins) -----------------------

    def add_config(self, name: str, discovery: FileDiscoveryConfig,
                   queue_key: int, tail_existing: bool = False,
                   multiline_start: Optional[str] = None,
                   multiline_end: Optional[str] = None,
                   tag_provider=None, encoding: str = "utf8",
                   chunk_size: Optional[int] = None) -> None:
        with self._lock:
            st = _ConfigState(
                name, discovery, queue_key, tail_existing,
                multiline_start=multiline_start, multiline_end=multiline_end,
                encoding=encoding, chunk_size=chunk_size)
            st.tag_provider = tag_provider
            self._configs[name] = st

    def update_config_paths(self, name: str, file_paths) -> None:
        """Replace a registered config's discovery globs (container churn);
        an empty list drains and prunes all current readers next round."""
        with self._lock:
            st = self._configs.get(name)
            if st is not None:
                st.poller.config.file_paths = list(file_paths)
                st.last_discovery = 0.0  # force rediscovery next round

    def remove_config(self, name: str) -> None:
        with self._lock:
            st = self._configs.pop(name, None)
        if st:
            for r in st.readers.values():
                self.checkpoints.update(r.checkpoint())
                r.close()

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        with self._lock:
            if self._running:
                return
            self._running = True
        self.checkpoints.load()
        self._listener = create_listener()
        self._thread = threading.Thread(target=self._run, name="file-server",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        with self._lock:
            if not self._running:
                return
            self._running = False
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None
        # final flush of partial lines + checkpoints
        with self._lock:
            states = list(self._configs.values())
        for st in states:
            for r in st.readers.values():
                self._drain_reader(st, r, force_flush=True)
                self.checkpoints.update(r.checkpoint())
                r.close()
        self.checkpoints.dump()

    def pause(self) -> None:
        self._paused = True

    def resume(self) -> None:
        self._paused = False

    # -- main loop ----------------------------------------------------------

    def _run(self) -> None:
        while self._running:
            if self._paused:
                time.sleep(IDLE_SLEEP_S)
                continue
            try:
                busy = self._round()
                self.checkpoints.dump_periodically(
                    float(flags.get_flag("checkpoint_dump_interval")))
            except Exception:  # noqa: BLE001 - never kill the event thread
                log.exception("file server round failed")
                busy = False
            base = (IDLE_SLEEP_INOTIFY_S
                    if self._listener is not None and self._watch_complete
                    else IDLE_SLEEP_S)
            sleep = base
            level = self.cpu_level_provider() if self.cpu_level_provider else 0.0
            if level > 0.9:
                sleep = base * 8             # heavy throttle near the limit
            elif level > 0.7:
                sleep = base * 3
            if busy and level <= 0.9:
                continue
            if self._blocked_wake.is_set():
                # a queue we blocked on drained: resume immediately
                self._blocked_wake.clear()
                continue
            with self._lock:
                any_pending = any(st.pending for st in
                                  self._configs.values())
            if any_pending:
                # back-pressured readers outstanding: the inotify wait
                # below cannot see the feedback event, so bound the sleep
                # instead of waiting out the full (possibly throttled) tick
                sleep = min(sleep, 0.05)
            if self._listener is not None:
                # sleep ON the inotify fd: an append wakes the thread now,
                # not at the next poll tick (sub-poll-interval tail latency)
                for path, needs_discovery in self._listener.wait(sleep):
                    self._dirty_paths.add(path)
                    if needs_discovery:
                        with self._lock:
                            for st in self._configs.values():
                                st.last_discovery = 0.0
            else:
                self._blocked_wake.wait(sleep)
        if self._listener is not None:
            self._listener.close()
            self._listener = None

    def _round(self) -> bool:
        with self._lock:
            states = list(self._configs.values())
        dirty = self._dirty_paths
        self._dirty_paths = set()
        busy = False
        now = time.monotonic()
        live_dirs: set = set()
        for st in states:
            ran_discovery = False
            if now - st.last_discovery >= DISCOVERY_INTERVAL_S or st.first_round:
                ran_discovery = True
                st.last_discovery = now
                st.known = st.poller.poll()
                for path in st.known:
                    if path not in st.readers:
                        self._open_reader(st, path)
                    else:
                        self._check_rotation(st, path)
                # prune readers whose file left the glob or was deleted —
                # otherwise open fds pin deleted files' disk space forever
                known_set = set(st.known)
                for path in list(st.readers):
                    if path not in known_set:
                        r = st.readers.pop(path)
                        self._drain_reader(st, r, force_flush=True)
                        self.checkpoints.remove(r.dev_inode.dev,
                                                r.dev_inode.inode)
                        r.close()
                        self._delay_alarms.pop(path, None)
                        self._reader_limit_alarms.pop(path, None)
                st.first_round = False
            # drain readers with unread bytes. With complete inotify
            # coverage, off-discovery rounds only stat files that fired an
            # event or still had bytes after the last burst — THE idle-CPU
            # win of the listener; the periodic discovery pass remains the
            # safety net for inotify-silent filesystems.
            if self._listener is not None and self._watch_complete \
                    and not ran_discovery:
                targets = [st.readers[p]
                           for p in (dirty | st.pending) if p in st.readers]
            else:
                targets = list(st.readers.values())
            for r in targets:
                if ran_discovery:
                    # once per discovery pass is plenty for an alarm that
                    # rate-limits to one per minute; checking every poll
                    # tick would double the per-reader fstat load
                    self._check_read_delay(st, r)
                if r.has_more():
                    moved = self._drain_reader(st, r)
                    busy |= moved
                    if r.has_more():
                        st.pending.add(r.path)   # burst cap / back-pressure
                    else:
                        st.pending.discard(r.path)
                else:
                    st.pending.discard(r.path)
            for r in list(st.rotated):
                busy |= self._drain_reader(st, r, force_flush=True)
                if not r.has_more() and ack_watermark.fully_acked(
                        r.dev_inode.dev, r.dev_inode.inode):
                    # fully read AND every span terminally acked: only now
                    # may the inode's books close — dropping the checkpoint
                    # with spans still in flight would lose them on a crash.
                    # Remove only this reader's own inode entry — the live
                    # reader at the same path owns a different (dev, inode)
                    self.checkpoints.remove(r.dev_inode.dev,
                                            r.dev_inode.inode)
                    ack_watermark.tracker().forget(r.dev_inode.dev,
                                                   r.dev_inode.inode)
                    r.close()
                    st.rotated.remove(r)
            if self._listener is not None:
                import os as _os
                for path in st.known:
                    live_dirs.add(_os.path.dirname(path) or ".")
                for pattern in st.poller.config.file_paths:
                    # static prefix of each glob: catches files created later
                    d = _os.path.dirname(pattern)
                    while any(c in d for c in "*?["):
                        d = _os.path.dirname(d)
                    if d and _os.path.isdir(d):
                        live_dirs.add(d)
        if self._listener is not None:
            complete = True
            for d in live_dirs:
                complete = self._listener.watch_dir(d) and complete
            self._listener.unwatch_missing(live_dirs)
            self._watch_complete = complete
        return busy

    def _check_read_delay(self, st: _ConfigState, reader) -> None:
        """READ_LOG_DELAY_ALARM (reference LogFileReader.cpp:1540-1559):
        the writer is outrunning the reader by more than the threshold —
        alarm at most once per duration per file."""
        backlog = reader.backlog()
        if backlog <= flags.get_flag("read_delay_alarm_bytes"):
            self._delay_alarms.pop(reader.path, None)
            return
        now = time.monotonic()
        last = self._delay_alarms.get(reader.path, 0.0)
        if now - last < flags.get_flag("read_delay_alarm_duration"):
            return
        self._delay_alarms[reader.path] = now
        log.warning("read log delay: %s falls behind %d bytes",
                    reader.path, backlog)
        AlarmManager.instance().send_alarm(
            AlarmType.READ_LOG_DELAY,
            f"fall behind {backlog} bytes, path: {reader.path}",
            AlarmLevel.ERROR, st.name)

    def _register_feedback(self, queue_key: int) -> None:
        # registered on EVERY rejection (set_feedback replaces the list, so
        # this is idempotent): a deleted-and-recreated queue under the same
        # key gets the wakeup again; _feedback_keys is introspection only
        getter = getattr(self.process_queue_manager, "get_queue", None)
        q = getter(queue_key) if getter is not None else None
        if q is not None:
            q.set_feedback(self)
            self._feedback_keys.add(queue_key)

    def feedback(self, key: int) -> None:
        """Queue drained below its low watermark: wake the event thread so
        blocked readers resume immediately (FeedbackInterface)."""
        self._blocked_wake.set()

    def _check_rotation(self, st: _ConfigState, path: str) -> None:
        """rename+recreate rotation: the path's inode changed — finish the
        old inode via the rotated list, open a fresh reader at offset 0
        (reference: rotation via DevInode tracking, SURVEY.md §2.2)."""
        from .reader import get_dev_inode
        r = st.readers.get(path)
        if r is None:
            return
        cur = get_dev_inode(path)
        if cur.valid() and cur.inode != r.dev_inode.inode:
            st.rotated.append(r)
            # rotation churn must not blow past the fd ceiling: shed old
            # rotated readers first (best effort — the LIVE path always
            # reopens, or rotated data would be lost)
            self._shed_for_capacity(st, path)
            new = st.new_reader(path)
            if new.open():
                st.readers[path] = new
            else:
                del st.readers[path]

    def _reader_count(self) -> int:
        with self._lock:
            return sum(len(c.readers) + len(c.rotated)
                       for c in self._configs.values())

    def _shed_for_capacity(self, st: _ConfigState, path: str) -> bool:
        """At the reader ceiling: shed the oldest ROTATED reader (the
        reference cleans the rotator queue, EventHandler.cpp:330-348).
        Returns True when a slot was freed.  The alarm rate-limits per
        path — at a pinned limit a 1 s discovery pass would otherwise emit
        one alarm per pending file per second, forever."""
        if self._reader_count() < flags.get_flag("max_file_reader_num"):
            return True
        freed = False
        with self._lock:
            configs = list(self._configs.values())
        for c in configs:
            if c.rotated:
                old = c.rotated.pop(0)
                self.checkpoints.update(old.checkpoint())
                old.close()
                freed = True
                break
        now = time.monotonic()
        last = self._reader_limit_alarms.get(path, 0.0)
        if now - last >= flags.get_flag("read_delay_alarm_duration"):
            self._reader_limit_alarms[path] = now
            msg = (f"log reader count at limit "
                   f"({flags.get_flag('max_file_reader_num')}); "
                   + ("dropped an old rotated reader" if freed
                      else f"skipping {path}"))
            log.warning("%s", msg)
            AlarmManager.instance().send_alarm(
                AlarmType.FILE_READER_EXCEED, msg,
                AlarmLevel.WARNING, st.name)
        return freed

    def _open_reader(self, st: _ConfigState, path: str) -> None:
        if not self._shed_for_capacity(st, path):
            return
        r = st.new_reader(path)
        if not r.open():
            return
        cp = self.checkpoints.get(r.dev_inode.dev, r.dev_inode.inode)
        if cp is not None:
            r.restore(cp)
        elif not st.tail_existing and not st.first_round:
            pass  # new file appears later: read from 0
        elif not st.tail_existing and st.first_round:
            # skip history on first sight (reference TailExisted=false):
            import os
            try:
                r.offset = os.fstat(r._fd).st_size
            except OSError:
                pass
        # from here this source's checkpoint dumps use the ACKED frontier,
        # not the read offset (loongcrash at-least-once contract)
        ack_watermark.register_source(r.dev_inode.dev, r.dev_inode.inode,
                                      r.offset)
        st.readers[path] = r

    def _drain_reader(self, st: _ConfigState, reader: LogFileReader,
                      force_flush: bool = False) -> bool:
        """Read until empty or back-pressure; returns True if data moved."""
        moved = False
        pqm = self.process_queue_manager
        for _ in range(64):  # bounded burst per round
            if pqm is not None and not pqm.is_valid_to_push(st.queue_key):
                # watermark high: requeue for the feedback wakeup
                self._register_feedback(st.queue_key)
                break
            try:
                group = reader.read(force_flush=force_flush)
            except OSError:
                break  # reader closed concurrently (config removal)
            if group is None or not reader.is_open:
                break
            if recovery.suppress_duplicate(group):
                # previous run already delivered this exact span (acked
                # after the last checkpoint dump): count it, advance the
                # books, and never let it re-enter the pipeline
                moved = True
                self.checkpoints.update(reader.checkpoint())
                continue
            if st.tag_provider is not None:
                try:
                    tags = st.tag_provider(reader.path)
                except Exception:  # noqa: BLE001
                    tags = None
                if tags:
                    for k, v in tags.items():
                        group.set_tag(k, v)
            if pqm is not None:
                if not pqm.push_queue(st.queue_key, group):
                    # queue rejected after read: restore offset (SOURCE
                    # bytes) and the multiline stitch state together
                    reader.rollback_last()
                    self._register_feedback(st.queue_key)
                    break
            moved = True
            self.checkpoints.update(reader.checkpoint())
        return moved
