"""File read checkpoints (v1): JSON dump of per-file offsets.

Reference: core/file_server/checkpoint/CheckPointManager.{h,cpp} (h:99-140) —
dev/inode + signature + offset per file, dumped periodically
(application/Application.cpp:384) and restored on start.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional

from .reader import ReaderCheckpoint


class CheckPointManager:
    def __init__(self, path: str = ""):
        self.path = path
        self._checkpoints: Dict[str, ReaderCheckpoint] = {}
        self._lock = threading.Lock()
        self.last_dump = 0.0

    def update(self, cp: ReaderCheckpoint) -> None:
        with self._lock:
            self._checkpoints[cp.path] = cp

    def get(self, path: str) -> Optional[ReaderCheckpoint]:
        with self._lock:
            return self._checkpoints.get(path)

    def remove(self, path: str) -> None:
        with self._lock:
            self._checkpoints.pop(path, None)

    def dump(self) -> None:
        if not self.path:
            return
        with self._lock:
            data = {
                "version": 1,
                "check_point": {
                    p: {
                        "offset": cp.offset, "dev": cp.dev, "inode": cp.inode,
                        "sig": cp.signature, "sig_size": cp.signature_size,
                        "update_time": cp.update_time,
                    } for p, cp in self._checkpoints.items()
                },
            }
        tmp = self.path + ".tmp"
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(data, f)
        os.replace(tmp, self.path)
        self.last_dump = time.monotonic()

    def load(self) -> None:
        if not self.path or not os.path.exists(self.path):
            return
        try:
            with open(self.path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            return
        with self._lock:
            for p, d in data.get("check_point", {}).items():
                self._checkpoints[p] = ReaderCheckpoint(
                    path=p, offset=d.get("offset", 0), dev=d.get("dev", 0),
                    inode=d.get("inode", 0), signature=d.get("sig", ""),
                    signature_size=d.get("sig_size", 0),
                    update_time=d.get("update_time", 0.0))

    def dump_periodically(self, interval: float = 5.0) -> None:
        if time.monotonic() - self.last_dump >= interval:
            self.dump()
