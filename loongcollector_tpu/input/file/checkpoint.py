"""File read checkpoints: JSON dump of per-file offsets.

Reference: core/file_server/checkpoint/CheckPointManager.{h,cpp} (h:99-140) —
entries are keyed by DevInode (not path), carrying path + signature + offset,
dumped periodically (application/Application.cpp:384) and restored on start.
Keying by (dev, inode) is what makes rename+recreate rotation safe: the
rotated reader and the new reader at the same path own distinct entries.

v3 (loongcrash): `offset` is the *durable* offset — the acked-bytes
low-watermark from runner/ack_watermark.py for file-server-registered
sources, the read offset for everything else — and `read_offset` records
where reading actually stood (rotation/backlog introspection).  Restoring
seeks to `offset`, so a crash re-reads exactly the unacked window:
at-least-once, never loss.  v1/v2 files load unchanged (offset doubles as
read_offset).
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from typing import Dict, Optional, Tuple

from ...runner import ack_watermark
from .reader import ReaderCheckpoint


class CheckPointManager:
    def __init__(self, path: str = ""):
        self.path = path
        self._checkpoints: Dict[Tuple[int, int], ReaderCheckpoint] = {}
        self._lock = threading.Lock()
        self.last_dump = 0.0
        self.quarantined_loads = 0

    @staticmethod
    def _key(cp: ReaderCheckpoint) -> Tuple[int, int]:
        return (cp.dev, cp.inode)

    def update(self, cp: ReaderCheckpoint) -> None:
        with self._lock:
            self._checkpoints[self._key(cp)] = cp

    def get(self, dev: int, inode: int) -> Optional[ReaderCheckpoint]:
        with self._lock:
            return self._checkpoints.get((dev, inode))

    def get_by_path(self, path: str) -> Optional[ReaderCheckpoint]:
        """Path lookup for callers that only know the path (e.g. status
        introspection). Reads prefer dev/inode: with rotation several
        entries may share a path; returns the most recently updated."""
        with self._lock:
            best = None
            for cp in self._checkpoints.values():
                if cp.path == path and (
                        best is None or cp.update_time > best.update_time):
                    best = cp
            return best

    def remove(self, dev: int, inode: int) -> None:
        with self._lock:
            self._checkpoints.pop((dev, inode), None)

    def dump(self) -> None:
        if not self.path:
            return
        with self._lock:
            entries = {}
            for (dev, ino), cp in self._checkpoints.items():
                # the persisted offset is the acked-bytes low-watermark for
                # sources the file server registered; bare readers fall back
                # to the read offset (seed semantics) inside durable_offset
                durable = ack_watermark.durable_offset(dev, ino, cp.offset)
                entries[f"{dev}:{ino}"] = {
                    "path": cp.path, "offset": durable,
                    "read_offset": cp.offset,
                    "dev": cp.dev, "inode": cp.inode,
                    "sig": cp.signature, "sig_size": cp.signature_size,
                    "update_time": cp.update_time,
                }
            data = {"version": 3, "check_point": entries}
        dirname = os.path.dirname(self.path) or "."
        os.makedirs(dirname, exist_ok=True)
        # unique tmp per dumper (concurrent dumps can't truncate each
        # other's file mid-write) + fsync before the atomic swap: a crash
        # right after dump() must find either the old or the new file,
        # never a torn one — this file is what recovery resumes from
        fd, tmp = tempfile.mkstemp(prefix=".checkpoint-", suffix=".tmp",
                                   dir=dirname)
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(data, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self.last_dump = time.monotonic()

    def load(self) -> None:
        if not self.path or not os.path.exists(self.path):
            return
        try:
            with open(self.path) as f:
                data = json.load(f)
            if not isinstance(data, dict):
                raise ValueError("checkpoint root is not an object")
        except (OSError, ValueError) as e:
            self._quarantine(e)
            return
        version = data.get("version", 1)
        with self._lock:
            for key, d in data.get("check_point", {}).items():
                # v1 files keyed entries by path; the entry body always
                # carried dev/inode, so both versions key the same way here
                path = d.get("path", key if version == 1 else "")
                cp = ReaderCheckpoint(
                    path=path, offset=d.get("offset", 0), dev=d.get("dev", 0),
                    inode=d.get("inode", 0), signature=d.get("sig", ""),
                    signature_size=d.get("sig_size", 0),
                    update_time=d.get("update_time", 0.0))
                self._checkpoints[self._key(cp)] = cp

    def _quarantine(self, err: Exception) -> None:
        """Corrupt/torn checkpoint: preserve the evidence as `.bad` (the
        next dump recreates the real file), alarm, and count — a silent
        restart-from-zero with no trace is how loss hides."""
        from ...monitor.alarms import AlarmLevel, AlarmManager, AlarmType
        bad = self.path + ".bad"
        try:
            os.replace(self.path, bad)
        except OSError:
            bad = "<unlinkable>"
        self.quarantined_loads += 1
        # what is discarded here is a metadata file, not events — the
        # events re-read from offset 0 and re-enter the ledger normally
        AlarmManager.instance().send_alarm(  # loonglint: disable=unledgered-drop
            AlarmType.CHECKPOINT_FAIL,
            f"corrupt checkpoint file quarantined to {bad}: {err}",
            AlarmLevel.ERROR)

    def dump_periodically(self, interval: float = 5.0) -> None:
        if time.monotonic() - self.last_dump >= interval:
            self.dump()
