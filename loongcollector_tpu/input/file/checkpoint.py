"""File read checkpoints (v1): JSON dump of per-file offsets.

Reference: core/file_server/checkpoint/CheckPointManager.{h,cpp} (h:99-140) —
entries are keyed by DevInode (not path), carrying path + signature + offset,
dumped periodically (application/Application.cpp:384) and restored on start.
Keying by (dev, inode) is what makes rename+recreate rotation safe: the
rotated reader and the new reader at the same path own distinct entries.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional, Tuple

from .reader import ReaderCheckpoint


class CheckPointManager:
    def __init__(self, path: str = ""):
        self.path = path
        self._checkpoints: Dict[Tuple[int, int], ReaderCheckpoint] = {}
        self._lock = threading.Lock()
        self.last_dump = 0.0

    @staticmethod
    def _key(cp: ReaderCheckpoint) -> Tuple[int, int]:
        return (cp.dev, cp.inode)

    def update(self, cp: ReaderCheckpoint) -> None:
        with self._lock:
            self._checkpoints[self._key(cp)] = cp

    def get(self, dev: int, inode: int) -> Optional[ReaderCheckpoint]:
        with self._lock:
            return self._checkpoints.get((dev, inode))

    def get_by_path(self, path: str) -> Optional[ReaderCheckpoint]:
        """Path lookup for callers that only know the path (e.g. status
        introspection). Reads prefer dev/inode: with rotation several
        entries may share a path; returns the most recently updated."""
        with self._lock:
            best = None
            for cp in self._checkpoints.values():
                if cp.path == path and (
                        best is None or cp.update_time > best.update_time):
                    best = cp
            return best

    def remove(self, dev: int, inode: int) -> None:
        with self._lock:
            self._checkpoints.pop((dev, inode), None)

    def dump(self) -> None:
        if not self.path:
            return
        with self._lock:
            data = {
                "version": 2,
                "check_point": {
                    f"{dev}:{ino}": {
                        "path": cp.path, "offset": cp.offset,
                        "dev": cp.dev, "inode": cp.inode,
                        "sig": cp.signature, "sig_size": cp.signature_size,
                        "update_time": cp.update_time,
                    } for (dev, ino), cp in self._checkpoints.items()
                },
            }
        tmp = self.path + ".tmp"
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(data, f)
        os.replace(tmp, self.path)
        self.last_dump = time.monotonic()

    def load(self) -> None:
        if not self.path or not os.path.exists(self.path):
            return
        try:
            with open(self.path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            return
        version = data.get("version", 1)
        with self._lock:
            for key, d in data.get("check_point", {}).items():
                # v1 files keyed entries by path; the entry body always
                # carried dev/inode, so both versions key the same way here
                path = d.get("path", key if version == 1 else "")
                cp = ReaderCheckpoint(
                    path=path, offset=d.get("offset", 0), dev=d.get("dev", 0),
                    inode=d.get("inode", 0), signature=d.get("sig", ""),
                    signature_size=d.get("sig_size", 0),
                    update_time=d.get("update_time", 0.0))
                self._checkpoints[self._key(cp)] = cp

    def dump_periodically(self, interval: float = 5.0) -> None:
        if time.monotonic() - self.last_dump >= interval:
            self.dump()
