"""File discovery: glob-pattern directory polling.

Reference: core/file_server/polling/PollingDirFile.cpp (directory/file
discovery round) + PollingModify.cpp (stat-based modify detection).  The
reference also merges inotify (EventListener_Linux.h); polling alone is
sufficient and portable — the FileServer loop stats registered files each
round (the reference's modify-poll interval defaults to comparable rates).
"""

from __future__ import annotations

import glob
import os
from dataclasses import dataclass
from typing import Dict, Iterable, List, Set, Tuple


@dataclass
class FileDiscoveryConfig:
    """Reference FileDiscoveryOptions: FilePaths (glob), MaxDirSearchDepth,
    ExcludeFilePaths/ExcludeFiles/ExcludeDirs."""

    file_paths: List[str]
    exclude_file_paths: List[str] = None
    exclude_files: List[str] = None
    exclude_dirs: List[str] = None

    def __post_init__(self):
        self.exclude_file_paths = self.exclude_file_paths or []
        self.exclude_files = self.exclude_files or []
        self.exclude_dirs = self.exclude_dirs or []


class PollingDirFile:
    def __init__(self, config: FileDiscoveryConfig):
        self.config = config

    def poll(self) -> List[str]:
        """One discovery round: resolve glob patterns → matching file paths."""
        found: List[str] = []
        seen: Set[str] = set()
        for pattern in self.config.file_paths:
            for path in glob.glob(pattern, recursive="**" in pattern):
                if path in seen or not os.path.isfile(path):
                    continue
                if self._excluded(path):
                    continue
                seen.add(path)
                found.append(path)
        return found

    def _excluded(self, path: str) -> bool:
        import fnmatch
        base = os.path.basename(path)
        d = os.path.dirname(path)
        for pat in self.config.exclude_file_paths:
            if fnmatch.fnmatch(path, pat):
                return True
        for pat in self.config.exclude_files:
            if fnmatch.fnmatch(base, pat):
                return True
        for pat in self.config.exclude_dirs:
            if fnmatch.fnmatch(d, pat):
                return True
        return False


class PollingModify:
    """Stat-based change detection over a registered file set."""

    def __init__(self) -> None:
        self._stats: Dict[str, Tuple[int, float]] = {}

    def changed(self, paths: Iterable[str]) -> List[str]:
        out = []
        for path in paths:
            try:
                st = os.stat(path)
            except OSError:
                self._stats.pop(path, None)
                continue
            sig = (st.st_size, st.st_mtime)
            if self._stats.get(path) != sig:
                self._stats[path] = sig
                out.append(path)
        return out
