"""Checkpoint v2 + exactly-once range checkpoints.

Reference: core/file_server/checkpoint/CheckpointManagerV2.h:26-173 (leveldb
store) and RangeCheckpoint.h (PB-persisted per-send-concurrency ranges),
wired by ExactlyOnceQueueManager (collection_pipeline/queue/ExactlyOnce*).

Store: sqlite3 (stdlib, durable, transactional) replaces leveldb.  Semantics:
an exactly-once sender slot persists the (file, read-offset range) BEFORE
dispatch; on restart, uncommitted ranges replay and groups are marked
IsReplay so downstream can dedupe (PipelineEventGroup replay flag).
"""

from __future__ import annotations

import os
import sqlite3
import threading
import time
from dataclasses import dataclass
from typing import List, Optional


@dataclass
class RangeCheckpoint:
    key: str = ""              # pipeline + concurrency slot
    inode: int = 0
    dev: int = 0
    file_path: str = ""
    read_offset: int = 0
    read_length: int = 0
    committed: bool = False
    sequence_id: int = 0
    update_time: float = 0.0


class CheckpointManagerV2:
    def __init__(self, db_path: str):
        self.db_path = db_path
        os.makedirs(os.path.dirname(db_path) or ".", exist_ok=True)
        self._lock = threading.Lock()
        self._conn = sqlite3.connect(db_path, check_same_thread=False)
        self._conn.execute("""
            CREATE TABLE IF NOT EXISTS range_checkpoints (
                key TEXT PRIMARY KEY,
                inode INTEGER, dev INTEGER, file_path TEXT,
                read_offset INTEGER, read_length INTEGER,
                committed INTEGER, sequence_id INTEGER, update_time REAL
            )""")
        self._conn.commit()

    def save(self, cp: RangeCheckpoint) -> None:
        cp.update_time = time.time()
        with self._lock:
            self._conn.execute(
                """INSERT OR REPLACE INTO range_checkpoints
                   VALUES (?,?,?,?,?,?,?,?,?)""",
                (cp.key, cp.inode, cp.dev, cp.file_path, cp.read_offset,
                 cp.read_length, int(cp.committed), cp.sequence_id,
                 cp.update_time))
            self._conn.commit()

    def commit(self, key: str, sequence_id: int) -> None:
        with self._lock:
            self._conn.execute(
                "UPDATE range_checkpoints SET committed=1, update_time=? "
                "WHERE key=? AND sequence_id=?",
                (time.time(), key, sequence_id))
            self._conn.commit()

    def get(self, key: str) -> Optional[RangeCheckpoint]:
        with self._lock:
            row = self._conn.execute(
                "SELECT * FROM range_checkpoints WHERE key=?", (key,)
            ).fetchone()
        return self._row_to_cp(row) if row else None

    def uncommitted(self, prefix: str = "") -> List[RangeCheckpoint]:
        """Ranges persisted but not acknowledged — replayed on restart."""
        with self._lock:
            rows = self._conn.execute(
                "SELECT * FROM range_checkpoints WHERE committed=0 "
                "AND key LIKE ?", (prefix + "%",)).fetchall()
        return [self._row_to_cp(r) for r in rows]

    def delete(self, key: str) -> None:
        with self._lock:
            self._conn.execute("DELETE FROM range_checkpoints WHERE key=?",
                               (key,))
            self._conn.commit()

    def delete_if_sequence(self, key: str, sequence_id: int) -> None:
        """Delete only if the row still belongs to the given attempt — a
        fresh in-flight range that reused the key is left untouched."""
        with self._lock:
            self._conn.execute(
                "DELETE FROM range_checkpoints WHERE key=? AND sequence_id=?",
                (key, sequence_id))
            self._conn.commit()

    def gc(self, max_age_s: float = 86400.0) -> int:
        cutoff = time.time() - max_age_s
        with self._lock:
            cur = self._conn.execute(
                "DELETE FROM range_checkpoints WHERE committed=1 "
                "AND update_time < ?", (cutoff,))
            self._conn.commit()
            return cur.rowcount

    def close(self) -> None:
        with self._lock:
            self._conn.close()

    @staticmethod
    def _row_to_cp(row) -> RangeCheckpoint:
        return RangeCheckpoint(key=row[0], inode=row[1], dev=row[2],
                               file_path=row[3], read_offset=row[4],
                               read_length=row[5], committed=bool(row[6]),
                               sequence_id=row[7], update_time=row[8])


_default_manager: Optional[CheckpointManagerV2] = None
_default_lock = threading.Lock()


def get_default_manager(db_path: Optional[str] = None
                        ) -> Optional[CheckpointManagerV2]:
    """Process-wide checkpoint-v2 store; first caller with a path creates it
    (the Application does this at init)."""
    global _default_manager
    with _default_lock:
        if _default_manager is None and db_path:
            _default_manager = CheckpointManagerV2(db_path)
        return _default_manager


class ExactlyOnceSender:
    """Per-pipeline exactly-once send slots.

    Reference semantics (ExactlyOnceQueueManager): N concurrency slots, each
    carrying one in-flight range; a slot persists its range before dispatch
    and commits after sink ack.  `pending_replays()` exposes crashed-in-
    flight ranges at startup.
    """

    def __init__(self, manager: CheckpointManagerV2, pipeline: str,
                 concurrency: int = 8):
        self.manager = manager
        self.pipeline = pipeline
        self.concurrency = concurrency
        self._seq = 0
        self._lock = threading.Lock()
        self._free = list(range(concurrency))

    def acquire_slot(self, file_path: str, dev: int, inode: int,
                     read_offset: int, read_length: int
                     ) -> Optional[RangeCheckpoint]:
        with self._lock:
            if not self._free:
                return None
            slot = self._free.pop()
            self._seq += 1
            seq = self._seq
        cp = RangeCheckpoint(
            key=f"{self.pipeline}/{slot}", file_path=file_path, dev=dev,
            inode=inode, read_offset=read_offset, read_length=read_length,
            sequence_id=seq)
        self.manager.save(cp)
        return cp

    def commit_slot(self, cp: RangeCheckpoint) -> None:
        self.manager.commit(cp.key, cp.sequence_id)
        slot = int(cp.key.rsplit("/", 1)[1])
        with self._lock:
            self._free.append(slot)

    def pending_replays(self) -> List[RangeCheckpoint]:
        return self.manager.uncommitted(self.pipeline + "/")
