"""input_journal — systemd journal tailing.

Reference: plugins/input/journal/ (go-systemd sdjournal). This runtime has
no libsystemd binding baked in, so the input drives `journalctl -o json -f`
as a line stream — same field model (MESSAGE, PRIORITY, _SYSTEMD_UNIT,
_HOSTNAME, __REALTIME_TIMESTAMP) — with the journal cursor checkpointed so
restarts resume where they left off. Gated: init fails soft when
journalctl is absent (containers without systemd).
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..models import PipelineEventGroup
from ..pipeline.plugin.interface import Input, PluginContext
from ..utils.logger import get_logger

log = get_logger("journal")

# journald fields promoted to event fields (reference journal input's
# default field mapping); everything else is dropped unless KeepAllFields
_FIELDS = {
    "MESSAGE": b"content",
    "PRIORITY": b"priority",
    "_SYSTEMD_UNIT": b"unit",
    "_HOSTNAME": b"hostname",
    "_PID": b"pid",
    "_COMM": b"command",
    "SYSLOG_IDENTIFIER": b"identifier",
}


def parse_journal_entry(line: bytes) -> Optional[Tuple[int, Dict[bytes, bytes],
                                                       str]]:
    """One `journalctl -o json` line → (ts_seconds, fields, cursor)."""
    try:
        obj = json.loads(line)
    except ValueError:
        return None
    fields: Dict[bytes, bytes] = {}
    for src, dst in _FIELDS.items():
        v = obj.get(src)
        if v is None:
            continue
        if isinstance(v, list):          # binary-ish fields arrive as arrays
            v = bytes(v).decode("utf-8", "replace")
        fields[dst] = str(v).encode()
    ts_us = obj.get("__REALTIME_TIMESTAMP")
    try:
        ts = int(ts_us) // 1_000_000
    except (TypeError, ValueError):
        ts = int(time.time())
    return ts, fields, str(obj.get("__CURSOR", ""))


class InputJournal(Input):
    name = "input_journal"

    def __init__(self) -> None:
        super().__init__()
        self._proc: Optional[subprocess.Popen] = None
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self._cursor = ""
        self._cursor_path = ""

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.units: List[str] = list(config.get("Units", []))
        self.max_batch = int(config.get("MaxBatch", 256))
        self.journalctl = config.get("JournalctlPath") or \
            shutil.which("journalctl")
        if not self.journalctl:
            log.error("input_journal: journalctl not found; disabled")
            return False
        data_dir = config.get("CursorDir") or os.path.expanduser(
            "~/.loongcollector_tpu")
        self._cursor_path = os.path.join(
            data_dir, f"journal_cursor_{context.pipeline_name}")
        try:
            with open(self._cursor_path) as f:
                self._cursor = f.read().strip()
        except OSError:
            self._cursor = ""
        return True

    def _cmd(self) -> List[str]:
        cmd = [self.journalctl, "-o", "json", "-f", "--no-pager"]
        if self._cursor:
            cmd += ["--after-cursor", self._cursor]
        else:
            cmd += ["-n", "0"]          # tail only: no history replay
        for u in self.units:
            cmd += ["-u", u]
        return cmd

    def start(self) -> bool:
        try:
            self._proc = subprocess.Popen(
                self._cmd(), stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL)
        except OSError as e:
            log.error("input_journal spawn failed: %s", e)
            return False
        self._running = True
        self._batch: List[Tuple[int, Dict[bytes, bytes]]] = []
        self._batch_lock = threading.Lock()
        self._thread = threading.Thread(target=self._run, name="journal",
                                        daemon=True)
        self._thread.start()
        # the reader thread blocks in the journalctl pipe; a quiet journal
        # would otherwise hold the last burst unflushed indefinitely, so a
        # timer drains the pending batch every second
        self._flush_thread = threading.Thread(
            target=self._flush_timer, name="journal-flush", daemon=True)
        self._flush_thread.start()
        return True

    def _run(self) -> None:
        assert self._proc is not None and self._proc.stdout is not None
        for line in self._proc.stdout:
            if not self._running:
                break
            parsed = parse_journal_entry(line)
            if parsed is None:
                continue
            ts, fields, cursor = parsed
            with self._batch_lock:
                if cursor:
                    self._cursor = cursor
                self._batch.append((ts, fields))
                full = len(self._batch) >= self.max_batch
            if full:
                self._flush_now()
        self._flush_now()

    def _flush_timer(self) -> None:
        while self._running:
            time.sleep(1.0)
            self._flush_now()

    def _flush_now(self) -> None:
        with self._batch_lock:
            batch, self._batch = self._batch, []
        if batch:
            self._flush(batch)

    def _flush(self, batch) -> None:
        pqm = self.context.process_queue_manager
        if pqm is None or not batch:
            return
        group = PipelineEventGroup()
        sb = group.source_buffer
        for ts, fields in batch:
            ev = group.add_log_event(ts)
            for k, v in fields.items():
                ev.set_content(k, sb.copy_string(v))
        group.set_tag(b"__source__", b"journal")
        pqm.push_queue(self.context.process_queue_key, group)
        self._save_cursor()

    def _save_cursor(self) -> None:
        if not self._cursor or not self._cursor_path:
            return
        try:
            os.makedirs(os.path.dirname(self._cursor_path), exist_ok=True)
            tmp = self._cursor_path + ".tmp"
            with open(tmp, "w") as f:
                f.write(self._cursor)
            os.replace(tmp, self._cursor_path)
        except OSError:
            pass

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        self._running = False
        if self._proc is not None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=3)
            except subprocess.TimeoutExpired:
                self._proc.kill()
            self._proc = None
        self._save_cursor()
        return True
