"""input_goprofile — pull pprof profiles from Go services.

Reference: plugins/input/goprofile/ — periodically scrapes targets'
/debug/pprof endpoints (profile/heap/goroutine...) and ships the decoded
profiles as events.

The pprof wire format (google/pprof profile.proto, gzip-compressed):

  Profile  { sample_type=1, sample=2, location=4, function=5,
             string_table=6, time_nanos=9, duration_nanos=10 }
  Sample   { location_id=1 (packed u64), value=2 (packed i64) }
  Location { id=1, line=4 }
  Line     { function_id=1, line=2 }
  Function { id=1, name=2 (string-table index) }

This decoder aggregates flat sample values per leaf function and emits the
top-N as LogEvents (function, value, unit, profile type) — the shape the
reference's profile pipeline ships — using the generic proto reader
(config/agent_v2_pb); no pprof dependency.
"""

from __future__ import annotations

import gzip
import http.client
import threading
import time
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import urlparse

from ..config.agent_v2_pb import dec_varint, iter_fields
from ..models import PipelineEventGroup
from ..pipeline.plugin.interface import Input, PluginContext
from ..utils.logger import get_logger

log = get_logger("goprofile")


def _packed_varints(data: bytes) -> List[int]:
    out = []
    pos = 0
    while pos < len(data):
        v, pos = dec_varint(data, pos)
        out.append(v)
    return out


def decode_pprof(data: bytes, top_n: int = 20) -> List[Tuple[str, int, str]]:
    """[(function_name, flat_value, unit)] for the top-N leaf functions of
    the LAST sample_type (pprof convention: cpu 'samples/count' first,
    'cpu/nanoseconds' last; heap 'inuse_space' last)."""
    if data[:2] == b"\x1f\x8b":
        data = gzip.decompress(data)
    strings: List[bytes] = []
    samples: List[bytes] = []
    locations: Dict[int, int] = {}      # location id -> function id
    functions: Dict[int, int] = {}      # function id -> name string idx
    sample_types: List[bytes] = []
    for f, wt, v in iter_fields(data):
        if wt != 2:
            continue
        v = bytes(v)
        if f == 1:                       # ValueType{type=1, unit=2}
            unit_idx = 0
            for f2, wt2, v2 in iter_fields(v):
                if f2 == 2 and wt2 == 0:
                    unit_idx = v2
            sample_types.append(unit_idx)
        elif f == 2:
            samples.append(v)
        elif f == 4:                     # Location
            loc_id = 0
            func_id = 0
            for f2, wt2, v2 in iter_fields(v):
                if f2 == 1 and wt2 == 0:
                    loc_id = v2
                elif f2 == 4 and wt2 == 2:   # first Line wins (leaf)
                    if func_id == 0:
                        for f3, wt3, v3 in iter_fields(bytes(v2)):
                            if f3 == 1 and wt3 == 0:
                                func_id = v3
            locations[loc_id] = func_id
        elif f == 5:                     # Function
            fid = 0
            name_idx = 0
            for f2, wt2, v2 in iter_fields(v):
                if f2 == 1 and wt2 == 0:
                    fid = v2
                elif f2 == 2 and wt2 == 0:
                    name_idx = v2
            functions[fid] = name_idx
        elif f == 6:
            strings.append(v)
    value_idx = max(0, len(sample_types) - 1)
    unit = b"count"
    if sample_types:
        uidx = sample_types[value_idx]
        if 0 <= uidx < len(strings):
            unit = strings[uidx]
    flat: Dict[int, int] = {}
    for raw in samples:
        loc_ids: List[int] = []
        values: List[int] = []
        for f2, wt2, v2 in iter_fields(raw):
            if f2 == 1:
                if wt2 == 2:
                    loc_ids.extend(_packed_varints(bytes(v2)))
                elif wt2 == 0:
                    loc_ids.append(v2)
            elif f2 == 2:
                if wt2 == 2:
                    values.extend(_packed_varints(bytes(v2)))
                elif wt2 == 0:
                    values.append(v2)
        if not loc_ids or value_idx >= len(values):
            continue
        leaf_func = locations.get(loc_ids[0], 0)
        flat[leaf_func] = flat.get(leaf_func, 0) + values[value_idx]
    scored = sorted(flat.items(), key=lambda kv: -kv[1])[:top_n]
    out = []
    for fid, value in scored:
        name_idx = functions.get(fid, 0)
        name = (strings[name_idx] if 0 <= name_idx < len(strings)
                else b"<unknown>")
        out.append((name.decode("utf-8", "replace"), value,
                    unit.decode("utf-8", "replace")))
    return out


class InputGoProfile(Input):
    name = "input_goprofile"

    PROFILE_PATHS = {
        "cpu": "/debug/pprof/profile?seconds={dur}",
        "heap": "/debug/pprof/heap",
        "goroutine": "/debug/pprof/goroutine",
        "allocs": "/debug/pprof/allocs",
        "block": "/debug/pprof/block",
        "mutex": "/debug/pprof/mutex",
    }

    def __init__(self) -> None:
        super().__init__()
        self.targets: List[str] = []
        self.profiles = ["cpu"]
        self.interval_s = 60.0
        self.cpu_seconds = 10
        self.top_n = 20
        self._thread: Optional[threading.Thread] = None
        self._running = False

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.targets = list(config.get("Targets", []))
        self.profiles = [p for p in config.get("Profiles", ["cpu"])
                         if p in self.PROFILE_PATHS]
        self.interval_s = float(config.get("IntervalSecs", 60))
        self.cpu_seconds = int(config.get("CpuSeconds", 10))
        self.top_n = int(config.get("TopN", 20))
        return bool(self.targets) and bool(self.profiles)

    def start(self) -> bool:
        self._running = True
        self._thread = threading.Thread(target=self._run,
                                        name="goprofile", daemon=True)
        self._thread.start()
        return True

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=3)
            self._thread = None
        return True

    def _run(self) -> None:
        while self._running:
            for target in self.targets:
                for prof in self.profiles:
                    if not self._running:
                        return
                    try:
                        self.scrape_once(target, prof)
                    except Exception as e:  # noqa: BLE001
                        log.warning("pprof scrape %s/%s failed: %s",
                                    target, prof, e)
            deadline = time.monotonic() + self.interval_s
            while self._running and time.monotonic() < deadline:
                time.sleep(0.2)

    def scrape_once(self, target: str, prof: str) -> int:
        u = urlparse(target if "//" in target else f"http://{target}")
        path = self.PROFILE_PATHS[prof].format(dur=self.cpu_seconds)
        timeout = (self.cpu_seconds + 10 if prof == "cpu" else 10)
        conn = http.client.HTTPConnection(u.netloc, timeout=timeout)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            body = resp.read()
            if resp.status != 200:
                raise RuntimeError(f"HTTP {resp.status}")
        finally:
            conn.close()
        rows = decode_pprof(body, self.top_n)
        if not rows:
            return 0
        group = PipelineEventGroup()
        sb = group.source_buffer
        group.set_tag(b"__profile_target__", u.netloc.encode())
        group.set_tag(b"__profile_type__", prof.encode())
        now = int(time.time())
        for name, value, unit in rows:
            ev = group.add_log_event(now)
            ev.set_content(sb.copy_string(b"function"),
                           sb.copy_string(name.encode()))
            ev.set_content(sb.copy_string(b"value"),
                           sb.copy_string(str(value).encode()))
            ev.set_content(sb.copy_string(b"unit"),
                           sb.copy_string(unit.encode()))
            ev.set_content(sb.copy_string(b"profile"),
                           sb.copy_string(prof.encode()))
        pqm = self.context.process_queue_manager if self.context else None
        if pqm is not None:
            pqm.push_queue(self.context.process_queue_key, group)
        return len(rows)
