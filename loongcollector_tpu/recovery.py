"""loongcrash recovery manager: detect unclean shutdown, orchestrate the
restart, and suppress the ack-to-crash duplicate window.

A sentinel marker is written at startup and removed only by a clean exit:
finding it at the NEXT start proves the previous process died without its
drain (SIGKILL, OOM, power).  Recovery then

  1. loads the ack journal (runner/ack_watermark.py) into a per-source
     duplicate window — spans the previous run ACKED but whose checkpoint
     dump never caught up.  The file server consults `suppress_duplicate`
     on every fresh read: a re-read of an already-delivered span is
     counted (`replay_duplicate_events`) and dropped BEFORE ingest, so
     the at-least-once re-read window produces bounded duplicates at the
     sink and zero ledger noise;
  2. sweeps torn disk-buffer temp files (`*.tmp` strays a crash left
     mid-spill — the committed `.lcb` rename is atomic, the tmp is junk);
  3. counts the events waiting in committed spill files (they replay via
     the normal DiskBufferWriter path) as `recovered_events_total`;
  4. surfaces the previous run's flight dump path, so the post-mortem
     (what the process was doing when it died) is one click away.

`/debug/status` gets a `recovery` section; counters also export through
monitor/metrics (category "agent", component "recovery").
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from .runner import ack_watermark
from .utils.logger import get_logger

log = get_logger("recovery")

MARKER_NAME = "unclean.marker"
STATE_NAME = "recovery_state.json"
JOURNAL_NAME = "ack_journal.jsonl"

# duplicate-window bound: per-source acked spans kept for suppression; a
# window this size covers any realistic ack-to-dump gap (dump cadence is
# seconds) while bounding recovery memory on a huge stale journal
MAX_WINDOW_SPANS = 65536


class _Window:
    """Acked intervals of one (dev, inode) from the previous run's journal:
    merged [start, end) list for containment, plus exact-span crcs for the
    strong (byte-verified) match."""

    __slots__ = ("ivals", "crcs")

    def __init__(self) -> None:
        self.ivals: List[List[int]] = []
        self.crcs: Dict[Tuple[int, int], int] = {}

    def add(self, off: int, length: int, crc: int) -> None:
        if length <= 0:
            return
        self.crcs[(off, length)] = crc
        start, end = off, off + length
        iv = self.ivals
        lo = 0
        while lo < len(iv) and iv[lo][1] < start:
            lo += 1
        hi = lo
        while hi < len(iv) and iv[hi][0] <= end:
            start = min(start, iv[hi][0])
            end = max(end, iv[hi][1])
            hi += 1
        iv[lo:hi] = [[start, end]]

    def covers(self, off: int, length: int, crc: int) -> bool:
        exact = self.crcs.get((off, length))
        if exact is not None:
            # byte-verified when both sides carry a crc; a mismatch means
            # the file changed under the same offsets — deliver, don't drop
            return not (exact and crc and exact != crc)
        end = off + length
        for start, stop in self.ivals:
            if start <= off and end <= stop:
                return True
            if start > off:
                break
        return False


class RecoveryManager:
    def __init__(self, data_dir: str, buffer_dir: str = "") -> None:
        self.data_dir = data_dir
        self.buffer_dir = buffer_dir or os.path.join(data_dir, "buffer")
        self.marker_path = os.path.join(data_dir, MARKER_NAME)
        self.state_path = os.path.join(data_dir, STATE_NAME)
        self.journal_path = os.path.join(data_dir, JOURNAL_NAME)
        self.unclean = False
        self.unclean_shutdown_total = 0
        self.recovered_events_total = 0
        self.replay_duplicate_events = 0
        self.replay_duplicate_spans = 0
        self.torn_spills_removed = 0
        self.window_spans = 0
        self.flight_dump: Optional[str] = None
        self.recovery_wall_s = 0.0
        self._windows: Dict[Tuple[int, int], _Window] = {}
        self._lock = threading.Lock()
        self._metrics = None

    # -- lifecycle -----------------------------------------------------------

    def begin(self) -> None:
        t0 = time.monotonic()
        self.unclean = os.path.exists(self.marker_path)
        self._load_state()
        if self.unclean:
            self.unclean_shutdown_total += 1
            self._save_state()
            self.flight_dump = self._find_flight_dump()
            log.warning(
                "unclean shutdown detected (marker %s); total=%d%s",
                self.marker_path, self.unclean_shutdown_total,
                f"; previous flight dump: {self.flight_dump}"
                if self.flight_dump else "")
            from .monitor.alarms import (AlarmLevel, AlarmManager, AlarmType)
            AlarmManager.instance().send_alarm(
                AlarmType.AGENT_RESTART,
                "unclean shutdown: recovering from acked-offset checkpoints"
                + (f" (flight dump: {self.flight_dump})"
                   if self.flight_dump else ""),
                AlarmLevel.ERROR)
        self._load_window()
        self._sweep_torn_spills()
        self._count_buffered_events()
        # the tracker journals future acks into the SAME file the window
        # was loaded from: a second crash inside this run keeps both the
        # old window's tail and this run's acks
        ack_watermark.tracker().attach_journal(self.journal_path)
        self._write_marker()
        self.recovery_wall_s = time.monotonic() - t0
        self._export_metrics()

    def mark_clean_exit(self) -> None:
        """Clean drain finished: compact the journal down to the live
        window and drop the sentinel — the next start is a clean start."""
        ack_watermark.tracker().compact_journal()
        try:
            os.unlink(self.marker_path)
        except OSError:
            pass
        self.close()

    def close(self) -> None:
        with self._lock:
            m, self._metrics = self._metrics, None
        if m is not None:
            m.mark_deleted()

    # -- duplicate suppression -----------------------------------------------

    def suppress_duplicate(self, group) -> bool:
        """True ⇒ this freshly-read group's SOURCE span was fully acked by
        the previous run — count it and drop it before ingest.  Called by
        the file server on the read path; empty-window fast path is one
        dict check."""
        if not self._windows:
            return False
        span = ack_watermark.span_of(group)
        if span is None:
            return False
        dev, ino, off, length = span
        win = self._windows.get((dev, ino))
        if win is None:
            return False
        crc = 0
        raw = group.get_metadata(_CRC_KEY)
        if raw is not None:
            try:
                crc = int(str(raw))
            except ValueError:
                crc = 0
        if not win.covers(off, length, crc):
            return False
        with self._lock:
            self.replay_duplicate_events += len(group)
            self.replay_duplicate_spans += 1
        if self._metrics is not None:
            self._metrics.counter("replay_duplicate_events").add(len(group))
        # the span is already delivered: fold it into the watermark so the
        # checkpoint advances past it (and the journal re-records it for a
        # second crash inside this run)
        ack_watermark.ack_spans([span], force=True)
        return True

    # -- internals -----------------------------------------------------------

    def _write_marker(self) -> None:
        try:
            with open(self.marker_path, "w") as f:
                f.write(json.dumps({"pid": os.getpid(),
                                    "start_time": time.time()}))
                f.flush()
        except OSError:
            log.exception("cannot write crash marker %s", self.marker_path)

    def _load_state(self) -> None:
        try:
            with open(self.state_path) as f:
                st = json.load(f)
            self.unclean_shutdown_total = int(
                st.get("unclean_shutdown_total", 0))
        except (OSError, ValueError):
            self.unclean_shutdown_total = 0

    def _save_state(self) -> None:
        try:
            with open(self.state_path, "w") as f:
                json.dump({"unclean_shutdown_total":
                           self.unclean_shutdown_total}, f)
        except OSError:
            pass

    def _load_window(self) -> None:
        """Journal → per-source duplicate windows.  Loaded on every start
        (not only unclean ones): after a clean exit the compacted journal
        holds exactly the spans above the last checkpoint dump, and
        suppressing those re-reads is what keeps a clean restart
        duplicate-free even though the dump ran before the final drain."""
        try:
            with open(self.journal_path) as f:
                lines = f.readlines()
        except OSError:
            return
        n = 0
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
                dev, ino = int(rec["d"]), int(rec["i"])
                off, length = int(rec["o"]), int(rec["l"])
                crc = int(rec.get("c", 0))
            except (ValueError, KeyError, TypeError):
                continue    # torn tail line (crash mid-append): ignore
            win = self._windows.get((dev, ino))
            if win is None:
                win = self._windows[(dev, ino)] = _Window()
            win.add(off, length, crc)
            n += 1
            if n >= MAX_WINDOW_SPANS:
                log.warning("ack journal window capped at %d spans", n)
                break
        self.window_spans = n
        if n:
            log.info("duplicate-suppression window: %d spans over %d "
                     "sources", n, len(self._windows))

    def _sweep_torn_spills(self) -> None:
        if not os.path.isdir(self.buffer_dir):
            return
        for root, _dirs, files in os.walk(self.buffer_dir):
            for name in files:
                if name.endswith(".tmp"):
                    try:
                        os.unlink(os.path.join(root, name))
                        self.torn_spills_removed += 1
                    except OSError:
                        pass
        if self.torn_spills_removed:
            log.warning("removed %d torn spill temp files",
                        self.torn_spills_removed)

    def _count_buffered_events(self) -> None:
        """Events sitting in committed spill files at startup: they WILL
        replay through the normal disk-buffer path — this is the recovered
        inventory an operator sees as `recovered_events_total`."""
        if not os.path.isdir(self.buffer_dir):
            return
        total = 0
        for root, _dirs, files in os.walk(self.buffer_dir):
            for name in files:
                if not name.endswith(".lcb"):
                    continue
                try:
                    with open(os.path.join(root, name), "rb") as f:
                        header = json.loads(f.readline().decode())
                    total += int(header.get("event_cnt", 0))
                except (OSError, ValueError, TypeError):
                    continue    # corrupt file: replay() quarantines it
        self.recovered_events_total = total
        if total:
            log.info("recovery: %d events pending in the disk buffer", total)

    def _find_flight_dump(self) -> Optional[str]:
        """Most recent flight dump in the data dir (prof/flight.py writes
        flight.json / flight_*.json there on signal/crash/breach)."""
        best, best_m = None, -1.0
        try:
            for name in os.listdir(self.data_dir):
                if name.startswith("flight") and name.endswith(".json"):
                    p = os.path.join(self.data_dir, name)
                    m = os.path.getmtime(p)
                    if m > best_m:
                        best, best_m = p, m
        except OSError:
            return None
        return best

    def _export_metrics(self) -> None:
        try:
            from .monitor.metrics import MetricsRecord
            self._metrics = MetricsRecord(
                category="agent", labels={"component": "recovery"})
            self._metrics.gauge("unclean_shutdown_total").set(
                float(self.unclean_shutdown_total))
            self._metrics.gauge("recovered_events_total").set(
                float(self.recovered_events_total))
            self._metrics.gauge("recovery_window_spans").set(
                float(self.window_spans))
        except Exception:   # noqa: BLE001 - metrics must not block recovery
            self._metrics = None

    def status(self) -> dict:
        with self._lock:
            doc = {
                "unclean_shutdown": self.unclean,
                "unclean_shutdown_total": self.unclean_shutdown_total,
                "recovered_events_total": self.recovered_events_total,
                "replay_duplicate_events": self.replay_duplicate_events,
                "replay_duplicate_spans": self.replay_duplicate_spans,
                "torn_spills_removed": self.torn_spills_removed,
                "window_spans": self.window_spans,
                "recovery_wall_s": round(self.recovery_wall_s, 4),
            }
        if self.flight_dump:
            doc["previous_flight_dump"] = self.flight_dump
        doc["watermark"] = ack_watermark.tracker().status()
        return doc


from .models import EventGroupMetaKey as _MetaKey  # noqa: E402

_CRC_KEY = _MetaKey.LOG_FILE_CRC32

_manager: Optional[RecoveryManager] = None


def begin(data_dir: str, buffer_dir: str = "") -> RecoveryManager:
    """Install + run the recovery manager for this process (application
    init, before any reader opens)."""
    global _manager
    _manager = RecoveryManager(data_dir, buffer_dir)
    _manager.begin()
    return _manager


def active_manager() -> Optional[RecoveryManager]:
    return _manager


def mark_clean_exit() -> None:
    if _manager is not None:
        _manager.mark_clean_exit()


def suppress_duplicate(group) -> bool:
    m = _manager
    if m is None:
        return False
    return m.suppress_duplicate(group)


def status() -> Optional[dict]:
    m = _manager
    return m.status() if m is not None else None


def reset() -> None:
    """Tests: drop the installed manager (the tracker resets separately)."""
    global _manager
    if _manager is not None:
        _manager.close()
    _manager = None
