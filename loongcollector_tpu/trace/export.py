"""Trace → self-telemetry conversion.

Reference shape: core/monitor/SelfMonitorServer.cpp converts metric
records and alarms into PipelineEventGroups pushed into INTERNAL
pipelines; traces ride the same dogfooding path — every finished span and
timeline event becomes a log event tagged ``__source__ = loongtrace``, so
an operator's sink sees a breaker trip, the chaos injection that caused
it, and the resulting spill as rows of one queryable stream.
"""

from __future__ import annotations

import json
from typing import List, Optional

from ..models import PipelineEventGroup
from .tracer import Span, TraceEvent


def _put(ev, sb, key: str, value: str) -> None:
    ev.set_content(sb.copy_string(key), sb.copy_string(value))


def traces_to_group(spans: List[Span],
                    events: List[TraceEvent]) -> Optional[PipelineEventGroup]:
    """One event group carrying a drained trace batch; None when empty."""
    if not spans and not events:
        return None
    group = PipelineEventGroup()
    sb = group.source_buffer
    for span in spans:
        ev = group.add_log_event(int(span.start_wall))
        _put(ev, sb, "kind", "span")
        _put(ev, sb, "name", span.name)
        _put(ev, sb, "trace_id", span.trace_id)
        _put(ev, sb, "span_id", str(span.span_id))
        if span.parent_id is not None:
            _put(ev, sb, "parent_id", str(span.parent_id))
        _put(ev, sb, "status", span.status)
        if span.duration_s is not None:
            _put(ev, sb, "duration_ms",
                 f"{span.duration_s * 1000.0:.3f}")
        if span.attrs:
            _put(ev, sb, "attrs", json.dumps(span.attrs, sort_keys=True,
                                             default=str))
        if span.events:
            _put(ev, sb, "events", json.dumps(
                [{"name": n, "t_ms": round(dt * 1000.0, 3), **a}
                 for n, dt, a in span.events],
                sort_keys=True, default=str))
    for tev in events:
        ev = group.add_log_event(int(tev.wall))
        _put(ev, sb, "kind", "event")
        _put(ev, sb, "name", tev.name)
        _put(ev, sb, "seq", str(tev.seq))
        if tev.span_id is not None:
            _put(ev, sb, "span_id", str(tev.span_id))
        if tev.attrs:
            _put(ev, sb, "attrs", json.dumps(tev.attrs, sort_keys=True,
                                             default=str))
    group.set_tag(b"__source__", b"loongtrace")
    return group
