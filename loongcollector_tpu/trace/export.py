"""Trace → self-telemetry conversion + the unified timeline export.

Reference shape: core/monitor/SelfMonitorServer.cpp converts metric
records and alarms into PipelineEventGroups pushed into INTERNAL
pipelines; traces ride the same dogfooding path — every finished span and
timeline event becomes a log event tagged ``__source__ = loongtrace``, so
an operator's sink sees a breaker trip, the chaos injection that caused
it, and the resulting spill as rows of one queryable stream.

loongxprof adds :func:`chrome_trace`: the host spans (loongtrace) and the
per-dispatch device legs (ops/xprof DeviceTimeline) merged into one
Chrome-trace JSON object — loadable in Perfetto / chrome://tracing —
correlated per dispatch id and aligned on a single perf_counter clock
(Span._start_perf and DeviceTimeline.epoch read the same counter).
:func:`canonicalize` reduces that document to its timing-independent
structure so two runs of the same seeded storm compare byte-identical,
exactly like ``Tracer.structure_bytes``.
"""

from __future__ import annotations

import json
from typing import List, Optional

from ..models import PipelineEventGroup
from .tracer import _VOLATILE_ATTRS, Span, TraceEvent


def _put(ev, sb, key: str, value: str) -> None:
    ev.set_content(sb.copy_string(key), sb.copy_string(value))


def traces_to_group(spans: List[Span],
                    events: List[TraceEvent]) -> Optional[PipelineEventGroup]:
    """One event group carrying a drained trace batch; None when empty."""
    if not spans and not events:
        return None
    group = PipelineEventGroup()
    sb = group.source_buffer
    for span in spans:
        ev = group.add_log_event(int(span.start_wall))
        _put(ev, sb, "kind", "span")
        _put(ev, sb, "name", span.name)
        _put(ev, sb, "trace_id", span.trace_id)
        _put(ev, sb, "span_id", str(span.span_id))
        if span.parent_id is not None:
            _put(ev, sb, "parent_id", str(span.parent_id))
        _put(ev, sb, "status", span.status)
        if span.duration_s is not None:
            _put(ev, sb, "duration_ms",
                 f"{span.duration_s * 1000.0:.3f}")
        if span.attrs:
            _put(ev, sb, "attrs", json.dumps(span.attrs, sort_keys=True,
                                             default=str))
        if span.events:
            _put(ev, sb, "events", json.dumps(
                [{"name": n, "t_ms": round(dt * 1000.0, 3), **a}
                 for n, dt, a in span.events],
                sort_keys=True, default=str))
    for tev in events:
        ev = group.add_log_event(int(tev.wall))
        _put(ev, sb, "kind", "event")
        _put(ev, sb, "name", tev.name)
        _put(ev, sb, "seq", str(tev.seq))
        if tev.span_id is not None:
            _put(ev, sb, "span_id", str(tev.span_id))
        if tev.attrs:
            _put(ev, sb, "attrs", json.dumps(tev.attrs, sort_keys=True,
                                             default=str))
    group.set_tag(b"__source__", b"loongtrace")
    return group


# ---------------------------------------------------------------------------
# loongxprof: unified host/device Chrome-trace export
# ---------------------------------------------------------------------------

#: Chrome-trace process ids — one track group for the host spans, one for
#: the device dispatch legs
PID_HOST = 1
PID_DEVICE = 2

#: device legs get one tid each so Perfetto renders four stacked tracks
#: in pipeline order
_LEG_TIDS = {"h2d": 1, "submit": 2, "exec": 3, "d2h": 4}

#: args stripped by canonicalize(): run-dependent values (the tracer's
#: volatile attr set, plus the per-run dispatch id counter)
_CANON_VOLATILE = frozenset(_VOLATILE_ATTRS) | {"dispatch_id"}


def chrome_trace(tracer=None, timeline=None) -> dict:
    """The unified host/device execution timeline as a Chrome-trace JSON
    object (the ``traceEvents`` array format Perfetto loads directly).

    Host spans become complete ("ph":"X") events under pid ``PID_HOST``;
    device dispatch legs become complete events under pid ``PID_DEVICE``
    with one thread row per leg.  Both sides carry ``dispatch_id`` in
    their args where known, so a stalled ``device.roundtrip`` host span
    can be lined up with the exact H2D/submit/exec/D2H decomposition of
    the dispatch underneath it.  Defaults to the live planes
    (``trace.active_tracer()`` / ``xprof.active_timeline()``); either may
    be None — the export degrades to whichever side is recording."""
    if tracer is None:
        from . import active_tracer
        tracer = active_tracer()
    if timeline is None:
        from ..ops import xprof
        timeline = xprof.active_timeline()

    spans = tracer.finished_spans() if tracer is not None else []
    dispatches = timeline.dispatches() if timeline is not None else []

    # one shared perf_counter epoch: the device timeline's if it exists,
    # else the earliest host span (timestamps only need to be coherent
    # WITHIN the document)
    if timeline is not None:
        epoch = timeline.epoch
    elif spans:
        epoch = min(s._start_perf for s in spans)
    else:
        epoch = 0.0

    events: List[dict] = [
        {"ph": "M", "name": "process_name", "pid": PID_HOST,
         "args": {"name": "host (loongtrace spans)"}},
        {"ph": "M", "name": "process_name", "pid": PID_DEVICE,
         "args": {"name": "device (loongxprof dispatch legs)"}},
    ]
    for leg, tid in sorted(_LEG_TIDS.items(), key=lambda kv: kv[1]):
        events.append({"ph": "M", "name": "thread_name",
                       "pid": PID_DEVICE, "tid": tid,
                       "args": {"name": leg}})

    for span in spans:
        args = {k: v for k, v in span.attrs.items()}
        args["trace_id"] = span.trace_id
        args["status"] = span.status
        events.append({
            "ph": "X",
            "name": span.name,
            "cat": "host",
            "pid": PID_HOST,
            "tid": 1,
            "ts": round((span._start_perf - epoch) * 1e6, 3),
            "dur": round((span.duration_s or 0.0) * 1e6, 3),
            "args": args,
        })

    for rec in dispatches:
        for leg, t0, dur, attrs in rec.legs:
            args = {"dispatch_id": rec.id, "nbytes": rec.nbytes,
                    "program": rec.program or "unattributed",
                    "geometry": rec.geometry or "-"}
            args.update(attrs)
            events.append({
                "ph": "X",
                "name": leg,
                "cat": "device",
                "pid": PID_DEVICE,
                "tid": _LEG_TIDS.get(leg, 9),
                "ts": round(t0 * 1e6, 3),
                "dur": round(dur * 1e6, 3),
                "args": args,
            })

    return {"traceEvents": events, "displayTimeUnit": "ms"}


def canonicalize(doc: dict) -> bytes:
    """The Chrome-trace document reduced to its timing-independent
    structure, canonically serialized: timestamps/durations dropped,
    volatile args (dispatch ids, wall/thread) stripped, entries sorted.
    Two runs of the same seeded storm yield identical bytes — the
    re-run-the-seed acceptance artifact, timeline edition."""
    entries: List[tuple] = []
    for ev in doc.get("traceEvents", []):
        args = tuple(sorted(
            (k, str(v)) for k, v in (ev.get("args") or {}).items()
            if k not in _CANON_VOLATILE))
        if ev.get("ph") == "M":
            entries.append(("meta", ev.get("name"), ev.get("pid"),
                            ev.get("tid", 0), args))
        else:
            entries.append(("slice", ev.get("cat"), ev.get("pid"),
                            ev.get("tid", 0), ev.get("name"), args))
    entries.sort(key=lambda e: json.dumps(e, default=str))
    return json.dumps(entries, sort_keys=True, separators=(",", ":"),
                      default=str).encode("utf-8")
