"""loongtrace: the always-available, off-by-default pipeline span layer.

The paper's throughput headline (546 MB/s single-line, 68 MB/s regex
parse) says nothing about WHERE time goes once the parse hot path moves
onto the device plane; ParPaRaw-style parallel pipelines live or die on
per-stage latency balance.  This tracer makes the full event path — input
read → processor runner → device submit/resolve → batch/serialize →
flusher send — observable as spans, and makes the loongchaos plane's
injections, breaker transitions, spill/replay and retry decisions visible
as *span events* on one causal timeline.

Contract (mirrors chaos/plane.py, which established the idiom):

  * Disabled (the production default) every hook is ONE module-global
    read and an immediate return — `scripts/trace_overhead.py` gates the
    cost against a plain no-op call.
  * Enabled, sampling is deterministic per event-group key: the keep/drop
    draw depends only on ``(seed, key)`` (the seeded-stream idea from
    chaos/plan.py), so a traced soak replays the identical trace set.
  * The timeline's *structure* (names + attributes, never timestamps) is
    canonically serializable (`structure_bytes`), so two runs of the same
    seeded storm compare byte-identical.

Activation: programmatic ``enable()`` / scoped ``active()`` for tests, or
``LOONG_TRACE=1`` (with optional ``LOONG_TRACE_SAMPLE`` / ``LOONG_TRACE_SEED``)
via ``install_from_env()`` at application start.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import random
import threading
import time
from typing import Dict, List, Optional, Tuple

ENV_ENABLE = "LOONG_TRACE"
ENV_SAMPLE = "LOONG_TRACE_SAMPLE"
ENV_SEED = "LOONG_TRACE_SEED"

_SPAN_CAP = 50_000      # finished-span ring bound
_EVENT_CAP = 100_000    # timeline bound (matches chaos._SCHEDULE_CAP)
_MAX_EVENTS_PER_SPAN = 256


class Span:
    """One timed operation.  `end()` is idempotent; the tracer records the
    span at first end.  `add_event` attaches a named point event (kept in
    arrival order); events recorded after `end()` are dropped."""

    __slots__ = ("tracer", "name", "trace_id", "span_id", "parent_id",
                 "start_wall", "_start_perf", "duration_s", "attrs",
                 "events", "status", "_ended")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 span_id: int, parent_id: Optional[int],
                 attrs: Optional[dict] = None):
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_wall = time.time()
        self._start_perf = time.perf_counter()
        self.duration_s: Optional[float] = None
        self.attrs = dict(attrs) if attrs else {}
        self.events: List[Tuple[str, float, dict]] = []
        self.status = "ok"
        self._ended = False

    def set_attr(self, key: str, value) -> None:
        if not self._ended:
            self.attrs[key] = value

    def add_event(self, name: str, **attrs) -> None:
        if self._ended or len(self.events) >= _MAX_EVENTS_PER_SPAN:
            return
        self.events.append(
            (name, time.perf_counter() - self._start_perf, attrs))

    def end(self, status: Optional[str] = None) -> None:
        if self._ended:
            return
        self._ended = True
        if status is not None:
            self.status = status
        self.duration_s = time.perf_counter() - self._start_perf
        self.tracer._record(self)

    # context-manager sugar: ``with trace.span("x") as sp: ...``
    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.end("error" if exc_type is not None else None)


class TraceEvent:
    """A free-standing timeline entry (breaker transition, chaos
    injection, spill...) — recorded even when no span is current, so the
    causal storm timeline survives thread hops."""

    __slots__ = ("name", "seq", "wall", "attrs", "span_id")

    def __init__(self, name: str, seq: int, attrs: dict,
                 span_id: Optional[int]):
        self.name = name
        self.seq = seq
        self.wall = time.time()
        self.attrs = attrs
        self.span_id = span_id

    def structure_key(self) -> tuple:
        """Identity stripped of everything timing- and thread-dependent."""
        return (self.name,
                tuple(sorted((k, _stable(v)) for k, v in self.attrs.items())))


def _stable(v):
    """Canonicalize an attribute value for structure comparison: floats
    are rounded (chaos Decision.key idiom) so re-derived magnitudes
    compare equal; everything else must already be primitive."""
    if isinstance(v, float):
        return round(v, 9)
    return v


class TraceConfig:
    __slots__ = ("sample_rate", "seed")

    def __init__(self, sample_rate: float = 1.0, seed: int = 0):
        self.sample_rate = float(sample_rate)
        self.seed = int(seed)


class Tracer:
    """Process-wide span/timeline store.  All mutation is lock-cheap:
    one lock, short critical sections, bounded buffers."""

    def __init__(self, config: Optional[TraceConfig] = None):
        self.config = config or TraceConfig()
        self._lock = threading.Lock()
        self._spans: List[Span] = []          # finished spans, arrival order
        self._timeline: List[TraceEvent] = []
        self._event_seq = itertools.count()
        self._span_ids = itertools.count(1)
        self._dropped_spans = 0
        self._sample_cache: Dict[str, bool] = {}
        self._group_seq: Dict[str, int] = {}
        self._tls = threading.local()

    # -- sampling (deterministic per key) -----------------------------------

    def should_sample(self, key: str) -> bool:
        """Keep/drop draw for one event-group key.  Depends only on
        (seed, key) — the chaos/plan.py seeded-stream idea — so replaying
        the same workload traces the identical group set."""
        rate = self.config.sample_rate
        if rate >= 1.0:
            return True
        if rate <= 0.0:
            return False
        with self._lock:
            hit = self._sample_cache.get(key)
            if hit is None:
                hit = (random.Random(f"{self.config.seed}:{key}").random()
                       < rate)
                if len(self._sample_cache) < _EVENT_CAP:
                    self._sample_cache[key] = hit
        return hit

    def next_group_key(self, stream: str) -> str:
        """Stable per-stream sequence key: the Nth group of stream S gets
        key "S:N" in every run that feeds S the same groups in order."""
        with self._lock:
            n = self._group_seq.get(stream, 0)
            self._group_seq[stream] = n + 1
        return f"{stream}:{n}"

    # -- spans --------------------------------------------------------------

    def start_span(self, name: str, trace_id: str = "",
                   parent: Optional[Span] = None,
                   attrs: Optional[dict] = None) -> Span:
        if parent is None:
            parent = self.current_span()
        if parent is not None and not trace_id:
            trace_id = parent.trace_id
        return Span(self, name, trace_id, next(self._span_ids),
                    parent.span_id if parent is not None else None, attrs)

    def child_or_sampled(self, stream: str, name: str,
                         attrs: Optional[dict] = None) -> Optional[Span]:
        """Span-creation policy for instrumented stages: under a live
        (already-sampled) root span the stage always records as its
        child; a rootless stage draws its own deterministic keep/drop
        from the per-stream key sequence — so total span volume scales
        with the sample rate at EVERY instrumentation point, not just
        the pipeline root."""
        parent = self.current_span()
        if parent is not None:
            return self.start_span(name, parent=parent, attrs=attrs)
        if self.config.sample_rate >= 1.0:       # fast path: no key draw
            return self.start_span(name, attrs=attrs)
        key = self.next_group_key(stream)
        if not self.should_sample(key):
            return None
        return self.start_span(name, trace_id=key, attrs=attrs)

    def _record(self, span: Span) -> None:
        with self._lock:
            if len(self._spans) < _SPAN_CAP:
                self._spans.append(span)
            else:
                self._dropped_spans += 1
        stack = getattr(self._tls, "stack", None)
        if stack and span in stack:
            stack.remove(span)

    # current-span stack (per thread) — push/pop is explicit so the
    # overlapped dispatch loop can detach group N's span while N+1 packs
    def push_current(self, span: Span) -> None:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        stack.append(span)

    def pop_current(self, span: Optional[Span] = None) -> None:
        stack = getattr(self._tls, "stack", None)
        if not stack:
            return
        if span is None:
            stack.pop()
        elif span in stack:
            stack.remove(span)

    def current_span(self) -> Optional[Span]:
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else None

    # -- timeline -----------------------------------------------------------

    def event(self, name: str, **attrs) -> None:
        cur = self.current_span()
        if cur is not None:
            cur.add_event(name, **attrs)
        ev = TraceEvent(name, next(self._event_seq), attrs,
                        cur.span_id if cur is not None else None)
        with self._lock:
            if len(self._timeline) < _EVENT_CAP:
                self._timeline.append(ev)

    # -- retrieval ----------------------------------------------------------

    def finished_spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def timeline(self) -> List[TraceEvent]:
        with self._lock:
            return list(self._timeline)

    def timeline_by_name(self) -> Dict[str, List[TraceEvent]]:
        out: Dict[str, List[TraceEvent]] = {}
        for ev in self.timeline():
            out.setdefault(ev.name, []).append(ev)
        return out

    def drain(self) -> Tuple[List[Span], List[TraceEvent]]:
        """Remove-and-return everything recorded so far (self-monitor
        export cadence): each span/event ships exactly once."""
        with self._lock:
            spans, self._spans = self._spans, []
            events, self._timeline = self._timeline, []
        return spans, events

    def structure(self) -> List[tuple]:
        """The timeline + span set reduced to its timing-independent
        structure, canonically ordered: per-name event subsequences keep
        arrival order (deterministic under one thread, and per-point
        deterministic like the chaos schedule under many), names sort
        lexically, spans reduce to (name, status, sorted attr keys,
        event names)."""
        events = self.timeline_by_name()
        out: List[tuple] = []
        for name in sorted(events):
            for ev in events[name]:
                out.append(("event",) + ev.structure_key())
        spans = sorted(
            ((s.name, s.status,
              tuple(sorted((k, _stable(v)) for k, v in s.attrs.items()
                           if k not in _VOLATILE_ATTRS)),
              tuple(e[0] for e in s.events))
             for s in self.finished_spans()))
        out.extend(("span",) + s for s in spans)
        return out

    def structure_bytes(self) -> bytes:
        """Byte-comparable canonical serialization of `structure()` — the
        re-run-the-seed acceptance artifact."""
        return json.dumps(self.structure(), sort_keys=True,
                          separators=(",", ":"),
                          default=str).encode("utf-8")

    def stats(self) -> dict:
        with self._lock:
            return {"spans": len(self._spans),
                    "events": len(self._timeline),
                    "dropped_spans": self._dropped_spans}


#: span attributes whose values are run-dependent (sizes are stable, ids
#: and timings are not) — excluded from structure comparison.
#: dispatch_id is loongxprof's per-run correlation counter: interleaving
#: under concurrency may renumber dispatches between identical runs
_VOLATILE_ATTRS = frozenset({"duration_ms", "wall", "thread",
                             "dispatch_id"})


# ---------------------------------------------------------------------------
# module-level plane (the chaos/plane.py shape): one global, one branch


_tracer: Optional[Tracer] = None


def is_active() -> bool:
    return _tracer is not None


def active_tracer() -> Optional[Tracer]:
    """THE disabled-path hook: call sites read this once; None means
    tracing is off and nothing else may run."""
    return _tracer


def enable(config: Optional[TraceConfig] = None) -> Tracer:
    global _tracer
    t = Tracer(config)
    _tracer = t
    return t


def disable() -> None:
    global _tracer
    _tracer = None


@contextlib.contextmanager
def active(config: Optional[TraceConfig] = None):
    """Scoped activation for tests: ``with trace.active() as t: ...``."""
    t = enable(config)
    try:
        yield t
    finally:
        disable()


def install_from_env(env=os.environ) -> bool:
    """LOONG_TRACE=1 activates tracing at application start;
    LOONG_TRACE_SAMPLE (float, default 1.0) and LOONG_TRACE_SEED (int,
    default 0) shape deterministic sampling."""
    raw = env.get(ENV_ENABLE)
    if not raw or raw.strip().lower() in ("0", "false", "no", "off"):
        return False
    try:
        rate = float(env.get(ENV_SAMPLE, "1.0"))
    except ValueError:
        rate = 1.0
    try:
        seed = int(env.get(ENV_SEED, "0"))
    except ValueError:
        seed = 0
    enable(TraceConfig(sample_rate=rate, seed=seed))
    return True


# -- hot-path hooks: each is one global read + branch when disabled ---------


def event(name: str, **attrs) -> None:
    """Record a timeline event (and attach to the current span, if any).
    Disabled: a single branch."""
    t = _tracer
    if t is None:
        return
    t.event(name, **attrs)


def start_span(name: str, trace_id: str = "",
               parent: Optional[Span] = None,
               attrs: Optional[dict] = None) -> Optional[Span]:
    t = _tracer
    if t is None:
        return None
    return t.start_span(name, trace_id, parent, attrs)


def span(name: str, **attrs):
    """``with trace.span("stage"): ...`` — returns a no-op context when
    disabled (the with-statement itself is the only residual cost, so
    hot paths should prefer an ``is_active()`` guard)."""
    t = _tracer
    if t is None:
        return contextlib.nullcontext()
    sp = t.start_span(name, attrs=attrs or None)
    return sp


def current_span() -> Optional[Span]:
    t = _tracer
    if t is None:
        return None
    return t.current_span()
