"""loongtrace: end-to-end pipeline tracing (docs/observability.md).

Off by default; `enable()` / `LOONG_TRACE=1` turns it on.  Every hook in
this package is a single module-global read + branch when disabled —
scripts/trace_overhead.py gates that contract.
"""

from .tracer import (ENV_ENABLE, ENV_SAMPLE, ENV_SEED, Span, TraceConfig,
                     TraceEvent, Tracer, active, active_tracer, current_span,
                     disable, enable, event, install_from_env, is_active,
                     span, start_span)

__all__ = [
    "ENV_ENABLE", "ENV_SAMPLE", "ENV_SEED", "Span", "TraceConfig",
    "TraceEvent", "Tracer", "active", "active_tracer", "current_span",
    "disable", "enable", "event", "install_from_env", "is_active", "span",
    "start_span",
]
