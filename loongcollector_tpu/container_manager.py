"""Container discovery & metadata.

Reference: core/container_manager/ (discovery diffing; pushes matched-
container info, triggers FileServer pause/resume on changes,
ContainerManager.cpp:325) and core/metadata/ (K8sMetadata pod/service cache).

Discovery sources:
  * Docker Engine API over /var/run/docker.sock (stdlib HTTP over AF_UNIX)
  * CRI log directory layout (/var/log/pods/<ns>_<pod>_<uid>/<container>/)
  * static container info files (the reference's mounted containerInfo)

The FileServer consumes discovery results as extra glob roots; label/env
filters follow the reference's ContainerFilters config shape.
"""

from __future__ import annotations

import fnmatch
import http.client
import json
import os
import socket
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .utils.logger import get_logger

log = get_logger("container_manager")

DOCKER_SOCK = "/var/run/docker.sock"
CRI_POD_LOG_DIR = "/var/log/pods"


@dataclass
class ContainerInfo:
    id: str = ""
    name: str = ""
    image: str = ""
    log_path: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    env: Dict[str, str] = field(default_factory=dict)
    k8s_namespace: str = ""
    k8s_pod: str = ""
    k8s_container: str = ""


class _UnixHTTPConnection(http.client.HTTPConnection):
    def __init__(self, sock_path: str, timeout: float = 5.0):
        super().__init__("localhost", timeout=timeout)
        self._sock_path = sock_path

    def connect(self) -> None:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(self.timeout)
        s.connect(self._sock_path)
        self.sock = s


class DockerDiscovery:
    """List running containers via the Docker Engine API."""

    def __init__(self, sock_path: str = DOCKER_SOCK):
        self.sock_path = sock_path

    def available(self) -> bool:
        return os.path.exists(self.sock_path)

    def list_containers(self) -> List[ContainerInfo]:
        if not self.available():
            return []
        try:
            conn = _UnixHTTPConnection(self.sock_path)
            conn.request("GET", "/containers/json")
            resp = conn.getresponse()
            body = resp.read()
            conn.close()
            if resp.status != 200:
                return []
            data = json.loads(body)
        except (OSError, ValueError, http.client.HTTPException):
            return []
        if not isinstance(data, list):
            return []
        out = []
        for c in data:
            cid = c.get("Id", "")
            info = ContainerInfo(
                id=cid,
                name=(c.get("Names") or [""])[0].lstrip("/"),
                image=c.get("Image", ""),
                labels=c.get("Labels") or {},
                log_path=f"/var/lib/docker/containers/{cid}/{cid}-json.log")
            labels = info.labels
            info.k8s_namespace = labels.get("io.kubernetes.pod.namespace", "")
            info.k8s_pod = labels.get("io.kubernetes.pod.name", "")
            info.k8s_container = labels.get("io.kubernetes.container.name", "")
            out.append(info)
        return out


class CRIDiscovery:
    """Discover container stdout logs from the kubelet pod-log layout."""

    def __init__(self, root: str = CRI_POD_LOG_DIR):
        self.root = root

    def available(self) -> bool:
        return os.path.isdir(self.root)

    def list_containers(self) -> List[ContainerInfo]:
        out = []
        if not self.available():
            return out
        try:
            pods = os.listdir(self.root)
        except OSError:
            return out
        for pod_dir in pods:
            parts = pod_dir.split("_")
            if len(parts) != 3:
                continue
            ns, pod, uid = parts
            pod_path = os.path.join(self.root, pod_dir)
            try:
                containers = os.listdir(pod_path)
            except OSError:
                continue
            for cname in containers:
                cdir = os.path.join(pod_path, cname)
                if not os.path.isdir(cdir):
                    continue
                out.append(ContainerInfo(
                    id=f"{uid}/{cname}", name=cname,
                    log_path=os.path.join(cdir, "*.log"),
                    k8s_namespace=ns, k8s_pod=pod, k8s_container=cname))
        return out


class ContainerFilters:
    """Reference ContainerFilters: include/exclude by label/env/k8s names."""

    def __init__(self, config: Optional[dict] = None):
        cfg = config or {}
        self.include_labels = cfg.get("IncludeContainerLabel", {})
        self.exclude_labels = cfg.get("ExcludeContainerLabel", {})
        self.k8s_namespace_regex = cfg.get("K8sNamespaceRegex", "")
        self.k8s_pod_regex = cfg.get("K8sPodRegex", "")

    def match(self, info: ContainerInfo) -> bool:
        import re
        for k, v in self.include_labels.items():
            if not fnmatch.fnmatch(info.labels.get(k, ""), v):
                return False
        for k, v in self.exclude_labels.items():
            if k in info.labels and fnmatch.fnmatch(info.labels[k], v):
                return False
        if self.k8s_namespace_regex and not re.fullmatch(
                self.k8s_namespace_regex, info.k8s_namespace):
            return False
        if self.k8s_pod_regex and not re.fullmatch(
                self.k8s_pod_regex, info.k8s_pod):
            return False
        return True


class ContainerManager:
    _instance: Optional["ContainerManager"] = None
    _instance_lock = threading.Lock()

    def __init__(self) -> None:
        self.docker = DockerDiscovery()
        self.cri = CRIDiscovery()
        self._last: Dict[str, ContainerInfo] = {}
        self._lock = threading.Lock()
        self.on_diff = None  # callback(added, removed) -> bool (delivered)
        self._thread: Optional[threading.Thread] = None
        self._running = False

    @classmethod
    def instance(cls) -> "ContainerManager":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def discover(self) -> List[ContainerInfo]:
        found = self.docker.list_containers() + self.cri.list_containers()
        return found

    def diff_round(self) -> tuple:
        """One discovery diff (reference: container diff each supervision
        round, Application.cpp:386-392).  The diff baseline only advances
        when delivery succeeds, so a full queue re-emits next round rather
        than losing the add/remove events."""
        found = {c.id: c for c in self.discover()}
        with self._lock:
            added = [c for cid, c in found.items() if cid not in self._last]
            removed = [c for cid, c in self._last.items() if cid not in found]
        delivered = True
        if (added or removed) and self.on_diff is not None:
            try:
                delivered = self.on_diff(added, removed) is not False
            except Exception:  # noqa: BLE001
                log.exception("container diff delivery failed")
                delivered = False
        if delivered:
            with self._lock:
                self._last = found
        return added, removed

    def set_on_diff(self, callback) -> bool:
        """Install the (single) diff consumer and run discovery on an owned
        thread — discovery does blocking socket/FS I/O and must not ride the
        application supervision loop.  Returns False if already claimed."""
        with self._lock:
            if callback is not None and self.on_diff is not None:
                return False
            self.on_diff = callback
            start = callback is not None and not self._running
            if callback is None:
                self._running = False
        if start:
            self._running = True
            self._thread = threading.Thread(target=self._run,
                                            name="container-diff", daemon=True)
            self._thread.start()
        return True

    def _run(self) -> None:
        while self._running:
            try:
                self.diff_round()
            except Exception:  # noqa: BLE001
                log.exception("container diff failed")
            for _ in range(100):
                if not self._running:
                    return
                time.sleep(0.1)


class K8sMetadata:
    """Pod metadata cache (reference core/metadata/K8sMetadata) — resolves
    from the kube-apiserver when in-cluster credentials exist."""

    def __init__(self) -> None:
        self.token_path = "/var/run/secrets/kubernetes.io/serviceaccount/token"
        self.ca_path = "/var/run/secrets/kubernetes.io/serviceaccount/ca.crt"
        self._cache: Dict[str, dict] = {}
        self._lock = threading.Lock()

    def available(self) -> bool:
        return os.path.exists(self.token_path) and \
            bool(os.environ.get("KUBERNETES_SERVICE_HOST"))

    def pod_metadata(self, namespace: str, pod: str) -> Optional[dict]:
        key = f"{namespace}/{pod}"
        with self._lock:
            if key in self._cache:
                return self._cache[key]
        if not self.available():
            return None
        import ssl
        if not os.path.exists(self.ca_path):
            log.warning("in-cluster CA bundle missing; refusing unverified "
                        "apiserver connection")
            return None
        try:
            with open(self.token_path) as f:
                token = f.read().strip()
            host = os.environ["KUBERNETES_SERVICE_HOST"]
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            ctx = ssl.create_default_context(cafile=self.ca_path)
            conn = http.client.HTTPSConnection(host, int(port), timeout=5,
                                               context=ctx)
            conn.request("GET", f"/api/v1/namespaces/{namespace}/pods/{pod}",
                         headers={"Authorization": f"Bearer {token}"})
            resp = conn.getresponse()
            data = json.loads(resp.read()) if resp.status == 200 else None
            conn.close()
        except (OSError, ValueError, KeyError):
            return None
        if data is not None:
            meta = {
                "labels": data.get("metadata", {}).get("labels", {}),
                "node": data.get("spec", {}).get("nodeName", ""),
                "ip": data.get("status", {}).get("podIP", ""),
            }
            with self._lock:
                if len(self._cache) > 4096:
                    self._cache.clear()
                self._cache[key] = meta
            return meta
        return None
