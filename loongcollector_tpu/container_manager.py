"""Container discovery & metadata.

Reference: core/container_manager/ (discovery diffing; pushes matched-
container info, triggers FileServer pause/resume on changes,
ContainerManager.cpp:325) and core/metadata/ (K8sMetadata pod/service cache).

Discovery sources:
  * Docker Engine API over /var/run/docker.sock (stdlib HTTP over AF_UNIX)
  * CRI log directory layout (/var/log/pods/<ns>_<pod>_<uid>/<container>/)
  * static container info files (the reference's mounted containerInfo)

The FileServer consumes discovery results as extra glob roots; label/env
filters follow the reference's ContainerFilters config shape.
"""

from __future__ import annotations

import fnmatch
import http.client
import json
import os
import socket
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .utils.logger import get_logger

log = get_logger("container_manager")

DOCKER_SOCK = "/var/run/docker.sock"
CRI_POD_LOG_DIR = "/var/log/pods"


@dataclass
class ContainerInfo:
    id: str = ""
    name: str = ""
    image: str = ""
    log_path: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    env: Dict[str, str] = field(default_factory=dict)
    k8s_namespace: str = ""
    k8s_pod: str = ""
    k8s_container: str = ""

    @property
    def stable_key(self) -> str:
        """Identity stable ACROSS discovery sources: the CRI socket and the
        pod-log-dir walk report different ids for the same container, so
        diffing by raw id would flap when one source has a bad round."""
        if self.k8s_pod:
            return f"{self.k8s_namespace}/{self.k8s_pod}/{self.k8s_container or self.name}"
        return f"id/{self.id}"


class _UnixHTTPConnection(http.client.HTTPConnection):
    def __init__(self, sock_path: str, timeout: float = 5.0):
        super().__init__("localhost", timeout=timeout)
        self._sock_path = sock_path

    def connect(self) -> None:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(self.timeout)
        s.connect(self._sock_path)
        self.sock = s


class DockerDiscovery:
    """List running containers via the Docker Engine API."""

    def __init__(self, sock_path: Optional[str] = None):
        # resolved at construction (env override for non-standard sockets
        # and test fixtures), not at class-definition time
        self.sock_path = sock_path or os.environ.get(
            "LOONG_DOCKER_SOCK", DOCKER_SOCK)

    def available(self) -> bool:
        return os.path.exists(self.sock_path)

    def list_containers(self) -> List[ContainerInfo]:
        if not self.available():
            return []
        try:
            conn = _UnixHTTPConnection(self.sock_path)
            conn.request("GET", "/containers/json")
            resp = conn.getresponse()
            body = resp.read()
            conn.close()
            if resp.status != 200:
                return []
            data = json.loads(body)
        except (OSError, ValueError, http.client.HTTPException):
            return []
        if not isinstance(data, list):
            return []
        out = []
        for c in data:
            cid = c.get("Id", "")
            info = ContainerInfo(
                id=cid,
                name=(c.get("Names") or [""])[0].lstrip("/"),
                image=c.get("Image", ""),
                labels=c.get("Labels") or {},
                log_path=f"/var/lib/docker/containers/{cid}/{cid}-json.log")
            labels = info.labels
            info.k8s_namespace = labels.get("io.kubernetes.pod.namespace", "")
            info.k8s_pod = labels.get("io.kubernetes.pod.name", "")
            info.k8s_container = labels.get("io.kubernetes.container.name", "")
            out.append(info)
        return out


class CRIDiscovery:
    """Discover container stdout logs from the kubelet pod-log layout."""

    def __init__(self, root: Optional[str] = None):
        self.root = root or os.environ.get(
            "LOONG_CRI_POD_LOG_DIR", CRI_POD_LOG_DIR)

    def available(self) -> bool:
        return os.path.isdir(self.root)

    def list_containers(self) -> List[ContainerInfo]:
        out = []
        if not self.available():
            return out
        try:
            pods = os.listdir(self.root)
        except OSError:
            return out
        for pod_dir in pods:
            parts = pod_dir.split("_")
            if len(parts) != 3:
                continue
            ns, pod, uid = parts
            pod_path = os.path.join(self.root, pod_dir)
            try:
                containers = os.listdir(pod_path)
            except OSError:
                continue
            for cname in containers:
                cdir = os.path.join(pod_path, cname)
                if not os.path.isdir(cdir):
                    continue
                out.append(ContainerInfo(
                    id=f"{uid}/{cname}", name=cname,
                    log_path=os.path.join(cdir, "*.log"),
                    k8s_namespace=ns, k8s_pod=pod, k8s_container=cname))
        return out


def pb_fields(buf: bytes) -> Dict[int, List]:
    """Generic protobuf decoder: field → [value] (bytes for LEN, int for
    VARINT/fixed). Enough to read CRI responses without generated stubs."""
    out: Dict[int, List] = {}
    p, n = 0, len(buf)
    try:
        while p < n:
            v = s = 0
            while True:
                b = buf[p]; p += 1
                v |= (b & 0x7F) << s
                if not b & 0x80:
                    break
                s += 7
            field, wt = v >> 3, v & 7
            if wt == 0:
                v = s = 0
                while True:
                    b = buf[p]; p += 1
                    v |= (b & 0x7F) << s
                    if not b & 0x80:
                        break
                    s += 7
                out.setdefault(field, []).append(v)
            elif wt == 2:
                ln = s = 0
                while True:
                    b = buf[p]; p += 1
                    ln |= (b & 0x7F) << s
                    if not b & 0x80:
                        break
                    s += 7
                if p + ln > n:
                    break  # truncated LEN payload
                out.setdefault(field, []).append(buf[p:p + ln])
                p += ln
            elif wt == 5:
                out.setdefault(field, []).append(
                    int.from_bytes(buf[p:p + 4], "little"))
                p += 4
            elif wt == 1:
                out.setdefault(field, []).append(
                    int.from_bytes(buf[p:p + 8], "little"))
                p += 8
            else:
                break  # unsupported wire type: stop parsing defensively
    except IndexError:
        pass  # truncated varint: keep what parsed cleanly
    return out


def _pb_map(entries: List[bytes]) -> Dict[str, str]:
    out = {}
    for e in entries:
        f = pb_fields(e)
        k = f.get(1, [b""])[0]
        v = f.get(2, [b""])[0]
        out[k.decode("utf-8", "replace")] = (
            v.decode("utf-8", "replace") if isinstance(v, bytes) else str(v))
    return out


CRI_SOCKETS = ("/run/containerd/containerd.sock",
               "/var/run/containerd/containerd.sock",
               "/var/run/crio/crio.sock",
               "/run/k3s/containerd/containerd.sock")
_CONTAINER_RUNNING = 1


class CRISocketDiscovery:
    """CRI runtime API over the containerd/CRI-O socket (gRPC
    runtime.v1.RuntimeService/ListContainers), protobuf hand-decoded.

    Reference: core/container_manager/ talks to the CRI runtime for
    container metadata where Docker's engine API is absent (containerd-only
    nodes — the common K8s case since dockershim's removal).
    """

    def __init__(self, sockets=CRI_SOCKETS):
        self.sockets = [s for s in sockets]
        self.socket_override = None
        self.pod_log_dir = os.environ.get("LOONG_CRI_POD_LOG_DIR",
                                          CRI_POD_LOG_DIR)

    def _socket(self) -> Optional[str]:
        if self.socket_override:
            return self.socket_override
        for s in self.sockets:
            if os.path.exists(s):
                return s
        return None

    def available(self) -> bool:
        return self._socket() is not None

    def list_containers(self) -> List[ContainerInfo]:
        sock = self._socket()
        if sock is None:
            return []
        try:
            import grpc
        except ImportError:
            return []
        target = sock if "://" in sock else f"unix:{sock}"
        ch = None
        try:
            ch = grpc.insecure_channel(target)
            raw = None
            for service in ("runtime.v1.RuntimeService",
                            "runtime.v1alpha2.RuntimeService"):
                call = ch.unary_unary(
                    f"/{service}/ListContainers",
                    request_serializer=lambda x: x,
                    response_deserializer=lambda x: x)
                try:
                    raw = call(b"", timeout=3)
                    break
                except grpc.RpcError as e:
                    if e.code() == grpc.StatusCode.UNIMPLEMENTED:
                        continue
                    raise
        except Exception:  # noqa: BLE001 — discovery is best-effort
            return []
        finally:
            if ch is not None:
                ch.close()
        if raw is None:
            return []
        out = []
        for cbuf in pb_fields(raw).get(1, []):
            c = pb_fields(cbuf)
            state = c.get(6, [None])[0]
            if state is not None and state != _CONTAINER_RUNNING:
                continue
            labels = _pb_map(c.get(8, []))
            meta = pb_fields(c.get(3, [b""])[0])
            name = meta.get(1, [b""])[0]
            image_spec = pb_fields(c.get(4, [b""])[0])
            info = ContainerInfo(
                id=c.get(1, [b""])[0].decode("utf-8", "replace"),
                name=(name.decode("utf-8", "replace")
                      if isinstance(name, bytes) else ""),
                image=image_spec.get(1, [b""])[0].decode("utf-8", "replace"),
                labels=labels,
                k8s_namespace=labels.get("io.kubernetes.pod.namespace", ""),
                k8s_pod=labels.get("io.kubernetes.pod.name", ""),
                k8s_container=labels.get("io.kubernetes.container.name", ""))
            uid = labels.get("io.kubernetes.pod.uid", "")
            if info.k8s_pod and uid:
                info.log_path = os.path.join(
                    self.pod_log_dir,
                    f"{info.k8s_namespace}_{info.k8s_pod}_{uid}",
                    info.k8s_container or info.name, "*.log")
            out.append(info)
        return out


class ContainerFilters:
    """Reference ContainerFilters: include/exclude by label/env/k8s names."""

    def __init__(self, config: Optional[dict] = None):
        cfg = config or {}
        self.include_labels = cfg.get("IncludeContainerLabel", {})
        self.exclude_labels = cfg.get("ExcludeContainerLabel", {})
        self.k8s_namespace_regex = cfg.get("K8sNamespaceRegex", "")
        self.k8s_pod_regex = cfg.get("K8sPodRegex", "")

    def match(self, info: ContainerInfo) -> bool:
        import re
        for k, v in self.include_labels.items():
            if not fnmatch.fnmatch(info.labels.get(k, ""), v):
                return False
        for k, v in self.exclude_labels.items():
            if k in info.labels and fnmatch.fnmatch(info.labels[k], v):
                return False
        if self.k8s_namespace_regex and not re.fullmatch(
                self.k8s_namespace_regex, info.k8s_namespace):
            return False
        if self.k8s_pod_regex and not re.fullmatch(
                self.k8s_pod_regex, info.k8s_pod):
            return False
        return True


class ContainerManager:
    _instance: Optional["ContainerManager"] = None
    _instance_lock = threading.Lock()

    def __init__(self) -> None:
        self.docker = DockerDiscovery()
        self.cri = CRIDiscovery()
        self.cri_socket = CRISocketDiscovery()
        self.k8s = K8sMetadata()
        self._last: Dict[str, ContainerInfo] = {}
        self._lock = threading.Lock()
        self.on_diff = None  # callback(added, removed) -> bool (delivered)
        self._thread: Optional[threading.Thread] = None
        self._running = False

    @classmethod
    def instance(cls) -> "ContainerManager":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def discover(self) -> List[ContainerInfo]:
        """Merged view across sources; the CRI socket wins over the log-dir
        walk for the same pod/container (richer labels), docker engine for
        non-K8s containers."""
        seen: Dict[str, ContainerInfo] = {}
        for src in (self.cri_socket.list_containers(),
                    self.docker.list_containers(),
                    self.cri.list_containers()):
            for c in src:
                seen.setdefault(c.stable_key, c)
        found = list(seen.values())
        if self.k8s.available():
            for c in found:
                if c.k8s_pod:
                    meta = self.k8s.pod_metadata(c.k8s_namespace, c.k8s_pod)
                    if meta:
                        for k, v in meta.get("labels", {}).items():
                            c.labels.setdefault(f"pod.label.{k}", v)
        return found

    def diff_round(self) -> tuple:
        """One discovery diff (reference: container diff each supervision
        round, Application.cpp:386-392).  The diff baseline only advances
        when delivery succeeds, so a full queue re-emits next round rather
        than losing the add/remove events."""
        # keyed by stable_key: source-specific ids differ for the same
        # container, and a one-round source outage must not churn the diff
        found = {c.stable_key: c for c in self.discover()}
        with self._lock:
            added = [c for cid, c in found.items() if cid not in self._last]
            removed = [c for cid, c in self._last.items() if cid not in found]
        delivered = True
        if (added or removed) and self.on_diff is not None:
            try:
                delivered = self.on_diff(added, removed) is not False
            except Exception:  # noqa: BLE001
                log.exception("container diff delivery failed")
                delivered = False
        if delivered:
            with self._lock:
                self._last = found
        return added, removed

    def set_on_diff(self, callback) -> bool:
        """Install the (single) diff consumer and run discovery on an owned
        thread — discovery does blocking socket/FS I/O and must not ride the
        application supervision loop.  Returns False if already claimed."""
        with self._lock:
            if callback is not None and self.on_diff is not None:
                return False
            self.on_diff = callback
            start = callback is not None and not self._running
            if callback is None:
                self._running = False
        if start:
            self._running = True
            self._thread = threading.Thread(target=self._run,
                                            name="container-diff", daemon=True)
            self._thread.start()
        return True

    def _run(self) -> None:
        while self._running:
            try:
                self.diff_round()
            except Exception:  # noqa: BLE001
                log.exception("container diff failed")
            for _ in range(100):
                if not self._running:
                    return
                time.sleep(0.1)


K8S_META_TTL_S = 300.0
K8S_NEG_TTL_S = 30.0


class K8sMetadata:
    """Pod/service metadata cache (reference core/metadata/K8sMetadata.h:
    apiserver-backed cache with async refresh).

    * pod_metadata(): per-pod GET with a TTL'd cache;
    * start_watch(): one chunked WATCH stream over the node's pods keeps the
      cache warm — entries update on MODIFIED/DELETED without polling;
    * service_metadata(): namespace service list, TTL'd.

    Endpoint/credentials are injectable (`configure`) so tests run against
    a local fake apiserver over plain HTTP; production default is the
    in-cluster HTTPS endpoint with the mounted CA + token.
    """

    def __init__(self) -> None:
        self.token_path = "/var/run/secrets/kubernetes.io/serviceaccount/token"
        self.ca_path = "/var/run/secrets/kubernetes.io/serviceaccount/ca.crt"
        self._cache: Dict[str, tuple] = {}       # key → (meta, expiry)
        self._svc_cache: Dict[str, tuple] = {}   # ns → (services, expiry)
        self._lock = threading.Lock()
        self._override = None                    # (scheme, host, port, token)
        self._watch_thread: Optional[threading.Thread] = None
        self._watching = False

    def configure(self, scheme: str, host: str, port: int,
                  token: str = "") -> None:
        """Point at an explicit apiserver (tests / out-of-cluster)."""
        self._override = (scheme, host, port, token)

    def available(self) -> bool:
        if self._override is not None:
            return True
        return os.path.exists(self.token_path) and \
            bool(os.environ.get("KUBERNETES_SERVICE_HOST"))

    # -- transport ----------------------------------------------------------

    def _connect(self):
        if self._override is not None:
            scheme, host, port, token = self._override
            if scheme == "https":
                import ssl
                ctx = ssl.create_default_context()
                conn = http.client.HTTPSConnection(host, port, timeout=5,
                                                   context=ctx)
            else:
                conn = http.client.HTTPConnection(host, port, timeout=5)
            return conn, token
        import ssl
        if not os.path.exists(self.ca_path):
            log.warning("in-cluster CA bundle missing; refusing unverified "
                        "apiserver connection")
            raise OSError("no CA bundle")
        with open(self.token_path) as f:
            token = f.read().strip()
        host = os.environ["KUBERNETES_SERVICE_HOST"]
        port = int(os.environ.get("KUBERNETES_SERVICE_PORT", "443"))
        ctx = ssl.create_default_context(cafile=self.ca_path)
        return (http.client.HTTPSConnection(host, port, timeout=5,
                                            context=ctx), token)

    def _get_json(self, path: str, timeout: Optional[float] = None):
        conn, token = self._connect()
        if timeout is not None:
            conn.timeout = timeout
        conn.request("GET", path,
                     headers={"Authorization": f"Bearer {token}"}
                     if token else {})
        resp = conn.getresponse()
        data = json.loads(resp.read()) if resp.status == 200 else None
        conn.close()
        return data

    # -- pod cache ----------------------------------------------------------

    @staticmethod
    def _pod_meta(data: dict) -> dict:
        return {
            "labels": data.get("metadata", {}).get("labels", {}) or {},
            "node": data.get("spec", {}).get("nodeName", ""),
            "ip": data.get("status", {}).get("podIP", ""),
        }

    def pod_metadata(self, namespace: str, pod: str) -> Optional[dict]:
        key = f"{namespace}/{pod}"
        now = time.monotonic()
        with self._lock:
            hit = self._cache.get(key)
            if hit is not None and hit[1] > now:
                return hit[0]
        if not self.available():
            return None
        try:
            data = self._get_json(
                f"/api/v1/namespaces/{namespace}/pods/{pod}")
        except (OSError, ValueError, KeyError):
            data = None
        meta = self._pod_meta(data) if data is not None else None
        ttl = K8S_META_TTL_S if meta is not None else K8S_NEG_TTL_S
        with self._lock:
            if len(self._cache) > 4096:
                self._cache.clear()
            # negative results cache too (short TTL): an unauthorized or
            # unreachable apiserver must not cost a 5s timeout per pod per
            # discovery round
            self._cache[key] = (meta, now + ttl)
        return meta

    # -- watch stream -------------------------------------------------------

    def start_watch(self, node_name: str = "") -> bool:
        """Chunked WATCH over pods (optionally this node's) keeping the
        cache warm; reconnects with backoff. Returns False if unavailable."""
        if not self.available() or self._watching:
            return self._watching
        self._watching = True
        self._watch_thread = threading.Thread(
            target=self._watch_loop, args=(node_name,),
            name="k8s-meta-watch", daemon=True)
        self._watch_thread.start()
        return True

    def stop_watch(self) -> None:
        self._watching = False

    def _watch_loop(self, node_name: str) -> None:
        backoff = 1.0
        sel = (f"&fieldSelector=spec.nodeName={node_name}"
               if node_name else "")
        while self._watching:
            try:
                conn, token = self._connect()
                conn.timeout = 60
                conn.request(
                    "GET", f"/api/v1/pods?watch=1{sel}",
                    headers={"Authorization": f"Bearer {token}"}
                    if token else {})
                resp = conn.getresponse()
                if resp.status != 200:
                    conn.close()
                    raise OSError(f"watch status {resp.status}")
                backoff = 1.0
                buf = b""
                while self._watching:
                    chunk = resp.read1(65536)
                    if not chunk:
                        break
                    buf += chunk
                    while b"\n" in buf:
                        line, buf = buf.split(b"\n", 1)
                        if line.strip():
                            self._apply_watch_event(line)
                conn.close()
            except (OSError, ValueError, http.client.HTTPException):
                time.sleep(backoff)
                backoff = min(backoff * 2, 30.0)

    def _apply_watch_event(self, line: bytes) -> None:
        try:
            ev = json.loads(line)
        except ValueError:
            return
        obj = ev.get("object", {})
        md = obj.get("metadata", {})
        key = f"{md.get('namespace', '')}/{md.get('name', '')}"
        if key == "/":
            return
        with self._lock:
            if ev.get("type") == "DELETED":
                self._cache.pop(key, None)
            else:
                self._cache[key] = (self._pod_meta(obj),
                                    time.monotonic() + K8S_META_TTL_S)

    # -- services -----------------------------------------------------------

    def service_metadata(self, namespace: str) -> List[dict]:
        now = time.monotonic()
        with self._lock:
            hit = self._svc_cache.get(namespace)
            if hit is not None and hit[1] > now:
                return hit[0]
        if not self.available():
            return []
        try:
            data = self._get_json(f"/api/v1/namespaces/{namespace}/services")
        except (OSError, ValueError, KeyError):
            return []
        items = (data or {}).get("items", [])
        services = [{
            "name": s.get("metadata", {}).get("name", ""),
            "selector": s.get("spec", {}).get("selector", {}) or {},
            "cluster_ip": s.get("spec", {}).get("clusterIP", ""),
        } for s in items]
        with self._lock:
            self._svc_cache[namespace] = (services, now + K8S_META_TTL_S)
        return services

    def services_for_pod(self, namespace: str, pod: str) -> List[str]:
        """Service names whose selector matches the pod's labels (the
        reference's pod→service linkage in K8sMetadata)."""
        meta = self.pod_metadata(namespace, pod)
        if meta is None:
            return []
        labels = meta.get("labels", {})
        out = []
        for svc in self.service_metadata(namespace):
            sel = svc["selector"]
            if sel and all(labels.get(k) == v for k, v in sel.items()):
                out.append(svc["name"])
        return out
