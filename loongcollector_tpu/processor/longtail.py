"""Go long-tail processors, batch 1 (round-3 VERDICT item 5).

Reference (Go-compat semantics, differentially tested in
tests/test_longtail_processors.py):
  plugins/processor/dictmap/processor_dict_map.go       — value mapping
  plugins/processor/pickkey/processor_pick_key.go       — include/exclude
  plugins/processor/packjson/processor_packjson.go      — pack into JSON
  plugins/processor/base64/{encoding,decoding}/         — base64
  plugins/processor/encrypt/processor_encrypt.go        — AES-CBC + PKCS7
  plugins/processor/ratelimit/                          — token bucket
  plugins/processor/fieldswithcondition/                — switch-case
  plugins/processor/geoip/processor_geoip.go            — MMDB lookup

All operate on object LogEvents (post-parse). Group-level columnar fast
paths are provided where the operation is a pure per-field transform
(dictmap, pickkey).
"""

from __future__ import annotations

import base64
import binascii
import csv
import json
import re
import threading
import time
from typing import Any, Dict, List, Optional

from ..models import PipelineEventGroup
from ..pipeline.plugin.interface import PluginContext, Processor
from ..utils.logger import get_logger
from .filter import compact_columns

log = get_logger("longtail")


def _contents(ev) -> Optional[list]:
    return ev.contents if hasattr(ev, "contents") else None


def _materialize(group: PipelineEventGroup) -> None:
    """Columnar → object events for processors without a span-level path."""
    if group.columns is not None and not group._events:
        group.materialize()


class ProcessorDictMap(Processor):
    """Map a field's value through a dictionary
    (plugins/processor/dictmap/processor_dict_map.go:30-67, 139-186)."""

    name = "processor_dict_map"

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.source_key = config.get("SourceKey") or ""
        if not self.source_key:
            log.error("dict_map requires SourceKey")
            return False
        dest = config.get("DestKey") or ""
        self.scan_dest = bool(dest) and dest != self.source_key
        self.dest_key = dest if self.scan_dest else self.source_key
        self.mode = config.get("Mode", "overwrite")
        if self.mode not in ("overwrite", "fill"):
            log.error("dict_map Mode must be overwrite|fill")
            return False
        self.handle_missing = bool(config.get("HandleMissing", False))
        self.missing = str(config.get("Missing", "Unknown"))
        self.max_dict_size = int(config.get("MaxDictSize", 1000))
        self.map = {str(k): str(v)
                    for k, v in (config.get("MapDict") or {}).items()}
        path = config.get("DictFilePath")
        if path:
            try:
                with open(path, newline="") as f:
                    for i, row in enumerate(csv.reader(f)):
                        if len(self.map) > self.max_dict_size:
                            break
                        if len(row) != 2:
                            log.error("dict_map row %d not 2 columns", i + 1)
                            return False
                        if row[0] in self.map and self.map[row[0]] != row[1]:
                            log.error("dict_map duplicate key %r", row[0])
                            return False
                        self.map[row[0]] = row[1]
            except OSError as e:
                log.error("dict_map cannot read %s: %s", path, e)
                return False
        if not self.map:
            log.error("dict_map requires MapDict or DictFilePath")
            return False
        if len(self.map) > self.max_dict_size:
            log.error("dict_map exceeds MaxDictSize %d", self.max_dict_size)
            return False
        self.bmap = {k.encode(): v.encode() for k, v in self.map.items()}
        return True

    def process(self, group: PipelineEventGroup) -> None:
        _materialize(group)
        sb = group.source_buffer
        skey = self.source_key.encode()
        dkey = self.dest_key.encode()
        for ev in group.events:
            if not hasattr(ev, "get_content"):
                continue
            src = ev.get_content(skey)
            if src is None:
                # Go: missing source → optionally write Missing to DestKey
                if self.handle_missing:
                    self._write_dest(ev, sb, dkey, self.missing.encode())
                continue
            mapped = self.bmap.get(src.to_bytes())
            if mapped is None:
                continue                 # unmapped value: untouched
            if not self.scan_dest:
                ev.set_content(sb.copy_string(skey), sb.copy_string(mapped))
            else:
                self._write_dest(ev, sb, dkey, mapped)

    def _write_dest(self, ev, sb, dkey: bytes, value: bytes) -> None:
        existing = ev.get_content(dkey)
        if existing is not None and self.mode == "fill":
            return                       # fill: only when dest is absent
        ev.set_content(sb.copy_string(dkey), sb.copy_string(value))


class ProcessorPickKey(Processor):
    """Keep Include fields / drop Exclude fields; events left with no
    fields are dropped (plugins/processor/pickkey/processor_pick_key.go)."""

    name = "processor_pick_key"
    supports_columnar = True

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.include = {str(k) for k in config.get("Include") or []}
        self.exclude = {str(k) for k in config.get("Exclude") or []}
        return bool(self.include or self.exclude)

    def process(self, group: PipelineEventGroup) -> None:
        cols = group.columns
        if cols is not None and not group._events:
            import numpy as np
            for name in list(cols.fields):
                if (self.include and name not in self.include) or \
                        name in self.exclude:
                    del cols.fields[name]
            # the raw content column is the `content` field of the object
            # path — subject to the same include/exclude decision
            content_live = not cols.content_consumed or not cols.fields
            drop_content = (self.include and "content" not in self.include) \
                or "content" in self.exclude
            if content_live and drop_content:
                cols.content_consumed = True
                content_live = False
            # rows left with NO fields at all are dropped (Go: process()
            # returns false on empty Contents)
            if not content_live:
                keep = np.zeros(len(cols), dtype=bool)
                for offs, lens in cols.fields.values():
                    keep |= lens >= 0
                if not keep.all():
                    group.set_columns(compact_columns(cols, keep))
            return
        _materialize(group)
        inc = {k.encode() for k in self.include}
        exc = {k.encode() for k in self.exclude}
        kept = []
        for ev in group.events:
            contents = _contents(ev)
            if contents is None:
                kept.append(ev)
                continue
            out = [(k, v) for k, v in contents
                   if (not inc or k.to_bytes() in inc)
                   and k.to_bytes() not in exc]
            if len(out) != len(contents):
                ev.clear_contents()
                for k, v in out:
                    ev.set_content(k, v)
            if out:
                kept.append(ev)
        if len(kept) != len(group._events):
            group._events = kept


class ProcessorPackJson(Processor):
    """Pack SourceKeys into one JSON object field
    (plugins/processor/packjson/processor_packjson.go)."""

    name = "processor_packjson"

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.source_keys = [str(k) for k in config.get("SourceKeys") or []]
        self.dest_key = config.get("DestKey") or ""
        self.keep_source = bool(config.get("KeepSource", True))
        self.alarm_if_incomplete = bool(config.get("AlarmIfIncomplete",
                                                   False))
        return bool(self.source_keys) and bool(self.dest_key)

    def process(self, group: PipelineEventGroup) -> None:
        _materialize(group)
        sb = group.source_buffer
        keyset = {k.encode() for k in self.source_keys}
        for ev in group.events:
            contents = _contents(ev)
            if contents is None:
                continue
            packed: Dict[str, str] = {}
            remaining = []
            for k, v in contents:
                if k.to_bytes() in keyset:
                    packed[k.to_str()] = v.to_bytes().decode(
                        "utf-8", "replace")
                    if self.keep_source:
                        remaining.append((k, v))
                else:
                    remaining.append((k, v))
            if self.alarm_if_incomplete and len(packed) != len(keyset):
                missing = [k for k in self.source_keys if k not in packed]
                log.warning("packjson SourceKeys not found %s", missing)
            if not self.keep_source and len(remaining) != len(contents):
                ev.clear_contents()
                for k, v in remaining:
                    ev.set_content(k, v)
            blob = json.dumps(packed, ensure_ascii=False,
                              separators=(",", ":")).encode()
            ev.set_content(sb.copy_string(self.dest_key.encode()),
                           sb.copy_string(blob))


class ProcessorBase64Encoding(Processor):
    """plugins/processor/base64/encoding — encode SourceKey, into NewKey
    when set else in place."""

    name = "processor_base64_encoding"
    decode = False

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.source_key = (config.get("SourceKey") or "").encode()
        self.new_key = (config.get("NewKey") or "").encode()
        return bool(self.source_key)

    def _transform(self, data: bytes) -> Optional[bytes]:
        return base64.b64encode(data)

    def process(self, group: PipelineEventGroup) -> None:
        _materialize(group)
        sb = group.source_buffer
        for ev in group.events:
            if not hasattr(ev, "get_content"):
                continue
            src = ev.get_content(self.source_key)
            if src is None:
                log.warning("base64: cannot find key %s",
                            self.source_key.decode())
                continue
            out = self._transform(src.to_bytes())
            if out is None:
                continue                 # decode error: leave untouched
            key = self.new_key or self.source_key
            ev.set_content(sb.copy_string(key), sb.copy_string(out))


class ProcessorBase64Decoding(ProcessorBase64Encoding):
    name = "processor_base64_decoding"
    decode = True

    def _transform(self, data: bytes) -> Optional[bytes]:
        try:
            return base64.b64decode(data, validate=True)
        except (binascii.Error, ValueError):
            log.warning("base64 decode error")
            return None


class ProcessorEncrypt(Processor):
    """AES-CBC + PKCS7, hex-encoded output
    (plugins/processor/encrypt/processor_encrypt.go: key/IV are hex
    strings, key may come from a file; errors blank the value unless
    KeepSourceValueIfError)."""

    name = "processor_encrypt"
    ERROR_TEXT = b"ENCRYPT_ERROR"

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.source_keys = {str(k).encode()
                            for k in config.get("SourceKeys") or []}
        params = config.get("EncryptionParameters") or {}
        self.keep_on_error = bool(config.get("KeepSourceValueIfError",
                                             False))
        key_hex = params.get("Key") or ""
        key_path = params.get("KeyFilePath") or ""
        if key_path:
            try:
                with open(key_path) as f:
                    key_hex = f.read().strip()
            except OSError as e:
                log.error("encrypt cannot read key file: %s", e)
                return False
        iv_hex = params.get("IV") or ""
        if not self.source_keys or not key_hex or not iv_hex:
            log.error("encrypt requires SourceKeys, Key (or KeyFilePath) "
                      "and IV")
            return False
        try:
            self.key = bytes.fromhex(key_hex)
            self.iv = bytes.fromhex(iv_hex)
        except ValueError as e:
            log.error("encrypt key/IV must be hex: %s", e)
            return False
        if len(self.key) not in (16, 24, 32) or len(self.iv) != 16:
            log.error("encrypt key must be 16/24/32 bytes, IV 16")
            return False
        if _aes_cbc(self.key, self.iv, b"\x00" * 16) is None:
            # never let a missing cipher destroy data silently at runtime
            log.error("encrypt unavailable: native AES not loaded")
            return False
        return True

    def _encrypt(self, plaintext: bytes) -> Optional[bytes]:
        pad = 16 - len(plaintext) % 16
        padded = plaintext + bytes([pad]) * pad
        out = _aes_cbc(self.key, self.iv, padded)
        return out

    def process(self, group: PipelineEventGroup) -> None:
        _materialize(group)
        sb = group.source_buffer
        for ev in group.events:
            contents = _contents(ev)
            if contents is None:
                continue
            for k, v in list(contents):
                if k.to_bytes() not in self.source_keys:
                    continue
                ct = self._encrypt(v.to_bytes())
                if ct is None:
                    if not self.keep_on_error:
                        ev.set_content(k, sb.copy_string(self.ERROR_TEXT))
                    continue
                ev.set_content(k, sb.copy_string(ct.hex().encode()))


def _aes_cbc(key: bytes, iv: bytes, padded: bytes) -> Optional[bytes]:
    """Native AES-CBC (pure-Python AES is unreasonably slow; the native
    library is part of the build — None signals unavailability)."""
    import ctypes

    import numpy as np

    from .. import native as native_mod
    lib = native_mod.get_lib()
    if lib is None or not hasattr(lib, "lct_aes_cbc_encrypt"):
        return None
    if not getattr(lib, "_aes_bound", False):
        u8p = ctypes.c_void_p   # raw addresses via native_mod._u8
        lib.lct_aes_cbc_encrypt.restype = ctypes.c_int64
        lib.lct_aes_cbc_encrypt.argtypes = [
            u8p, ctypes.c_int64, u8p, u8p, ctypes.c_int64, u8p]
        lib._aes_bound = True
    k = np.frombuffer(key, np.uint8)
    i = np.frombuffer(iv, np.uint8)
    d = np.frombuffer(padded, np.uint8)
    out = np.empty(len(d), np.uint8)
    rc = lib.lct_aes_cbc_encrypt(native_mod._u8(k), len(k),
                                 native_mod._u8(i), native_mod._u8(d),
                                 len(d), native_mod._u8(out))
    if rc != 0:
        return None
    return out.tobytes()


class _TokenBucket:
    """Per-key token bucket (plugins/processor/ratelimit/token_bucket.go):
    burst = the limit numerator; refill at limit/period per second."""

    SWEEP_INTERVAL = 60.0

    def __init__(self, burst: float, per_second: float):
        self.burst = burst
        self.per_second = per_second
        self.buckets: Dict[bytes, List[float]] = {}  # key -> [tokens, last]
        self.lock = threading.Lock()
        self._next_sweep = time.monotonic() + self.SWEEP_INTERVAL

    def _sweep(self, now: float) -> None:
        """Evict idle buckets (refilled to full = carrying no state) so
        high-cardinality keys don't grow memory unboundedly (the
        reference's token_bucket.go runs the same periodic GC)."""
        idle_after = max(self.SWEEP_INTERVAL,
                         self.burst / max(self.per_second, 1e-9))
        for key in [k for k, (_, last) in self.buckets.items()
                    if now - last > idle_after]:
            del self.buckets[key]
        self._next_sweep = now + self.SWEEP_INTERVAL

    def allow(self, key: bytes) -> bool:
        now = time.monotonic()
        with self.lock:
            if now >= self._next_sweep:
                self._sweep(now)
            b = self.buckets.get(key)
            if b is None:
                # a fresh bucket starts FULL minus this event's token
                self.buckets[key] = [self.burst - 1.0, now]
                return True
            tokens, last = b
            tokens = min(self.burst,
                         tokens + (now - last) * self.per_second)
            if tokens >= 1.0:
                b[0] = tokens - 1.0
                b[1] = now
                return True
            b[0] = tokens
            b[1] = now
            return False


class ProcessorRateLimit(Processor):
    """Drop events above Limit per unique combination of Fields values
    (plugins/processor/ratelimit/processor_rate_limit.go)."""

    name = "processor_rate_limit"
    supports_columnar = True

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.fields = sorted(str(f) for f in config.get("Fields") or [])
        limit = str(config.get("Limit", "100/s"))
        m = re.fullmatch(r"(\d+(?:\.\d+)?)/([smh])", limit.strip())
        if not m:
            log.error("rate_limit Limit must look like 200/s")
            return False
        n = float(m.group(1))
        unit = {"s": 1.0, "m": 60.0, "h": 3600.0}[m.group(2)]
        self.bucket = _TokenBucket(n, n / unit)
        return True

    def _key(self, ev) -> bytes:
        if not self.fields:
            return b""
        parts = []
        for f in self.fields:
            v = ev.get_content(f.encode()) if hasattr(ev, "get_content") \
                else None
            parts.append(v.to_bytes() if v is not None else b"")
        return b"\x1f".join(parts)

    def process(self, group: PipelineEventGroup) -> None:
        _materialize(group)
        kept = [ev for ev in group.events if self.bucket.allow(self._key(ev))]
        if len(kept) != len(group._events):
            group._events = kept


class ProcessorFieldsWithCondition(Processor):
    """Switch-case conditional field edit (plugins/processor/
    fieldswithcondition/processor_fields_with_condition.go): first
    matching case applies its actions; optionally drop non-matching."""

    name = "processor_fields_with_condition"
    supports_columnar = True

    _OPS = {
        "equals": lambda cond, val: val == cond,
        "contains": lambda cond, val: cond in val,
        "startwith": lambda cond, val: val.startswith(cond),
    }

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.drop_if_not_match = bool(
            config.get("DropIfNotMatchCondition", False))
        self.cases = []
        for cond in config.get("Switch") or []:
            case = cond.get("Case") or {}
            op = (case.get("RelationOperator") or "equals").lower()
            logic = (case.get("LogicalOperator") or "and").lower()
            if op not in ("equals", "regexp", "contains", "startwith"):
                op = "equals"
            fields = {}
            for k, v in (case.get("FieldConditions") or {}).items():
                fields[str(k).encode()] = (
                    re.compile(str(v).encode()) if op == "regexp"
                    else str(v).encode())
            actions = []
            for act in cond.get("Actions") or []:
                atype = act.get("type") or act.get("Type") or ""
                actions.append({
                    "type": atype,
                    "ignore_if_exist": bool(act.get("IgnoreIfExist")),
                    "fields": {str(k): str(v) for k, v in
                               (act.get("Fields") or {}).items()},
                    "drop_keys": [str(k) for k in
                                  act.get("DropKeys") or []],
                })
            self.cases.append((op, logic, fields, actions))
        return bool(self.cases)

    def _match(self, ev, op, logic, fields) -> bool:
        results = []
        for key, cond in fields.items():
            v = ev.get_content(key)
            if v is None:
                results.append(False)
                continue
            val = v.to_bytes()
            if op == "regexp":
                results.append(cond.search(val) is not None)
            else:
                results.append(self._OPS[op](cond, val))
        if not results:
            return True
        return all(results) if logic == "and" else any(results)

    def process(self, group: PipelineEventGroup) -> None:
        _materialize(group)
        sb = group.source_buffer
        kept = []
        for ev in group.events:
            if not hasattr(ev, "get_content"):
                kept.append(ev)
                continue
            matched = False
            for op, logic, fields, actions in self.cases:
                if self._match(ev, op, logic, fields):
                    matched = True
                    self._apply(ev, sb, actions)
                    break
            if matched or not self.drop_if_not_match:
                kept.append(ev)
        if len(kept) != len(group._events):
            group._events = kept

    def _apply(self, ev, sb, actions) -> None:
        for act in actions:
            if act["type"] == "processor_add_fields":
                for k, v in act["fields"].items():
                    if act["ignore_if_exist"] and \
                            ev.get_content(k.encode()) is not None:
                        continue
                    ev.set_content(sb.copy_string(k.encode()),
                                   sb.copy_string(v.encode()))
            elif act["type"] == "processor_drop":
                drop = {k.encode() for k in act["drop_keys"]}
                contents = [(k, v) for k, v in ev.contents
                            if k.to_bytes() not in drop]
                if len(contents) != len(ev.contents):
                    ev.clear_contents()
                    for k, v in contents:
                        ev.set_content(k, v)


class ProcessorGeoIP(Processor):
    """IP → geography via a MaxMind DB
    (plugins/processor/geoip/processor_geoip.go; field naming
    SourceKey_city_/_province_/_country_/_country_code_/_longitude_/
    _latitude_ per :143-163)."""

    name = "processor_geoip"

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.source_key = (config.get("SourceKey") or "").encode()
        self.language = config.get("Language", "zh-CN")
        self.no_city = bool(config.get("NoCity", False))
        self.no_province = bool(config.get("NoProvince", False))
        self.no_country = bool(config.get("NoCountry", False))
        self.no_country_code = bool(config.get("NoCountryCode", False))
        self.no_coordinate = bool(config.get("NoCoordinate", True))
        self.no_key_error = bool(config.get("NoKeyError", False))
        path = config.get("DBPath") or ""
        if not path or not self.source_key:
            log.error("geoip requires DBPath and SourceKey")
            return False
        try:
            from ..utils.mmdb import Reader
            self.db = Reader(path)
        except Exception as e:  # noqa: BLE001 — bad/missing db
            log.error("geoip cannot open %s: %s", path, e)
            return False
        return True

    def _names(self, section) -> Optional[str]:
        names = (section or {}).get("names") or {}
        return names.get(self.language) or names.get("en")

    def process(self, group: PipelineEventGroup) -> None:
        _materialize(group)
        sb = group.source_buffer
        prefix = self.source_key
        for ev in group.events:
            if not hasattr(ev, "get_content"):
                continue
            v = ev.get_content(self.source_key)
            if v is None:
                if self.no_key_error:
                    log.warning("geoip: cannot find key %s",
                                self.source_key.decode())
                continue
            rec = self.db.lookup(v.to_str())
            if rec is None:
                continue

            def put(suffix: bytes, value: str) -> None:
                ev.set_content(sb.copy_string(prefix + suffix),
                               sb.copy_string(value.encode()))

            if not self.no_city:
                city = self._names(rec.get("city"))
                if city:
                    put(b"_city_", city)
            subs = rec.get("subdivisions") or []
            if subs:
                if not self.no_province:
                    prov = self._names(subs[0])
                    if prov:
                        put(b"_province_", prov)
                iso = subs[0].get("iso_code")
                if iso:
                    put(b"_province_code_", iso)
            country = rec.get("country") or {}
            if not self.no_country:
                cn = self._names(country)
                if cn:
                    put(b"_country_", cn)
            if not self.no_country_code and country.get("iso_code"):
                put(b"_country_code_", country["iso_code"])
            loc = rec.get("location") or {}
            if not self.no_coordinate and "longitude" in loc:
                put(b"_longitude_", f"{loc['longitude']:.8f}")
                put(b"_latitude_", f"{loc['latitude']:.8f}")
