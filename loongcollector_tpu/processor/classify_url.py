"""processor_classify_url — rule-based URL/category classification on device.

The BASELINE.json scenario "eBPF HTTP/network events → TPU regex URL
classification": each rule is a regex over a source field (default `path`);
the first matching rule's name becomes the category.  Every rule runs as a
batched device match (Tier-1/DFA) over the whole group — N rules = N device
match passes over span columns, no per-event Python.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

from ..models import PipelineEventGroup
from ..ops.regex.engine import RegexEngine, get_engine
from ..pipeline.plugin.interface import PluginContext, Processor
from .common import extract_source


class ProcessorClassifyUrl(Processor):
    name = "processor_classify_url_tpu"
    supports_columnar = True

    def __init__(self) -> None:
        super().__init__()
        self.source_key = b"path"
        self.target_key = "category"
        self.default = b"other"
        self.rules: List[Tuple[bytes, RegexEngine]] = []

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.source_key = config.get("SourceKey", "path").encode()
        self.target_key = config.get("TargetKey", "category")
        self.default = config.get("DefaultCategory", "other").encode()
        for rule in config.get("Rules", []):
            name = rule.get("Name", "")
            pattern = rule.get("Regex", "")
            if not name or not pattern:
                return False
            self.rules.append((name.encode(), get_engine(pattern)))
        return bool(self.rules)

    def process(self, group: PipelineEventGroup) -> None:
        src = extract_source(group, self.source_key)
        if src is None:
            return
        n = len(src.offsets)
        if n == 0:
            return
        sb = group.source_buffer
        cat_views = [sb.copy_string(name) for name, _ in self.rules]
        default_view = sb.copy_string(self.default)

        if src.columnar:
            cols = group.columns
            offs = np.full(n, default_view.offset, dtype=np.int32)
            lens = np.where(src.present, default_view.length, -1).astype(np.int32)
            unassigned = src.present.copy()
            for (name, engine), view in zip(self.rules, cat_views):
                if not unassigned.any():
                    break
                idx = np.nonzero(unassigned)[0]
                ok = engine.match_batch(src.arena, src.offsets[idx],
                                        src.lengths[idx])
                hit = idx[ok]
                offs[hit] = view.offset
                lens[hit] = view.length
                unassigned[hit] = False
            cols.set_field(self.target_key, offs, lens)
            return

        for ev in group.events:
            if not hasattr(ev, "get_content"):
                continue
            v = ev.get_content(self.source_key)
            if v is None:
                continue
            data = v.to_bytes()
            label = default_view
            for (name, engine), view in zip(self.rules, cat_views):
                if engine._re.fullmatch(data):
                    label = view
                    break
            ev.set_content(self.target_key.encode(), label)
