"""Inner processor: multiline log assembly (stacktrace merging) — columnar.

Reference: core/plugin/processor/inner/ProcessorSplitMultilineLogStringNative
.cpp with MultilineOptions (file_server/MultilineOptions.h:38-47):
start/continue/end regexes group physical lines into logical events;
UnmatchedContentTreatment = discard | single_line.

TPU-first: this is the framework's "long-context" problem (SURVEY.md §5.7).
With a StartPattern, line classification runs as ONE device match batch, and
because split lines are contiguous slices of the same arena, merging a block
of lines is pure span arithmetic — the merged event is the arena span from
the first line's offset to the last line's end, newlines included, zero-copy.
Continue/End patterns run the same batched classification with a host-side
block-boundary pass.

Cross-chunk carry: the file reader holds open records in the file (its
multiline rollback), so chunks normally start and end on record boundaries.
When it CANNOT hold (record longer than a chunk, flush timeout) it marks
the group ML_PARTIAL_TAIL and the follow-up ML_CONTINUE; this processor
then stashes the open record's bytes per source and stitches them onto the
next chunk's leading lines, so a stacktrace split mid-record across two
read chunks still yields ONE event (round-2 VERDICT item 3).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..models import ColumnarLogs, EventGroupMetaKey, PipelineEventGroup
from ..ops.regex.engine import RegexEngine, get_engine
from ..pipeline.plugin.interface import PluginContext, Processor

CARRY_CAP_BYTES = 1 << 20   # give up stitching records larger than this
CARRY_FLUSH_S = 5.0         # idle carries flush via the pipeline timeout tick
CARRY_TTL_S = 30.0          # orphaned stashes flush through the next group


class ProcessorSplitMultilineLogString(Processor):
    name = "processor_split_multiline_log_string_native"

    def __init__(self) -> None:
        super().__init__()
        self.start: Optional[RegexEngine] = None
        self.cont: Optional[RegexEngine] = None
        self.end: Optional[RegexEngine] = None
        self.unmatched = "single_line"  # or "discard"
        # per-source open-record stash: key → (bytes, event_ts, stashed_at);
        # locked: _finish runs on processor threads, flush_timeout_groups on
        # thread 0's timeout tick (same contract as Batcher)
        self._carry: Dict[str, Tuple[bytes, int, float]] = {}
        self._carry_lock = threading.Lock()

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        mcfg = config.get("Multiline", config)
        sp = mcfg.get("StartPattern")
        cp = mcfg.get("ContinuePattern")
        ep = mcfg.get("EndPattern")
        self.start = get_engine(self._fullmatchify(sp)) if sp else None
        self.cont = get_engine(self._fullmatchify(cp)) if cp else None
        self.end = get_engine(self._fullmatchify(ep)) if ep else None
        self.unmatched = mcfg.get("UnmatchedContentTreatment", "single_line")
        return self.start is not None or self.end is not None

    @staticmethod
    def _fullmatchify(pattern: str) -> str:
        """Reference multiline patterns are full-line matches; users commonly
        write prefixes ending in `.*` — keep as-is (engine is full-match)."""
        return pattern

    def process(self, group: PipelineEventGroup) -> None:
        cols = group.columns
        if cols is None or group._events:
            return  # expects the line-split columnar form
        n = len(cols)
        if n == 0:
            return
        arena = group.source_buffer.as_array()
        offs = cols.offsets.astype(np.int64)
        lens = cols.lengths

        is_start = (self.start.match_batch(arena, offs, lens)
                    if self.start else np.zeros(n, dtype=bool))
        is_end = (self.end.match_batch(arena, offs, lens)
                  if self.end else None)
        is_cont = (self.cont.match_batch(arena, offs, lens)
                   if self.cont else None)

        blocks: List[Tuple[int, int]] = []
        unmatched: List[int] = []
        if self.start is not None:
            starts_idx = np.nonzero(is_start)[0]
            if is_end is not None:
                # start..end blocks: lines after an end and before next start
                # are unmatched
                i = 0
                while i < n:
                    if is_start[i]:
                        j = i
                        while j < n and not is_end[j]:
                            j += 1
                        if j < n:
                            blocks.append((i, j))
                            i = j + 1
                        else:
                            blocks.append((i, n - 1))
                            i = n
                    else:
                        unmatched.append(i)
                        i += 1
            elif is_cont is not None:
                i = 0
                while i < n:
                    if is_start[i]:
                        j = i
                        while j + 1 < n and is_cont[j + 1]:
                            j += 1
                        blocks.append((i, j))
                        i = j + 1
                    else:
                        unmatched.append(i)
                        i += 1
            else:
                # start-only: vectorised — block k spans starts_idx[k] ..
                # (starts_idx[k+1] - 1); leading lines are unmatched
                if len(starts_idx):
                    block_first = starts_idx
                    block_last = np.concatenate([starts_idx[1:] - 1, [n - 1]])
                    blocks = list(zip(block_first.tolist(),
                                      block_last.tolist()))
                    unmatched = list(range(int(starts_idx[0])))
                else:
                    unmatched = list(range(n))
        else:
            # end-only mode: block closes at each end-match
            start_i = 0
            for i in range(n):
                if is_end[i]:
                    blocks.append((start_i, i))
                    start_i = i + 1
            unmatched.extend(range(start_i, n))

        self._finish(group, cols, arena, blocks, unmatched, is_end)

    # -- carry stitching + emission -----------------------------------------

    def _source_key(self, group: PipelineEventGroup) -> str:
        path = group.get_metadata(EventGroupMetaKey.LOG_FILE_PATH) or ""
        ino = group.get_metadata(EventGroupMetaKey.LOG_FILE_INODE) or ""
        return f"{path}:{ino}"

    def _finish(self, group, cols, arena, blocks, unmatched, is_end) -> None:
        n = len(cols)
        offs = cols.offsets.astype(np.int64)
        lens = cols.lengths.astype(np.int64)
        tss = cols.timestamps
        key = self._source_key(group)
        ml_continue = group.get_metadata(EventGroupMetaKey.ML_CONTINUE) == "1"
        ml_partial = group.get_metadata(
            EventGroupMetaKey.ML_PARTIAL_TAIL) == "1"
        with self._carry_lock:
            carried = self._carry.pop(key, None)

        # records: (order, arena_off, arena_len) — order keeps input order;
        # injected: (order, bytes, ts) — carried records copied into the
        # group's arena at emit time (offset-stable across buffer growth)
        records: List[Tuple[int, int, int]] = []
        injected: List[Tuple[int, bytes, int]] = []

        # expire orphaned stashes (source rotated/deleted and never came
        # back): deliver their bytes through THIS group rather than losing
        # them — content intact, group-level source meta may differ
        now = time.monotonic()
        with self._carry_lock:
            for k in list(self._carry):
                b, t, at = self._carry[k]
                if now - at > CARRY_TTL_S:
                    del self._carry[k]
                    injected.append((-2, b, t))

        # leading run of unmatched lines (contiguous from line 0) — the
        # lines a carried open record can continue into
        lead_end = 0
        while lead_end < len(unmatched) and unmatched[lead_end] == lead_end:
            lead_end += 1

        lead_consumed = 0
        if carried is not None:
            cbytes, cts, _ = carried
            take = 0               # leading lines absorbed into the carry
            closed = False         # the absorbed run CLOSES the record
            if ml_continue:
                if self.end is not None and self.start is None:
                    # end-only mode: continuation lines close at an
                    # end-match and therefore form blocks[0], not unmatched
                    if blocks and blocks[0][0] == 0:
                        take = blocks.pop(0)[1] + 1
                        closed = True
                    elif not blocks and lead_end == n:
                        take = n   # no END yet: whole chunk continues
                else:
                    # start modes: absorb the leading unmatched run, but in
                    # start+end mode STOP at the first end-match — lines
                    # after it are ordinary unmatched content
                    take = lead_end
                    if is_end is not None:
                        for i in range(lead_end):
                            if is_end[i]:
                                take = i + 1
                                closed = True
                                break
            if take > 0:
                span_lo = int(offs[0])
                span_hi = int(offs[take - 1] + lens[take - 1])
                # line spans exclude their trailing newline, so the joint
                # between the carried half and this chunk needs it back
                merged = cbytes + b"\n" + bytes(
                    arena[span_lo:span_hi].tobytes())
                lead_consumed = take
                if ml_partial and not closed and take == n and not blocks:
                    # the whole chunk is still the SAME open record —
                    # keep carrying (unless it outgrew the cap)
                    self._stash(key, merged, cts, injected)
                else:
                    injected.append((-1, merged, cts))
            else:
                # record ended exactly at the chunk boundary (next line is a
                # start) or the continuation never arrived: emit standalone
                injected.append((-1, cbytes, cts))

        # tail record to stash when this chunk breaks mid-record (skip when
        # the whole chunk was already re-stashed as the carried record)
        if ml_partial and lead_consumed < n:
            if blocks and blocks[-1][1] == n - 1:
                first, last = blocks.pop()
                lo = int(offs[first])
                hi = int(offs[last] + lens[last])
                self._stash(key, bytes(arena[lo:hi].tobytes()),
                            int(tss[first]), injected)
            else:
                # trailing contiguous unmatched run ending at the last line
                # continues an open record
                t = len(unmatched)
                expect = n - 1
                while t > 0 and unmatched[t - 1] == expect and \
                        expect >= lead_consumed:
                    t -= 1
                    expect -= 1
                tail_run = unmatched[t:]
                if tail_run:
                    del unmatched[t:]
                    lo = int(offs[tail_run[0]])
                    hi = int(offs[tail_run[-1]] + lens[tail_run[-1]])
                    self._stash(key, bytes(arena[lo:hi].tobytes()),
                                int(tss[tail_run[0]]), injected)

        for first, last in blocks:
            lo = int(offs[first])
            records.append((first, lo, int(offs[last] + lens[last]) - lo))
        if self.unmatched != "discard":
            for i in unmatched:
                if i < lead_consumed:
                    continue
                records.append((i, int(offs[i]), int(lens[i])))
        self._emit(group, records, injected, tss)

    def _stash(self, key, data: bytes, ts: int, injected) -> None:
        if len(data) > CARRY_CAP_BYTES:
            injected.append((1 << 30, data, ts))  # too big: emit as-is, last
            return
        with self._carry_lock:
            prev = self._carry.pop(key, None)
            self._carry[key] = (data, ts, time.monotonic())
        if prev is not None:
            # With multiple processor threads, chunks of one source can be
            # processed out of order: a concurrent worker stashed for this
            # key between our pop and this stash. Overwriting would LOSE
            # that open record — emit it standalone instead (degraded
            # stitching, zero loss).
            injected.append((-3, prev[0], prev[1]))

    # -- pipeline drain hooks (idle/shutdown delivery of held records) ------

    def _carry_group(self, key: str, data: bytes,
                     ts: int) -> PipelineEventGroup:
        from ..models import SourceBuffer
        sb = SourceBuffer(len(data) + 64)
        g = PipelineEventGroup(sb)
        view = sb.copy_string(data)
        g.set_columns(ColumnarLogs(
            offsets=np.array([view.offset], np.int32),
            lengths=np.array([len(data)], np.int32),
            timestamps=np.array([ts or int(time.time())], np.int64)))
        path, _, ino = key.rpartition(":")
        if path:
            g.set_metadata(EventGroupMetaKey.LOG_FILE_PATH, path)
        if ino:
            g.set_metadata(EventGroupMetaKey.LOG_FILE_INODE, ino)
        return g

    def flush_timeout_groups(self) -> List[PipelineEventGroup]:
        """Carried records whose continuation never arrived flush on the
        pipeline's timeout tick, so an idle source still delivers its last
        record (reference flush-timeout semantics)."""
        now = time.monotonic()
        expired: List[Tuple[str, bytes, int]] = []
        with self._carry_lock:
            for key in list(self._carry):
                data, ts, at = self._carry[key]
                if now - at >= CARRY_FLUSH_S:
                    del self._carry[key]
                    expired.append((key, data, ts))
        return [self._carry_group(k, d, t) for k, d, t in expired]

    def drain_groups(self) -> List[PipelineEventGroup]:
        """Shutdown: every held record ships (pipeline stop drain)."""
        with self._carry_lock:
            held = list(self._carry.items())
            self._carry.clear()
        return [self._carry_group(k, d, t) for k, (d, t, _) in held]

    def _emit(self, group, records, injected, tss=None) -> None:
        sb = group.source_buffer
        rows: List[Tuple[int, int, int, int]] = []  # (order, off, len, ts)
        for order, off, ln in records:
            rows.append((order, off, ln,
                         int(tss[order]) if tss is not None else 0))
        for order, data, ts in injected:
            view = sb.copy_string(data)
            rows.append((order, view.offset, len(data), ts))
        rows.sort(key=lambda r: r[0])
        group.set_columns(ColumnarLogs(
            offsets=np.array([r[1] for r in rows], dtype=np.int32),
            lengths=np.array([r[2] for r in rows], dtype=np.int32),
            timestamps=np.array([r[3] for r in rows], dtype=np.int64)))
