"""Inner processor: multiline log assembly (stacktrace merging) — columnar.

Reference: core/plugin/processor/inner/ProcessorSplitMultilineLogStringNative
.cpp with MultilineOptions (file_server/MultilineOptions.h:38-47):
start/continue/end regexes group physical lines into logical events;
UnmatchedContentTreatment = discard | single_line.

TPU-first: this is the framework's "long-context" problem (SURVEY.md §5.7).
With a StartPattern, line classification runs as ONE device match batch, and
because split lines are contiguous slices of the same arena, merging a block
of lines is pure span arithmetic — the merged event is the arena span from
the first line's offset to the last line's end, newlines included, zero-copy.
Continue/End patterns run the same batched classification with a host-side
block-boundary pass.

Cross-chunk carry: the file reader holds open records in the file (its
multiline rollback), so chunks normally start and end on record boundaries.
When it CANNOT hold (record longer than a chunk, flush timeout) it marks
the group ML_PARTIAL_TAIL and the follow-up ML_CONTINUE; this processor
then stashes the open record's bytes per source and stitches them onto the
next chunk's leading lines, so a stacktrace split mid-record across two
read chunks still yields ONE event (round-2 VERDICT item 3).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..models import ColumnarLogs, EventGroupMetaKey, PipelineEventGroup
from ..ops.regex.engine import RegexEngine, get_engine
from ..pipeline.plugin.interface import PluginContext, Processor

CARRY_CAP_BYTES = 1 << 20   # give up stitching records larger than this
CARRY_FLUSH_S = 5.0         # idle carries flush via the pipeline timeout tick
CARRY_TTL_S = 30.0          # orphaned stashes flush through the next group


class ProcessorSplitMultilineLogString(Processor):
    name = "processor_split_multiline_log_string_native"
    supports_columnar = True
    requires_columnar = True

    def __init__(self) -> None:
        super().__init__()
        self.start: Optional[RegexEngine] = None
        self.cont: Optional[RegexEngine] = None
        self.end: Optional[RegexEngine] = None
        self.unmatched = "single_line"  # or "discard"
        # per-source open-record stash: key → (bytes, event_ts, stashed_at);
        # locked: _finish runs on processor threads, flush_timeout_groups on
        # thread 0's timeout tick (same contract as Batcher)
        self._carry: Dict[str, Tuple[bytes, int, float]] = {}
        self._carry_lock = threading.Lock()

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        mcfg = config.get("Multiline", config)
        sp = mcfg.get("StartPattern")
        cp = mcfg.get("ContinuePattern")
        ep = mcfg.get("EndPattern")
        self.start = get_engine(self._fullmatchify(sp)) if sp else None
        self.cont = get_engine(self._fullmatchify(cp)) if cp else None
        self.end = get_engine(self._fullmatchify(ep)) if ep else None
        self.unmatched = mcfg.get("UnmatchedContentTreatment", "single_line")
        # loongfuse: classify start/continue/end in ONE scan (one device
        # pass / one native table walk) instead of a match batch per
        # pattern — the per-pattern round trips are what collapsed
        # multiline on TPU (1.6 MB/s, ROADMAP item 3)
        self._fused_set = None
        self._fused_slots: Dict[str, int] = {}
        pats = [(name, eng.pattern) for name, eng in
                (("start", self.start), ("cont", self.cont),
                 ("end", self.end)) if eng is not None]
        if len(pats) > 1:
            from ..ops.regex.fuse import try_build_set
            self._fused_set = try_build_set([p for _, p in pats],
                                            names=[n for n, _ in pats])
            if self._fused_set is not None:
                self._fused_slots = {n: i for i, (n, _) in enumerate(pats)}
        return self.start is not None or self.end is not None

    @staticmethod
    def _classify(masks, name, engine, arena, offs, lens) -> np.ndarray:
        """Fused classification when the pattern joined the set; the
        per-pattern match batch when it was demoted or the set didn't
        fuse — identical booleans either way."""
        got = masks.get(name)
        if got is not None:
            return got
        return engine.match_batch(arena, offs, lens)

    @staticmethod
    def _fullmatchify(pattern: str) -> str:
        """Reference multiline patterns are full-line matches; users commonly
        write prefixes ending in `.*` — keep as-is (engine is full-match)."""
        return pattern

    def process(self, group: PipelineEventGroup) -> None:
        cols = group.columns
        if cols is None or group._events:
            return  # expects the line-split columnar form
        n = len(cols)
        if n == 0:
            return
        arena = group.source_buffer.as_array()
        offs = cols.offsets.astype(np.int64)
        lens = cols.lengths

        masks: Dict[str, Optional[np.ndarray]] = {}
        if self._fused_set is not None:
            member = self._fused_set.member_masks(
                self._fused_set.classify(arena, offs, lens))
            masks = {name: member[slot]
                     for name, slot in self._fused_slots.items()}
        self._classify_blocks(group, cols, arena, offs, lens, masks)

    def fused_stage_spec(self, ctx):
        """loongresident: the start/continue/end classify scan joins a
        fused pipeline program as its LAST stage (``terminal=True`` — the
        block merge rebuilds the row population, so nothing downstream
        can consume the packed rows).  The block walk and carry stitching
        are unchanged host logic over the scan's tag bitmask."""
        fs = self._fused_set
        if fs is None or not fs.fdfa.device_ok:
            return None
        if not ctx.bind_source(b"content"):
            return None
        from ..ops import fused_pipeline as fp
        from ..pipeline.fused_chain import FusedMemberStage
        spec = fp.StageSpec("scan", fs.fdfa,
                            ["scan"] + list(fs.fdfa.patterns),
                            staged=fs._device_kernel(),
                            terminal=True, label="multiline-classify")
        return FusedMemberStage(spec, self._fused_apply)

    def _fused_apply(self, group, src, out, rowmap):
        cols = group.columns
        if cols is None or group._events or len(rowmap) != len(cols):
            return rowmap
        arena = group.source_buffer.as_array()
        tags = np.asarray(out[0]).astype(np.uint32)[rowmap]
        member = self._fused_set.member_masks(tags)
        masks = {name: member[slot]
                 for name, slot in self._fused_slots.items()}
        self._classify_blocks(group, cols, arena,
                              cols.offsets.astype(np.int64), cols.lengths,
                              masks)
        return rowmap

    def _classify_blocks(self, group, cols, arena, offs, lens,
                         masks: Dict[str, Optional[np.ndarray]]) -> None:
        n = len(cols)
        is_start = (self._classify(masks, "start", self.start, arena, offs,
                                   lens)
                    if self.start else np.zeros(n, dtype=bool))
        is_end = (self._classify(masks, "end", self.end, arena, offs, lens)
                  if self.end else None)
        is_cont = (self._classify(masks, "cont", self.cont, arena, offs,
                                  lens)
                   if self.cont else None)

        # blocks as parallel arrays (first[k], last[k]) + sorted unmatched
        # indices — vectorised in the hot modes (start-only, end-only);
        # start+end / start+cont have a sequential absorb dependency and
        # walk Python lists
        if self.start is not None:
            starts_idx = np.nonzero(is_start)[0]
            if is_end is not None or is_cont is not None:
                first, last, unmatched = self._walk_blocks(
                    n, is_start.tolist(),
                    is_end.tolist() if is_end is not None else None,
                    is_cont.tolist() if is_cont is not None else None)
            else:
                # start-only: block k spans starts_idx[k] ..
                # (starts_idx[k+1] - 1); leading lines are unmatched
                if len(starts_idx):
                    first = starts_idx.astype(np.int64)
                    last = np.concatenate([starts_idx[1:] - 1, [n - 1]])
                    unmatched = np.arange(int(starts_idx[0]), dtype=np.int64)
                else:
                    first = np.zeros(0, dtype=np.int64)
                    last = np.zeros(0, dtype=np.int64)
                    unmatched = np.arange(n, dtype=np.int64)
        else:
            # end-only mode: block closes at each end-match
            ends_idx = np.nonzero(is_end)[0].astype(np.int64)
            if len(ends_idx):
                last = ends_idx
                first = np.concatenate([[0], ends_idx[:-1] + 1])
                tail_start = int(ends_idx[-1]) + 1
            else:
                first = last = np.zeros(0, dtype=np.int64)
                tail_start = 0
            unmatched = np.arange(tail_start, n, dtype=np.int64)

        self._finish(group, cols, arena, first, last, unmatched, is_end)

    @staticmethod
    def _walk_blocks(n, s_l, e_l, c_l):
        """start+end / start+cont block walk (sequential absorb dependency:
        a start line inside an open block is consumed by it, so this cannot
        vectorise).  end mode closes at an end-match; cont mode extends
        while the NEXT line continues."""
        firsts: List[int] = []
        lasts: List[int] = []
        unmatched_l: List[int] = []
        i = 0
        while i < n:
            if s_l[i]:
                j = i
                if e_l is not None:
                    while j < n and not e_l[j]:
                        j += 1
                    if j >= n:
                        j = n - 1
                else:
                    while j + 1 < n and c_l[j + 1]:
                        j += 1
                firsts.append(i)
                lasts.append(j)
                i = j + 1
            else:
                unmatched_l.append(i)
                i += 1
        return (np.array(firsts, dtype=np.int64),
                np.array(lasts, dtype=np.int64),
                np.array(unmatched_l, dtype=np.int64))

    # -- carry stitching + emission -----------------------------------------

    def _source_key(self, group: PipelineEventGroup) -> str:
        path = group.get_metadata(EventGroupMetaKey.LOG_FILE_PATH) or ""
        ino = group.get_metadata(EventGroupMetaKey.LOG_FILE_INODE) or ""
        return f"{path}:{ino}"

    def _finish(self, group, cols, arena, first, last, unmatched,
                is_end) -> None:
        n = len(cols)
        offs = cols.offsets.astype(np.int64)
        lens = cols.lengths.astype(np.int64)
        tss = cols.timestamps
        key = self._source_key(group)
        ml_continue = group.get_metadata(EventGroupMetaKey.ML_CONTINUE) == "1"
        ml_partial = group.get_metadata(
            EventGroupMetaKey.ML_PARTIAL_TAIL) == "1"
        with self._carry_lock:
            carried = self._carry.pop(key, None)

        # injected: (order, bytes, ts) — carried records copied into the
        # group's arena at emit time (offset-stable across buffer growth)
        injected: List[Tuple[int, bytes, int]] = []

        # expire orphaned stashes (source rotated/deleted and never came
        # back): deliver their bytes through THIS group rather than losing
        # them — content intact, group-level source meta may differ
        now = time.monotonic()
        with self._carry_lock:
            for k in list(self._carry):
                b, t, at = self._carry[k]
                if now - at > CARRY_TTL_S:
                    del self._carry[k]
                    injected.append((-2, b, t))

        # leading run of unmatched lines (contiguous from line 0) — the
        # lines a carried open record can continue into
        m = len(unmatched)
        brk = np.nonzero(unmatched != np.arange(m))[0]
        lead_end = int(brk[0]) if len(brk) else m

        lead_consumed = 0
        if carried is not None:
            cbytes, cts, _ = carried
            take = 0               # leading lines absorbed into the carry
            closed = False         # the absorbed run CLOSES the record
            if ml_continue:
                if self.end is not None and self.start is None:
                    # end-only mode: continuation lines close at an
                    # end-match and therefore form blocks[0], not unmatched
                    if len(first) and first[0] == 0:
                        take = int(last[0]) + 1
                        first, last = first[1:], last[1:]
                        closed = True
                    elif not len(first) and lead_end == n:
                        take = n   # no END yet: whole chunk continues
                else:
                    # start modes: absorb the leading unmatched run, but in
                    # start+end mode STOP at the first end-match — lines
                    # after it are ordinary unmatched content
                    take = lead_end
                    if is_end is not None:
                        hits = np.nonzero(is_end[:lead_end])[0]
                        if len(hits):
                            take = int(hits[0]) + 1
                            closed = True
            if take > 0:
                span_lo = int(offs[0])
                span_hi = int(offs[take - 1] + lens[take - 1])
                # line spans exclude their trailing newline, so the joint
                # between the carried half and this chunk needs it back
                merged = cbytes + b"\n" + bytes(
                    arena[span_lo:span_hi].tobytes())
                lead_consumed = take
                if ml_partial and not closed and take == n and not len(first):
                    # the whole chunk is still the SAME open record —
                    # keep carrying (unless it outgrew the cap)
                    self._stash(key, merged, cts, injected)
                else:
                    injected.append((-1, merged, cts))
            else:
                # record ended exactly at the chunk boundary (next line is a
                # start) or the continuation never arrived: emit standalone
                injected.append((-1, cbytes, cts))

        # tail record to stash when this chunk breaks mid-record (skip when
        # the whole chunk was already re-stashed as the carried record)
        if ml_partial and lead_consumed < n:
            if len(last) and last[-1] == n - 1:
                f_, l_ = int(first[-1]), int(last[-1])
                first, last = first[:-1], last[:-1]
                lo = int(offs[f_])
                hi = int(offs[l_] + lens[l_])
                self._stash(key, bytes(arena[lo:hi].tobytes()),
                            int(tss[f_]), injected)
            else:
                # trailing contiguous unmatched run ending at the last line
                # continues an open record
                m = len(unmatched)
                rev_brk = np.nonzero(
                    unmatched[::-1] != (n - 1 - np.arange(m)))[0]
                run = int(rev_brk[0]) if len(rev_brk) else m
                run = min(run, n - lead_consumed)
                if run > 0:
                    tail_run = unmatched[m - run:]
                    unmatched = unmatched[:m - run]
                    lo = int(offs[tail_run[0]])
                    hi = int(offs[tail_run[-1]] + lens[tail_run[-1]])
                    self._stash(key, bytes(arena[lo:hi].tobytes()),
                                int(tss[tail_run[0]]), injected)

        kept = (unmatched[unmatched >= lead_consumed]
                if self.unmatched != "discard"
                else np.zeros(0, dtype=np.int64))
        # records, vectorised: blocks are [offs[first], offs[last]+lens[last])
        # spans (newlines included — contiguous arena slices), unmatched
        # lines are their own spans; `order` (the block's first line index)
        # restores input order
        rec_order = np.concatenate([first, kept])
        rec_off = np.concatenate([offs[first], offs[kept]])
        rec_len = np.concatenate(
            [offs[last] + lens[last] - offs[first], lens[kept]])
        rec_ts = (tss[rec_order] if tss is not None
                  else np.zeros(len(rec_order), dtype=np.int64))
        self._emit(group, rec_order, rec_off, rec_len, rec_ts, injected)

    def _stash(self, key, data: bytes, ts: int, injected) -> None:
        if len(data) > CARRY_CAP_BYTES:
            injected.append((1 << 30, data, ts))  # too big: emit as-is, last
            return
        with self._carry_lock:
            prev = self._carry.pop(key, None)
            self._carry[key] = (data, ts, time.monotonic())
        if prev is not None:
            # With multiple processor threads, chunks of one source can be
            # processed out of order: a concurrent worker stashed for this
            # key between our pop and this stash. Overwriting would LOSE
            # that open record — emit it standalone instead (degraded
            # stitching, zero loss).
            injected.append((-3, prev[0], prev[1]))

    # -- pipeline drain hooks (idle/shutdown delivery of held records) ------

    def _carry_group(self, key: str, data: bytes,
                     ts: int) -> PipelineEventGroup:
        from ..models import SourceBuffer
        sb = SourceBuffer(len(data) + 64)
        g = PipelineEventGroup(sb)
        view = sb.copy_string(data)
        g.set_columns(ColumnarLogs(
            offsets=np.array([view.offset], np.int32),
            lengths=np.array([len(data)], np.int32),
            timestamps=np.array([ts or int(time.time())], np.int64)))
        path, _, ino = key.rpartition(":")
        if path:
            g.set_metadata(EventGroupMetaKey.LOG_FILE_PATH, path)
        if ino:
            g.set_metadata(EventGroupMetaKey.LOG_FILE_INODE, ino)
        return g

    def flush_timeout_groups(self) -> List[PipelineEventGroup]:
        """Carried records whose continuation never arrived flush on the
        pipeline's timeout tick, so an idle source still delivers its last
        record (reference flush-timeout semantics)."""
        now = time.monotonic()
        expired: List[Tuple[str, bytes, int]] = []
        with self._carry_lock:
            for key in list(self._carry):
                data, ts, at = self._carry[key]
                if now - at >= CARRY_FLUSH_S:
                    del self._carry[key]
                    expired.append((key, data, ts))
        return [self._carry_group(k, d, t) for k, d, t in expired]

    def drain_groups(self) -> List[PipelineEventGroup]:
        """Shutdown: every held record ships (pipeline stop drain)."""
        with self._carry_lock:
            held = list(self._carry.items())
            self._carry.clear()
        return [self._carry_group(k, d, t) for k, (d, t, _) in held]

    def _emit(self, group, rec_order, rec_off, rec_len, rec_ts,
              injected) -> None:
        sb = group.source_buffer
        if injected:
            extra = []
            for order, data, ts in injected:
                view = sb.copy_string(data)
                extra.append((order, view.offset, len(data), ts))
            rec_order = np.concatenate(
                [rec_order, [r[0] for r in extra]])
            rec_off = np.concatenate([rec_off, [r[1] for r in extra]])
            rec_len = np.concatenate([rec_len, [r[2] for r in extra]])
            rec_ts = np.concatenate([rec_ts, [r[3] for r in extra]])
        idx = np.argsort(rec_order, kind="stable")
        group.set_columns(ColumnarLogs(
            offsets=rec_off[idx].astype(np.int32),
            lengths=rec_len[idx].astype(np.int32),
            timestamps=rec_ts[idx].astype(np.int64)))
