"""Inner processor: multiline log assembly (stacktrace merging) — columnar.

Reference: core/plugin/processor/inner/ProcessorSplitMultilineLogStringNative
.cpp with MultilineOptions (file_server/MultilineOptions.h:38-47):
start/continue/end regexes group physical lines into logical events;
UnmatchedContentTreatment = discard | single_line.

TPU-first: this is the framework's "long-context" problem (SURVEY.md §5.7).
With a StartPattern, line classification runs as ONE device match batch, and
because split lines are contiguous slices of the same arena, merging a block
of lines is pure span arithmetic — the merged event is the arena span from
the first line's offset to the last line's end, newlines included, zero-copy.
Continue/End patterns run the same batched classification with a host-side
block-boundary pass.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ..models import ColumnarLogs, PipelineEventGroup
from ..ops.regex.engine import RegexEngine, get_engine
from ..pipeline.plugin.interface import PluginContext, Processor


class ProcessorSplitMultilineLogString(Processor):
    name = "processor_split_multiline_log_string_native"

    def __init__(self) -> None:
        super().__init__()
        self.start: Optional[RegexEngine] = None
        self.cont: Optional[RegexEngine] = None
        self.end: Optional[RegexEngine] = None
        self.unmatched = "single_line"  # or "discard"

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        mcfg = config.get("Multiline", config)
        sp = mcfg.get("StartPattern")
        cp = mcfg.get("ContinuePattern")
        ep = mcfg.get("EndPattern")
        self.start = get_engine(self._fullmatchify(sp)) if sp else None
        self.cont = get_engine(self._fullmatchify(cp)) if cp else None
        self.end = get_engine(self._fullmatchify(ep)) if ep else None
        self.unmatched = mcfg.get("UnmatchedContentTreatment", "single_line")
        return self.start is not None or self.end is not None

    @staticmethod
    def _fullmatchify(pattern: str) -> str:
        """Reference multiline patterns are full-line matches; users commonly
        write prefixes ending in `.*` — keep as-is (engine is full-match)."""
        return pattern

    def process(self, group: PipelineEventGroup) -> None:
        cols = group.columns
        if cols is None or group._events:
            return  # expects the line-split columnar form
        n = len(cols)
        if n == 0:
            return
        arena = group.source_buffer.as_array()
        offs = cols.offsets.astype(np.int64)
        lens = cols.lengths

        is_start = (self.start.match_batch(arena, offs, lens)
                    if self.start else np.zeros(n, dtype=bool))
        is_end = (self.end.match_batch(arena, offs, lens)
                  if self.end else None)
        is_cont = (self.cont.match_batch(arena, offs, lens)
                   if self.cont else None)

        # block id per line
        if self.start is not None:
            block = np.cumsum(is_start)          # 0 for leading unmatched
            starts_idx = np.nonzero(is_start)[0]
            if is_end is not None:
                # start..end blocks: lines after an end and before next start
                # are unmatched
                blocks = []
                unmatched = []
                i = 0
                while i < n:
                    if is_start[i]:
                        j = i
                        while j < n and not is_end[j]:
                            j += 1
                        if j < n:
                            blocks.append((i, j))
                            i = j + 1
                        else:
                            blocks.append((i, n - 1))
                            i = n
                    else:
                        unmatched.append(i)
                        i += 1
                self._emit(group, cols, arena, blocks, unmatched)
                return
            if is_cont is not None:
                blocks = []
                unmatched = []
                i = 0
                while i < n:
                    if is_start[i]:
                        j = i
                        while j + 1 < n and is_cont[j + 1]:
                            j += 1
                        blocks.append((i, j))
                        i = j + 1
                    else:
                        unmatched.append(i)
                        i += 1
                self._emit(group, cols, arena, blocks, unmatched)
                return
            # start-only: vectorised — block k spans starts_idx[k] ..
            # (starts_idx[k+1] - 1); leading lines are unmatched
            if len(starts_idx) == 0:
                if self.unmatched == "discard":
                    group.set_columns(ColumnarLogs(
                        np.zeros(0, np.int32), np.zeros(0, np.int32)))
                return
            block_first = starts_idx
            block_last = np.concatenate([starts_idx[1:] - 1, [n - 1]])
            blocks = list(zip(block_first.tolist(), block_last.tolist()))
            unmatched = list(range(int(starts_idx[0])))
            self._emit(group, cols, arena, blocks, unmatched)
            return

        # end-only mode: block closes at each end-match
        blocks = []
        unmatched = []
        i = 0
        start_i = 0
        for i in range(n):
            if is_end[i]:
                blocks.append((start_i, i))
                start_i = i + 1
        for j in range(start_i, n):
            unmatched.append(j)
        self._emit(group, cols, arena, blocks, unmatched)

    def _emit(self, group, cols, arena, blocks, unmatched) -> None:
        offs = cols.offsets.astype(np.int64)
        lens = cols.lengths.astype(np.int64)
        tss = cols.timestamps
        records = []  # (first_idx, merged_off, merged_len)
        for first, last in blocks:
            mo = int(offs[first])
            ml = int(offs[last] + lens[last]) - mo
            records.append((first, mo, ml))
        if self.unmatched != "discard":
            for i in unmatched:
                records.append((i, int(offs[i]), int(lens[i])))
        records.sort(key=lambda r: r[0])
        out = ColumnarLogs(
            offsets=np.array([r[1] for r in records], dtype=np.int32),
            lengths=np.array([r[2] for r in records], dtype=np.int32),
            timestamps=np.array([tss[r[0]] for r in records], dtype=np.int64))
        group.set_columns(out)
