"""Inner processor: merge partial lines (container stdout continuation).

Reference: core/plugin/processor/inner/ProcessorMergeMultilineLogNative.cpp —
MergeType "regex" (same start/continue semantics as the splitter) or "flag"
(merge events marked partial by the container-log parser until one is final).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ..models import ColumnarLogs, PipelineEventGroup
from ..pipeline.plugin.interface import PluginContext, Processor
from .split_multiline import ProcessorSplitMultilineLogString

PARTIAL_FLAG_FIELD = "_partial_"


class ProcessorMergeMultilineLog(Processor):
    name = "processor_merge_multiline_log_native"
    supports_columnar = True
    requires_columnar = True

    def __init__(self) -> None:
        super().__init__()
        self.merge_type = "regex"
        self._regex_impl = ProcessorSplitMultilineLogString()

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.merge_type = config.get("MergeType", "regex")
        if self.merge_type == "regex":
            return self._regex_impl.init(config, context)
        return True

    def process(self, group: PipelineEventGroup) -> None:
        if self.merge_type == "regex":
            self._regex_impl.process(group)
            return
        # flag mode: merge consecutive partial events (columnar)
        cols = group.columns
        if cols is None or group._events:
            return
        flags = cols.fields.get(PARTIAL_FLAG_FIELD)
        if flags is None:
            return
        _, flag_lens = flags
        partial = flag_lens >= 0
        n = len(cols)
        offs = cols.offsets.astype(np.int64)
        lens = cols.lengths.astype(np.int64)
        sb = group.source_buffer
        arena = group.source_buffer.as_array()
        records = []
        i = 0
        while i < n:
            j = i
            while j < n and partial[j]:
                j += 1
            last = min(j, n - 1)
            if last == i:
                records.append((i, int(offs[i]), int(lens[i])))
            else:
                # copy-concatenate the partial pieces (they are separated by
                # CRI prefixes in the arena, so span arithmetic cannot apply)
                parts = [arena[int(offs[k]): int(offs[k] + lens[k])].tobytes()
                         for k in range(i, last + 1)]
                view = sb.copy_string(b"".join(parts))
                records.append((i, view.offset, view.length))
            i = last + 1
        out = ColumnarLogs(
            offsets=np.array([r[1] for r in records], dtype=np.int32),
            lengths=np.array([r[2] for r in records], dtype=np.int32),
            timestamps=np.array([cols.timestamps[r[0]] for r in records],
                                dtype=np.int64))
        for name, (foffs, flens) in cols.fields.items():
            if name == PARTIAL_FLAG_FIELD:
                continue
            out.set_field(name,
                          np.array([foffs[r[0]] for r in records], np.int32),
                          np.array([flens[r[0]] for r in records], np.int32))
        group.set_columns(out)
