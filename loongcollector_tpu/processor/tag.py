"""Inner processor: host/agent tags → group tags with rename policies.

Reference: core/plugin/processor/inner/ProcessorTagNative.cpp — appends
host name/ip and agent tags to every group; PipelineMetaTagKey rename/
delete policies.
"""

from __future__ import annotations

import socket
from typing import Any, Dict

from ..models import PipelineEventGroup
from ..pipeline.plugin.interface import PluginContext, Processor

_DEFAULT_KEYS = {
    "HOST_NAME": "host.name",
    "HOST_IP": "host.ip",
}


class ProcessorTag(Processor):
    name = "processor_tag_native"
    supports_columnar = True

    def __init__(self) -> None:
        super().__init__()
        self.pipeline_meta_tag_key: Dict[str, str] = {}
        self.agent_tags: Dict[str, str] = {}
        self._host_name = socket.gethostname()
        try:
            self._host_ip = socket.gethostbyname(self._host_name)
        except OSError:
            self._host_ip = "127.0.0.1"

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.pipeline_meta_tag_key = dict(config.get("PipelineMetaTagKey", {}))
        self.agent_tags = dict(config.get("AgentEnvMetaTagKey", {}))
        return True

    def _tag_name(self, key: str) -> str:
        policy = self.pipeline_meta_tag_key.get(key, "__default__")
        if policy == "__default__":
            return _DEFAULT_KEYS.get(key, key.lower())
        return policy  # empty string ⇒ delete

    def process(self, group: PipelineEventGroup) -> None:
        name = self._tag_name("HOST_NAME")
        if name:
            group.set_tag(name, self._host_name)
        name = self._tag_name("HOST_IP")
        if name:
            group.set_tag(name, self._host_ip)
        for k, v in self.agent_tags.items():
            group.set_tag(k, v)
