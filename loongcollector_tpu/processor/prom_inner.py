"""Prometheus inner processors.

Reference: core/plugin/processor/inner/ProcessorPromParseMetricNative.cpp
(raw exposition lines → MetricEvents, one per sample) and
ProcessorPromRelabelMetricNative.cpp (metric_relabel_configs applied to
sample labels inside the pipeline, then the __-prefixed meta labels are
scrubbed before the flusher sees the group).

These exist so prometheus data can ride ORDINARY pipelines: a forwarder or
file input can carry exposition text and still get the scraper's parse +
relabel semantics.
"""

from __future__ import annotations

from typing import Any, Dict, List

from ..input.prometheus.relabel import RelabelConfigList
from ..input.prometheus.text_parser import parse_exposition
from ..models import LogEvent, MetricEvent, PipelineEventGroup, RawEvent
from ..pipeline.plugin.interface import PluginContext, Processor


class ProcessorPromParseMetric(Processor):
    """Exposition text (raw events / log `content`) → MetricEvents."""

    name = "processor_prom_parse_metric_native"

    def __init__(self) -> None:
        super().__init__()
        self.source_key = b"content"

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.source_key = config.get("SourceKey", "content").encode()
        return True

    def process(self, group: PipelineEventGroup) -> None:
        chunks: List[bytes] = []
        cols = group.columns
        columnar = cols is not None and not group._events
        if columnar:
            arena = group.source_buffer.as_array()
            for i in range(len(cols)):
                o, ln = int(cols.offsets[i]), int(cols.lengths[i])
                if ln > 0:
                    chunks.append(bytes(arena[o:o + ln].tobytes()))
        else:
            for ev in group.events:
                if isinstance(ev, RawEvent) and ev.content is not None:
                    chunks.append(ev.content.to_bytes())
                elif isinstance(ev, LogEvent):
                    v = ev.get_content(self.source_key)
                    if v is not None:
                        chunks.append(v.to_bytes())
        if not chunks:
            return    # nothing extractable: leave the group untouched
        # consume the source representation only once there is text to parse
        if columnar:
            group._columns = None
        else:
            group._events = []
        parse_exposition(b"\n".join(chunks), group=group)


class ProcessorPromRelabelMetric(Processor):
    """metric_relabel_configs inside the pipeline + meta-label scrub."""

    name = "processor_prom_relabel_metric_native"

    def __init__(self) -> None:
        super().__init__()
        self.relabel = RelabelConfigList([])
        self.keep_meta = False

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.relabel = RelabelConfigList(
            config.get("MetricRelabelConfigs",
                       config.get("metric_relabel_configs", [])))
        self.keep_meta = bool(config.get("KeepMetaLabels", False))
        return True

    def process(self, group: PipelineEventGroup) -> None:
        kept = []
        sb = group.source_buffer
        for ev in group.events:
            if not isinstance(ev, MetricEvent):
                kept.append(ev)
                continue
            labels = {k.decode("utf-8", "replace"): str(v)
                      for k, v in ev.tags.items()}
            if ev.name is not None:
                labels.setdefault("__name__", ev.name.to_str())
            out = self.relabel.process(labels)
            if out is None:
                continue       # sample dropped by keep/drop/dropmetric
            new_name = out.pop("__name__", None)
            if new_name is not None and (
                    ev.name is None or new_name != ev.name.to_str()):
                ev.set_name(sb.copy_string(new_name))
            if not self.keep_meta:
                # __-prefixed meta labels never reach the sink (reference
                # ProcessorPromRelabelMetricNative meta scrub)
                out = {k: v for k, v in out.items()
                       if not k.startswith("__")}
            ev.tags.clear()
            for k, v in out.items():
                ev.set_tag(sb.copy_string(k), sb.copy_string(v))
            kept.append(ev)
        group._events = kept
