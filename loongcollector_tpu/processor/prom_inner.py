"""Prometheus inner processors.

Reference: core/plugin/processor/inner/ProcessorPromParseMetricNative.cpp
(raw exposition lines → MetricEvents, one per sample) and
ProcessorPromRelabelMetricNative.cpp (metric_relabel_configs applied to
sample labels inside the pipeline, then the __-prefixed meta labels are
scrubbed before the flusher sees the group).

These exist so prometheus data can ride ORDINARY pipelines: a forwarder or
file input can carry exposition text and still get the scraper's parse +
relabel semantics.
"""

from __future__ import annotations

from typing import Any, Dict, List

from ..input.prometheus.relabel import (RelabelConfigList,
                                        relabel_metric_event)
from ..input.prometheus.text_parser import parse_exposition
from ..models import LogEvent, MetricEvent, PipelineEventGroup, RawEvent
from ..pipeline.plugin.interface import PluginContext, Processor


class ProcessorPromParseMetric(Processor):
    """Exposition text (raw events / log `content`) → MetricEvents."""

    name = "processor_prom_parse_metric_native"
    supports_columnar = True

    def __init__(self) -> None:
        super().__init__()
        self.source_key = b"content"

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.source_key = config.get("SourceKey", "content").encode()
        return True

    def process(self, group: PipelineEventGroup) -> None:
        chunks: List[bytes] = []
        cols = group.columns
        columnar = cols is not None and not group._events
        keep = []
        if columnar:
            arena = group.source_buffer.as_array()
            for i in range(len(cols)):
                o, ln = int(cols.offsets[i]), int(cols.lengths[i])
                if ln > 0:
                    chunks.append(bytes(arena[o:o + ln].tobytes()))
        else:
            for ev in group.events:
                if isinstance(ev, RawEvent) and ev.content is not None:
                    chunks.append(ev.content.to_bytes())
                elif isinstance(ev, LogEvent) and \
                        (v := ev.get_content(self.source_key)) is not None:
                    chunks.append(v.to_bytes())
                else:
                    keep.append(ev)   # contributed nothing: pass through
        if not chunks:
            return    # nothing extractable: leave the group untouched
        # consume only the events that became exposition text
        if columnar:
            group._columns = None
        else:
            group._events = keep
        parse_exposition(b"\n".join(chunks), group=group)


class ProcessorPromRelabelMetric(Processor):
    """metric_relabel_configs inside the pipeline + meta-label scrub."""

    name = "processor_prom_relabel_metric_native"
    supports_columnar = True

    def __init__(self) -> None:
        super().__init__()
        self.relabel = RelabelConfigList([])
        self.keep_meta = False

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.relabel = RelabelConfigList(
            config.get("MetricRelabelConfigs",
                       config.get("metric_relabel_configs", [])))
        self.keep_meta = bool(config.get("KeepMetaLabels", False))
        return True

    def process(self, group: PipelineEventGroup) -> None:
        kept = []
        sb = group.source_buffer
        for ev in group.events:
            if not isinstance(ev, MetricEvent):
                kept.append(ev)
                continue
            # __-prefixed meta labels never reach the sink (reference
            # ProcessorPromRelabelMetricNative meta scrub)
            if relabel_metric_event(ev, sb, self.relabel,
                                    scrub_meta=not self.keep_meta):
                kept.append(ev)
        group._events = kept
