"""processor_parse_regex — regex field extraction on TPU.

Reference: core/plugin/processor/ProcessorParseRegexNative.cpp — full-match
with capture groups → fields (SetContentNoCopy spans, :249-251); whole-line
fast path when the pattern is `(.*)` (:147-148); keep/discard semantics from
CommonParserOptions (:153-165): KeepingSourceWhenParseFail (default true ⇒
failed events keep the raw line under `rawLog`), KeepingSourceWhenParseSucceed,
RenamedSourceKey.

TPU redesign: the whole group parses as ONE device batch through
ops.regex.RegexEngine (Tier-1 segment kernel / DFA / CPU fallback chosen per
pattern); returned spans index the group's own arena, so downstream
serialization stays zero-copy.  Events whose parse fails keep their source
span — semantics identical to the reference, enforced by differential tests.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ..models import ColumnarLogs, PipelineEventGroup
from ..ops.regex.engine import RegexEngine, get_engine
from ..pipeline.plugin.interface import PluginContext, Processor
from .common import (RAW_LOG_KEY, apply_parse_spans,
                     extract_source, finish_row_keep)


class ProcessorParseRegex(Processor):
    name = "processor_parse_regex_tpu"
    supports_columnar = True

    def __init__(self) -> None:
        super().__init__()
        self.source_key = b"content"
        self.regex = ""
        self.keys: List[str] = []
        self.keep_source_on_fail = True
        self.keep_source_on_success = False
        self.renamed_source_key = RAW_LOG_KEY
        self.engine: RegexEngine = None  # type: ignore
        self.discard_unmatch = False

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.source_key = config.get("SourceKey", "content").encode()
        self.regex = config.get("Regex", "(.*)")
        self.keys = list(config.get("Keys", []))
        self.keep_source_on_fail = bool(
            config.get("KeepingSourceWhenParseFail", True))
        self.keep_source_on_success = bool(
            config.get("KeepingSourceWhenParseSucceed", False))
        self.renamed_source_key = config.get("RenamedSourceKey", RAW_LOG_KEY)
        self.discard_unmatch = not self.keep_source_on_fail
        self.engine = get_engine(self.regex)
        # name capture groups: config Keys win; else named groups; else g{N}
        if not self.keys:
            self.keys = [self.engine.group_names.get(i, f"g{i+1}")
                         for i in range(self.engine.num_caps)]
        return True

    supports_async_dispatch = True

    def fused_stage_spec(self, ctx):
        """loongresident: SEGMENT-tier extraction joins a fused pipeline
        program as an ``extract`` stage — one packed source column in,
        capture spans out, device-resident for any later member (a filter
        condition on a parsed key binds those spans without a host
        bounce).  Parsed keys register as capture columns; the consumed
        source key is retired from the run's static bindings exactly as
        ``apply_parse_spans`` retires it at apply time."""
        from ..ops.regex.program import PatternTier
        eng = self.engine
        if eng is None or eng.tier is not PatternTier.SEGMENT \
                or eng._segment_kernel is None:
            return None
        if not ctx.bind_source(self.source_key):
            return None
        from ..ops import fused_pipeline as fp
        from ..pipeline.fused_chain import FusedMemberStage
        spec = fp.StageSpec("extract", eng._segment_kernel.program,
                            ["extract", eng.pattern],
                            staged=eng._segment_kernel,
                            label=f"extract:{self.name}")
        ctx.note_fields(ctx.n_stages, self.keys[:eng.num_caps])
        ctx.note_consumed(self.source_key)
        return FusedMemberStage(spec, self._fused_apply)

    def _fused_apply(self, group, src, out, rowmap):
        from ..ops.regex.engine import BatchParseResult
        from .common import subset_source
        ok, off, ln = out
        self._apply(group, subset_source(src, rowmap),
                    BatchParseResult(ok[rowmap], off[rowmap], ln[rowmap]))
        return rowmap

    def process_dispatch(self, group: PipelineEventGroup):
        """Async device plane: dispatch the group's parse and return the
        pending handle; the device executes while the runner works on
        neighbouring groups (process_complete applies the spans).  A parse
        that completed synchronously (host-walker route) is applied here —
        deferring it buys no overlap and would only delay the send."""
        src = extract_source(group, self.source_key)
        if src is None:
            return None
        pending = self.engine.parse_batch_async(
            src.arena, src.offsets, src.lengths)
        if pending.done:
            self._apply(group, src, pending.result())
            return None
        return src, pending

    def process_complete(self, group: PipelineEventGroup, token) -> None:
        if token is None:
            return
        src, pending = token
        self._apply(group, src, pending.result())

    def process(self, group: PipelineEventGroup) -> None:
        self.process_complete(group, self.process_dispatch(group))

    def _apply(self, group: PipelineEventGroup, src, res) -> None:
        ok = res.ok & src.present

        if src.columnar:
            apply_parse_spans(group, src, res, self.keys,
                              self.keep_source_on_fail,
                              self.keep_source_on_success,
                              self.renamed_source_key,
                              source_key=self.source_key)
            return

        # row path (non-columnar groups) — reference ordering
        # (ProcessorParseRegexNative.cpp ProcessEvent): capture the raw
        # source FIRST (a key may overwrite it), delete the source unless a
        # successful parse overwrote it, then re-add under the renamed key
        # per the keep flags
        sb = group.source_buffer
        key_bytes = [k.encode() for k in self.keys]
        renamed = self.renamed_source_key.encode()
        for i, ev in enumerate(group.events):
            if not hasattr(ev, "get_content"):
                continue  # RawEvent/metric/span rows don't carry fields
            raw = ev.get_content(self.source_key)
            overwritten = False
            if ok[i]:
                for g in range(min(self.engine.num_caps, len(self.keys))):
                    ln = int(res.cap_len[i, g])
                    if ln >= 0:
                        o = int(res.cap_off[i, g])
                        data = bytes(src.arena[o : o + ln].tobytes())
                        ev.set_content(key_bytes[g], sb.copy_string(data))
                        if key_bytes[g] == self.source_key:
                            overwritten = True
            finish_row_keep(ev, raw, bool(ok[i]), self.source_key,
                            overwritten, self.keep_source_on_fail,
                            self.keep_source_on_success, renamed)
