"""processor_spl — pipeline query language over event groups.

Reference: core/plugin/processor/ProcessorSPL.cpp bridges the (closed) SLS
SPL engine; this framework implements the practically-used core of the
language natively, columnar-first:

    * | where level = 'ERROR'
      | where msg matches 'timeout.*'        (device regex tier)
      | where latency > 100
      | parse content with regex '(?P<ip>\\S+) .*'
      | extend combo = concat(host, ':', level)
      | rename old as new
      | project a, b, c          /  project-away x, y
      | limit 100

Stages execute left to right on the whole group; `where matches` runs the
tiered device engine; `parse with regex` is the Tier-1 extraction kernel.
Unsupported constructs fail init (surfaced at config load), never silently.
"""

from __future__ import annotations

import math
import re
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..models import ColumnarLogs, PipelineEventGroup
from ..ops.regex.engine import RegexEngine, get_engine
from ..pipeline.plugin.interface import PluginContext, Processor
from .common import extract_source
from .filter import compact_columns


class SPLError(Exception):
    pass


_WHERE_RE = re.compile(
    r"where\s+(\w+)\s*(>=|<=|!=|=|>|<|contains|matches)\s*(.+)", re.S)
_PARSE_RE = re.compile(r"parse\s+(\w+)\s+with\s+regex\s+(.+)", re.S)
_EXTEND_RE = re.compile(r"extend\s+(\w+)\s*=\s*(.+)", re.S)
_RENAME_RE = re.compile(r"rename\s+(\w+)\s+as\s+(\w+)")
_PROJECT_RE = re.compile(r"project(-away)?\s+(.+)")
_LIMIT_RE = re.compile(r"limit\s+(\d+)")
_STATS_RE = re.compile(r"stats\s+(.+?)(?:\s+by\s+([\w,\s]+))?\s*$", re.S)
_SORT_RE = re.compile(r"sort\s+by\s+(.+)", re.S)
_JOIN_RE = re.compile(
    r"join\s+(?:type\s*=\s*(inner|left)\s+)?"
    r"file\(\s*(['\"][^'\"]+['\"])\s*\)\s+on\s+(\w+)", re.S)


def _split_quote_aware(text: str, sep: str) -> List[str]:
    """Split on sep outside single/double-quoted spans (quotes may contain
    the separator — regex alternation pipes, literal commas)."""
    out: List[str] = []
    cur: List[str] = []
    quote = ""
    i = 0
    while i < len(text):
        c = text[i]
        if quote:
            cur.append(c)
            if c == quote:
                quote = ""
        elif c in "'\"":
            quote = c
            cur.append(c)
        elif c == sep:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(c)
        i += 1
    out.append("".join(cur))
    return out


def _unquote(v: str) -> str:
    v = v.strip()
    if len(v) >= 2 and v[0] == v[-1] and v[0] in "'\"":
        return v[1:-1]
    return v


class _Stage:
    def apply(self, group: PipelineEventGroup) -> None:  # pragma: no cover
        raise NotImplementedError


class _Where(_Stage):
    def __init__(self, field: str, op: str, value: str):
        self.field = field.encode()
        self.op = op
        self.value = _unquote(value)
        self.engine: Optional[RegexEngine] = None
        if op == "matches":
            self.engine = get_engine(self.value)
        self.num: Optional[float] = None
        if op in (">", ">=", "<", "<="):
            try:
                self.num = float(self.value)
            except ValueError:
                raise SPLError(f"numeric comparison with non-number "
                               f"{self.value!r}")

    def apply(self, group: PipelineEventGroup) -> None:
        src = extract_source(group, self.field)
        n = len(group)
        if src is None:
            keep = np.zeros(n, dtype=bool)
        elif self.op == "matches":
            keep = self.engine.match_batch(src.arena, src.offsets,
                                           src.lengths) & src.present
        else:
            keep = np.zeros(n, dtype=bool)
            want = self.value.encode()
            raw = src.arena
            for i in range(n):
                if not src.present[i]:
                    continue
                o, ln = int(src.offsets[i]), int(src.lengths[i])
                val = raw[o : o + ln].tobytes()
                if self.op == "=":
                    keep[i] = val == want
                elif self.op == "!=":
                    keep[i] = val != want
                elif self.op == "contains":
                    keep[i] = want in val
                else:
                    try:
                        x = float(val)
                    except ValueError:
                        continue
                    keep[i] = ((self.op == ">" and x > self.num)
                               or (self.op == ">=" and x >= self.num)
                               or (self.op == "<" and x < self.num)
                               or (self.op == "<=" and x <= self.num))
        _apply_keep(group, keep)


class _Parse(_Stage):
    def __init__(self, field: str, pattern: str):
        self.field = field
        self.engine = get_engine(_unquote(pattern))
        if not self.engine.group_names:
            raise SPLError("parse regex needs named groups (?P<name>...)")

    def apply(self, group: PipelineEventGroup) -> None:
        src = extract_source(group, self.field.encode())
        if src is None:
            return
        res = self.engine.parse_batch(src.arena, src.offsets, src.lengths)
        cols = group.columns
        ok = res.ok & src.present
        for g in range(self.engine.num_caps):
            name = self.engine.group_names.get(g)
            if not name:
                continue
            lens = np.where(ok, res.cap_len[:, g], -1).astype(np.int32)
            if cols is not None and not group._events:
                cols.set_field(name, res.cap_off[:, g], lens)
            else:
                sb = group.source_buffer
                for i, ev in enumerate(group.events):
                    if lens[i] >= 0 and hasattr(ev, "get_content"):
                        o = int(res.cap_off[i, g])
                        ev.set_content(name.encode(), sb.copy_string(
                            bytes(src.arena[o : o + lens[i]].tobytes())))


# ---------------------------------------------------------------------------
# extend expression language: nested function calls over row fields.
# Node = ('lit', bytes) | ('field', name) | ('call', fname, [args])
# ---------------------------------------------------------------------------

def _split_args(src: str) -> List[str]:
    """Split call arguments on top-level commas (quote- AND paren-aware —
    nested calls like round(div(a, b), 2) must not split inside div)."""
    out = []
    depth = 0
    quote = None
    start = 0
    for i, ch in enumerate(src):
        if quote:
            if ch == quote:
                quote = None
        elif ch in "'\"":
            quote = ch
        elif ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        elif ch == "," and depth == 0:
            out.append(src[start:i])
            start = i + 1
    out.append(src[start:])
    return out


def _parse_expr(src: str):
    src = src.strip()
    if src and src[0] in "'\"":
        return ("lit", _unquote(src).encode())
    m = re.fullmatch(r"([A-Za-z_][\w]*)\((.*)\)", src, re.S)
    if m:
        fname = m.group(1).lower()
        inner = m.group(2).strip()
        if fname == "if":
            # first-class node so if() nests anywhere and validates at
            # compile time like every other function
            args = _split_args(inner)
            if len(args) != 3:
                raise SPLError("if() takes (cond, then, else)")
            cm = _CMP_RE.search(args[0])
            if not cm:
                raise SPLError(f"if() needs a comparison: {args[0]!r}")
            return ("if", _parse_expr(args[0][: cm.start()]), cm.group(1),
                    _parse_expr(args[0][cm.end():]), _parse_expr(args[1]),
                    _parse_expr(args[2]))
        args = ([_parse_expr(a) for a in _split_args(inner)]
                if inner else [])
        return ("call", fname, args)
    if re.fullmatch(r"-?\d+(\.\d+)?", src):
        return ("lit", src.encode())
    return ("field", src)


def _b2f(v: bytes) -> float:
    try:
        return float(v)
    except ValueError:
        return 0.0


_CMP_RE = re.compile(r"(==|!=|>=|<=|>|<)")


def _eval_expr(node, fields: Dict[str, bytes]) -> bytes:
    kind = node[0]
    if kind == "lit":
        return node[1]
    if kind == "field":
        return fields.get(node[1], b"")
    if kind == "if":
        _, lhs, op, rhs, then, other = node
        lv = _eval_expr(lhs, fields)
        rv = _eval_expr(rhs, fields)
        ln, rn = _num(lv), _num(rv)
        if ln is not None and rn is not None:
            lv, rv = ln, rn            # numeric compare when both parse
        ok = {"==": lv == rv, "!=": lv != rv, ">": lv > rv,
              "<": lv < rv, ">=": lv >= rv, "<=": lv <= rv}[op]
        return _eval_expr(then if ok else other, fields)
    fname, args = node[1], node[2]
    a = [_eval_expr(x, fields) for x in args]
    # string functions (SLS SPL vocabulary)
    if fname == "concat":
        return b"".join(a)
    if fname == "upper":
        return a[0].upper()
    if fname == "lower":
        return a[0].lower()
    if fname == "trim":
        return a[0].strip()
    if fname == "ltrim":
        return a[0].lstrip()
    if fname == "rtrim":
        return a[0].rstrip()
    if fname == "length":
        return str(len(a[0])).encode()
    if fname == "reverse":
        return a[0][::-1]
    if fname == "substring":
        start = int(_b2f(a[1]))
        n = int(_b2f(a[2])) if len(a) > 2 else len(a[0])
        return a[0][start:start + n]
    if fname == "replace":
        return a[0].replace(a[1], a[2])
    if fname == "split_part":
        parts = a[0].split(a[1])
        idx = int(_b2f(a[2])) - 1          # SPL split_part is 1-based
        return parts[idx] if 0 <= idx < len(parts) else b""
    if fname == "md5":
        import hashlib as _h
        return _h.md5(a[0]).hexdigest().encode()
    if fname == "url_encode":
        from urllib.parse import quote
        return quote(a[0].decode("utf-8", "replace")).encode()
    if fname == "url_decode":
        from urllib.parse import unquote
        return unquote(a[0].decode("utf-8", "replace")).encode()
    if fname == "json_extract":
        import json as _json
        try:
            doc = _json.loads(a[0])
            for part in a[1].decode().strip("$.").split("."):
                if part:
                    doc = doc[int(part)] if isinstance(doc, list) else \
                        doc[part]
            if isinstance(doc, (dict, list)):
                return _json.dumps(doc, separators=(",", ":")).encode()
            return str(doc).encode()
        except (ValueError, KeyError, IndexError, TypeError):
            return b""
    if fname == "coalesce":
        for v in a:
            if v:
                return v
        return b""
    # math
    if fname in ("add", "sub", "mul", "div", "mod", "pow"):
        x, y = _b2f(a[0]), _b2f(a[1])
        try:
            val = {"add": x + y, "sub": x - y, "mul": x * y,
                   "div": x / y if y else 0.0,
                   "mod": x % y if y else 0.0, "pow": x ** y}[fname]
        except (OverflowError, ValueError):
            val = 0.0
        return _fmt(val)
    if fname == "round":
        nd = int(_b2f(a[1])) if len(a) > 1 else 0
        return _fmt(round(_b2f(a[0]), nd))
    if fname == "abs":
        return _fmt(abs(_b2f(a[0])))
    if fname == "floor":
        import math as _m
        return _fmt(_m.floor(_b2f(a[0])))
    if fname == "ceil":
        import math as _m
        return _fmt(_m.ceil(_b2f(a[0])))
    # time
    if fname == "now":
        import time as _t
        return str(int(_t.time())).encode()
    if fname == "from_unixtime":
        import time as _t
        fmt = (a[1].decode("utf-8", "replace") if len(a) > 1
               else "%Y-%m-%d %H:%M:%S")
        try:
            return _t.strftime(fmt, _t.gmtime(_b2f(a[0]))).encode()
        except (ValueError, OverflowError):
            return b""
    raise SPLError(f"unknown SPL function {fname!r}")


class _Extend(_Stage):
    """extend dst = <expr> — nested function calls (concat/upper/substring/
    replace/split_part/md5/json_extract/add/round/if/from_unixtime/...),
    field refs and literals."""

    def __init__(self, dst: str, expr: str):
        self.dst = dst
        self.node = _parse_expr(expr.strip())
        # validate function names at compile time on an empty row;
        # data-dependent runtime errors (empty separators etc.) are
        # not compile errors
        try:
            _eval_expr(self.node, {})
        except SPLError:
            raise
        except Exception:  # noqa: BLE001
            pass

    def _value(self, fields: Dict[str, bytes]) -> bytes:
        return _eval_expr(self.node, fields)

    def apply(self, group: PipelineEventGroup) -> None:
        sb = group.source_buffer
        cols = group.columns
        if cols is not None and not group._events:
            rows = _row_fields(group)
            n = len(rows)
            offs = np.zeros(n, dtype=np.int32)
            lens = np.full(n, -1, dtype=np.int32)
            for i, fields in enumerate(rows):
                view = sb.copy_string(self._value(fields))
                offs[i] = view.offset
                lens[i] = view.length
            cols.set_field(self.dst, offs, lens)
            return
        for ev in group.events:
            if not hasattr(ev, "contents"):
                continue
            fields = {k.to_str(): v.to_bytes() for k, v in ev.contents}
            ev.set_content(self.dst.encode(),
                           sb.copy_string(self._value(fields)))


class _Rename(_Stage):
    def __init__(self, old: str, new: str):
        self.old, self.new = old, new

    def apply(self, group: PipelineEventGroup) -> None:
        cols = group.columns
        if cols is not None and not group._events:
            if self.old in cols.fields:
                cols.fields[self.new] = cols.fields.pop(self.old)
            return
        for ev in group.events:
            if hasattr(ev, "get_content"):
                v = ev.get_content(self.old.encode())
                if v is not None:
                    ev.set_content(self.new.encode(), v)
                    ev.del_content(self.old.encode())


class _Project(_Stage):
    def __init__(self, fields: List[str], away: bool):
        self.fields = fields
        self.away = away

    def apply(self, group: PipelineEventGroup) -> None:
        cols = group.columns
        if cols is not None and not group._events:
            if self.away:
                for f in self.fields:
                    cols.fields.pop(f, None)
            else:
                cols.fields = {k: v for k, v in cols.fields.items()
                               if k in self.fields}
                if "content" not in self.fields:
                    cols.content_consumed = True
            return
        keep = set(self.fields)
        for ev in group.events:
            if not hasattr(ev, "contents"):
                continue
            names = [k.to_bytes() for k, _ in ev.contents]
            for name in names:
                present = name.decode("utf-8", "replace") in keep
                if self.away == present:
                    ev.del_content(name)


def _row_fields(group: PipelineEventGroup) -> List[Dict[str, bytes]]:
    """Per-event field dicts (shared by the aggregation verbs)."""
    cols = group.columns
    rows: List[Dict[str, bytes]] = []
    if cols is not None and not group._events:
        raw = group.source_buffer.as_array()
        n = len(cols)
        for i in range(n):
            fields: Dict[str, bytes] = {}
            for name, (fo, fl) in cols.fields.items():
                if fl[i] >= 0:
                    o = int(fo[i])
                    fields[name] = raw[o:o + int(fl[i])].tobytes()
            if not cols.content_consumed:
                o, ln = int(cols.offsets[i]), int(cols.lengths[i])
                fields.setdefault("content", raw[o:o + ln].tobytes())
            rows.append(fields)
        return rows
    for ev in group.events:
        if hasattr(ev, "contents"):
            rows.append({k.to_str(): v.to_bytes() for k, v in ev.contents})
        else:
            rows.append({})
    return rows


def _num(v: Optional[bytes]) -> Optional[float]:
    if v is None:
        return None
    try:
        x = float(v)
    except ValueError:
        return None
    # 'nan' poisons sorted() ordering and min/max; 'inf' breaks formatting
    return x if math.isfinite(x) else None


def _fmt(x: float) -> bytes:
    return (b"%d" % int(x)) if float(x).is_integer() else (
        repr(x).encode())


class _Stats(_Stage):
    """stats count(), sum(f), avg(f), min(f), max(f) [as alias], ...
    [by k1, k2] — the aggregation verbs the reference SPL engine exposes
    (ProcessorSPL.cpp:69-80); replaces the group's events with one event
    per key combination."""

    def __init__(self, aggs_src: str, by_src: Optional[str]):
        self.aggs: List[Tuple[str, Optional[str], str]] = []  # (fn, field, out)
        for part in _split_quote_aware(aggs_src, ","):
            part = part.strip()
            m = re.fullmatch(
                r"(count|sum|avg|min|max)\s*\(\s*(\w*)\s*\)"
                r"(?:\s+as\s+(\w+))?", part)
            if not m:
                raise SPLError(f"bad stats aggregate: {part!r}")
            fn, fieldname, alias = m.group(1), m.group(2) or None, m.group(3)
            if fn != "count" and not fieldname:
                raise SPLError(f"{fn}() needs a field")
            out = alias or (fn if fn == "count" and not fieldname
                            else f"{fn}_{fieldname}" if fieldname else fn)
            self.aggs.append((fn, fieldname, out))
        self.by = [k.strip() for k in (by_src or "").split(",") if k.strip()]

    def apply(self, group: PipelineEventGroup) -> None:
        rows = _row_fields(group)
        cols = group.columns
        tss = (cols.timestamps if cols is not None and not group._events
               else np.array([getattr(ev, "timestamp", 0)
                              for ev in group.events], dtype=np.int64))
        buckets: Dict[Tuple, Dict] = {}
        for i, fields in enumerate(rows):
            key = tuple(fields.get(k, b"") for k in self.by)
            b = buckets.get(key)
            if b is None:
                b = buckets[key] = {"n": 0, "vals": {}, "ts": 0}
            b["n"] += 1
            b["ts"] = max(b["ts"], int(tss[i]) if i < len(tss) else 0)
            for fn, fieldname, out in self.aggs:
                if fn == "count":
                    # count(field) counts rows where the field is present
                    # (SQL semantics); bare count() counts all rows
                    if fieldname:
                        b["vals"].setdefault(out, []).append(
                            1.0 if fieldname in fields else 0.0)
                    continue
                v = _num(fields.get(fieldname))
                if v is None:
                    continue
                acc = b["vals"].setdefault(out, [])
                acc.append(v)
        out_rows: List[Tuple[int, Dict[str, bytes]]] = []
        for key, b in buckets.items():
            fields: Dict[str, bytes] = {}
            for k, v in zip(self.by, key):
                fields[k] = v
            for fn, fieldname, out in self.aggs:
                if fn == "count":
                    if fieldname:
                        fields[out] = b"%d" % int(sum(b["vals"].get(out, [])))
                    else:
                        fields[out] = b"%d" % b["n"]
                    continue
                acc = b["vals"].get(out, [])
                if not acc:
                    fields[out] = b""
                elif fn == "sum":
                    fields[out] = _fmt(sum(acc))
                elif fn == "avg":
                    fields[out] = _fmt(sum(acc) / len(acc))
                elif fn == "min":
                    fields[out] = _fmt(min(acc))
                elif fn == "max":
                    fields[out] = _fmt(max(acc))
            out_rows.append((b["ts"], fields))
        self._rebuild(group, out_rows)

    @staticmethod
    def _rebuild(group: PipelineEventGroup,
                 out_rows: List[Tuple[int, Dict[str, bytes]]]) -> None:
        sb = group.source_buffer
        if group.columns is not None and not group._events:
            n = len(out_rows)
            new = ColumnarLogs(np.zeros(n, np.int32), np.zeros(n, np.int32),
                               np.array([r[0] for r in out_rows], np.int64))
            new.content_consumed = True
            names: List[str] = []
            for _, fields in out_rows:
                for name in fields:
                    if name not in names:
                        names.append(name)
            for name in names:
                offs = np.zeros(n, np.int32)
                lens = np.full(n, -1, np.int32)
                for i, (_, fields) in enumerate(out_rows):
                    v = fields.get(name)
                    if v is not None:
                        view = sb.copy_string(v)
                        offs[i], lens[i] = view.offset, view.length
                new.set_field(name, offs, lens)
            group.set_columns(new)
            return
        group._events = []
        group._columns = None   # stale pre-stats columns must not survive
        for ts, fields in out_rows:
            ev = group.add_log_event(ts)
            for k, v in fields.items():
                ev.set_content(sb.copy_string(k.encode()), sb.copy_string(v))


class _Sort(_Stage):
    """sort by f1 [desc], f2, ... — numeric when every value parses as a
    number, else bytewise; stable across keys (right-to-left passes)."""

    def __init__(self, keys_src: str):
        self.keys: List[Tuple[str, bool]] = []
        for part in keys_src.split(","):
            part = part.strip()
            desc = False
            if part.startswith("-"):
                desc, part = True, part[1:].strip()
            m = re.fullmatch(r"(\w+)(?:\s+(asc|desc))?", part)
            if not m:
                raise SPLError(f"bad sort key: {part!r}")
            self.keys.append((m.group(1), desc or m.group(2) == "desc"))

    def apply(self, group: PipelineEventGroup) -> None:
        n = len(group)
        if n <= 1:
            return
        cols = group.columns
        columnar = cols is not None and not group._events
        if columnar:
            # extract ONLY the key columns — materialising every field of
            # every row just to sort defeats the columnar layout
            raw = group.source_buffer.as_array()

            def get_col(name):
                spans = cols.fields.get(name)
                if spans is None and name == "content" \
                        and not cols.content_consumed:
                    spans = (cols.offsets, cols.lengths)
                if spans is None:
                    return [None] * n
                fo, fl = spans
                return [bytes(raw[int(fo[i]):int(fo[i]) + int(fl[i])]
                              .tobytes()) if fl[i] >= 0 else None
                        for i in range(n)]
        else:
            rows = _row_fields(group)

            def get_col(name):
                return [r.get(name) for r in rows]
        order = list(range(n))
        for name, desc in reversed(self.keys):
            col = get_col(name)
            vals = [col[i] for i in order]
            nums = [_num(v) for v in vals]
            if all(x is not None for x in nums):
                keyed = nums
            else:
                keyed = [v if v is not None else b"" for v in vals]
            idx = sorted(range(len(order)), key=lambda k: keyed[k],
                         reverse=desc)
            order = [order[k] for k in idx]
        perm = np.array(order, dtype=np.int64)
        if columnar:
            group.set_columns(compact_columns(cols, perm))
        else:
            group._events = [group.events[i] for i in order]
            group._columns = None   # any materialized columns are stale


class _Join(_Stage):
    """join [type=inner|left] file('<csv>') on <key> — hash join the event
    stream against a CSV lookup table (header row names the columns; the
    ON key must be one of them).  inner drops non-matching events; left
    keeps them without the lookup columns.  The SLS SPL engine joins
    datasets; an agent-side processor's second dataset is a local table."""

    def __init__(self, join_type: Optional[str], path_src: str, key: str):
        self.join_type = join_type or "inner"
        self.key = key
        self.path = _unquote(path_src)
        self.table: Optional[Dict[bytes, Dict[str, bytes]]] = None
        import os
        if os.path.exists(self.path):
            self._load()        # present at config time: fail fast on a
            # malformed table; an ABSENT table defers to runtime (lookup
            # files often ship separately from pipeline configs)

    def _load(self) -> None:
        import csv
        table: Dict[bytes, Dict[str, bytes]] = {}
        try:
            with open(self.path, newline="") as f:
                reader = csv.reader(f)
                header = next(reader, None)
                if not header or self.key not in header:
                    raise SPLError(f"join table {self.path!r} lacks key "
                                   f"column {self.key!r}")
                key_idx = header.index(self.key)
                for row in reader:
                    if len(row) != len(header):
                        continue
                    table[row[key_idx].encode()] = {
                        h: row[i].encode() for i, h in enumerate(header)
                        if i != key_idx}
        except OSError as e:
            raise SPLError(f"join table {self.path!r} unreadable: {e}")
        self.table = table

    def apply(self, group: PipelineEventGroup) -> None:
        if self.table is None:
            try:
                self._load()        # late-shipped table: retry per batch
            except SPLError:
                self.table = None
            if self.table is None:
                from ..utils.logger import get_logger
                get_logger("spl").warning(
                    "join table %s not loadable yet; passing events "
                    "through un-joined", self.path)
                return              # left-join-like passthrough until ready
        sb = group.source_buffer
        cols = group.columns
        if cols is not None and not group._events:
            group.materialize()     # join needs per-event mutation
            group._columns = None   # else dropped rows resurrect from cols
        keep = []
        for ev in group.events:
            fields = ({k.to_str(): v.to_bytes() for k, v in ev.contents}
                      if hasattr(ev, "contents") else {})
            row = self.table.get(fields.get(self.key, b""))
            if row is not None:
                for k, v in row.items():
                    ev.set_content(sb.copy_string(k.encode()),
                                   sb.copy_string(v))
                keep.append(ev)
            elif self.join_type == "left":
                keep.append(ev)
        group.events[:] = keep


class _Limit(_Stage):
    def __init__(self, n: int):
        self.n = n

    def apply(self, group: PipelineEventGroup) -> None:
        n = len(group)
        if n <= self.n:
            return
        keep = np.zeros(n, dtype=bool)
        keep[: self.n] = True
        _apply_keep(group, keep)


def _apply_keep(group: PipelineEventGroup, keep: np.ndarray) -> None:
    if keep.all():
        return
    cols = group.columns
    if cols is not None and not group._events:
        group.set_columns(compact_columns(cols, keep))
    else:
        group._events = [ev for i, ev in enumerate(group.events) if keep[i]]


def compile_spl(script: str) -> List[_Stage]:
    stages: List[_Stage] = []
    parts = [p.strip() for p in _split_quote_aware(script.strip(), "|")]
    if parts and parts[0].strip() in ("*", ""):
        parts = parts[1:]
    for part in parts:
        part = part.strip()
        if not part:
            continue
        if m := _WHERE_RE.fullmatch(part):
            stages.append(_Where(m.group(1), m.group(2), m.group(3)))
        elif m := _PARSE_RE.fullmatch(part):
            stages.append(_Parse(m.group(1), m.group(2)))
        elif m := _EXTEND_RE.fullmatch(part):
            stages.append(_Extend(m.group(1), m.group(2)))
        elif m := _RENAME_RE.fullmatch(part):
            stages.append(_Rename(m.group(1), m.group(2)))
        elif m := _PROJECT_RE.fullmatch(part):
            fields = [f.strip() for f in m.group(2).split(",")]
            stages.append(_Project(fields, away=bool(m.group(1))))
        elif m := _LIMIT_RE.fullmatch(part):
            stages.append(_Limit(int(m.group(1))))
        elif m := _STATS_RE.fullmatch(part):
            stages.append(_Stats(m.group(1), m.group(2)))
        elif m := _SORT_RE.fullmatch(part):
            stages.append(_Sort(m.group(1)))
        elif m := _JOIN_RE.fullmatch(part):
            stages.append(_Join(m.group(1), m.group(2), m.group(3)))
        else:
            raise SPLError(f"unsupported SPL stage: {part!r}")
    return stages


class ProcessorSPL(Processor):
    name = "processor_spl"
    supports_columnar = True

    def __init__(self) -> None:
        super().__init__()
        self.stages: List[_Stage] = []

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        script = config.get("Script", "")
        if not script:
            return False
        try:
            self.stages = compile_spl(script)
        except (SPLError, re.error) as e:
            from ..utils.logger import get_logger
            get_logger("spl").error("SPL compile failed: %s", e)
            return False
        return True

    def process(self, group: PipelineEventGroup) -> None:
        for stage in self.stages:
            stage.apply(group)
