"""processor_spl — pipeline query language over event groups.

Reference: core/plugin/processor/ProcessorSPL.cpp bridges the (closed) SLS
SPL engine; this framework implements the practically-used core of the
language natively, columnar-first:

    * | where level = 'ERROR'
      | where msg matches 'timeout.*'        (device regex tier)
      | where latency > 100
      | parse content with regex '(?P<ip>\\S+) .*'
      | extend combo = concat(host, ':', level)
      | rename old as new
      | project a, b, c          /  project-away x, y
      | limit 100

Stages execute left to right on the whole group; `where matches` runs the
tiered device engine; `parse with regex` is the Tier-1 extraction kernel.
Unsupported constructs fail init (surfaced at config load), never silently.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..models import PipelineEventGroup
from ..ops.regex.engine import RegexEngine, get_engine
from ..pipeline.plugin.interface import PluginContext, Processor
from .common import extract_source
from .filter import compact_columns


class SPLError(Exception):
    pass


_WHERE_RE = re.compile(
    r"where\s+(\w+)\s*(>=|<=|!=|=|>|<|contains|matches)\s*(.+)", re.S)
_PARSE_RE = re.compile(r"parse\s+(\w+)\s+with\s+regex\s+(.+)", re.S)
_EXTEND_RE = re.compile(r"extend\s+(\w+)\s*=\s*(.+)", re.S)
_RENAME_RE = re.compile(r"rename\s+(\w+)\s+as\s+(\w+)")
_PROJECT_RE = re.compile(r"project(-away)?\s+(.+)")
_LIMIT_RE = re.compile(r"limit\s+(\d+)")


def _split_quote_aware(text: str, sep: str) -> List[str]:
    """Split on sep outside single/double-quoted spans (quotes may contain
    the separator — regex alternation pipes, literal commas)."""
    out: List[str] = []
    cur: List[str] = []
    quote = ""
    i = 0
    while i < len(text):
        c = text[i]
        if quote:
            cur.append(c)
            if c == quote:
                quote = ""
        elif c in "'\"":
            quote = c
            cur.append(c)
        elif c == sep:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(c)
        i += 1
    out.append("".join(cur))
    return out


def _unquote(v: str) -> str:
    v = v.strip()
    if len(v) >= 2 and v[0] == v[-1] and v[0] in "'\"":
        return v[1:-1]
    return v


class _Stage:
    def apply(self, group: PipelineEventGroup) -> None:  # pragma: no cover
        raise NotImplementedError


class _Where(_Stage):
    def __init__(self, field: str, op: str, value: str):
        self.field = field.encode()
        self.op = op
        self.value = _unquote(value)
        self.engine: Optional[RegexEngine] = None
        if op == "matches":
            self.engine = get_engine(self.value)
        self.num: Optional[float] = None
        if op in (">", ">=", "<", "<="):
            try:
                self.num = float(self.value)
            except ValueError:
                raise SPLError(f"numeric comparison with non-number "
                               f"{self.value!r}")

    def apply(self, group: PipelineEventGroup) -> None:
        src = extract_source(group, self.field)
        n = len(group)
        if src is None:
            keep = np.zeros(n, dtype=bool)
        elif self.op == "matches":
            keep = self.engine.match_batch(src.arena, src.offsets,
                                           src.lengths) & src.present
        else:
            keep = np.zeros(n, dtype=bool)
            want = self.value.encode()
            raw = src.arena
            for i in range(n):
                if not src.present[i]:
                    continue
                o, ln = int(src.offsets[i]), int(src.lengths[i])
                val = raw[o : o + ln].tobytes()
                if self.op == "=":
                    keep[i] = val == want
                elif self.op == "!=":
                    keep[i] = val != want
                elif self.op == "contains":
                    keep[i] = want in val
                else:
                    try:
                        x = float(val)
                    except ValueError:
                        continue
                    keep[i] = ((self.op == ">" and x > self.num)
                               or (self.op == ">=" and x >= self.num)
                               or (self.op == "<" and x < self.num)
                               or (self.op == "<=" and x <= self.num))
        _apply_keep(group, keep)


class _Parse(_Stage):
    def __init__(self, field: str, pattern: str):
        self.field = field
        self.engine = get_engine(_unquote(pattern))
        if not self.engine.group_names:
            raise SPLError("parse regex needs named groups (?P<name>...)")

    def apply(self, group: PipelineEventGroup) -> None:
        src = extract_source(group, self.field.encode())
        if src is None:
            return
        res = self.engine.parse_batch(src.arena, src.offsets, src.lengths)
        cols = group.columns
        ok = res.ok & src.present
        for g in range(self.engine.num_caps):
            name = self.engine.group_names.get(g)
            if not name:
                continue
            lens = np.where(ok, res.cap_len[:, g], -1).astype(np.int32)
            if cols is not None and not group._events:
                cols.set_field(name, res.cap_off[:, g], lens)
            else:
                sb = group.source_buffer
                for i, ev in enumerate(group.events):
                    if lens[i] >= 0 and hasattr(ev, "get_content"):
                        o = int(res.cap_off[i, g])
                        ev.set_content(name.encode(), sb.copy_string(
                            bytes(src.arena[o : o + lens[i]].tobytes())))


class _Extend(_Stage):
    """extend dst = concat(args...) | 'literal' | field"""

    def __init__(self, dst: str, expr: str):
        self.dst = dst
        expr = expr.strip()
        m = re.fullmatch(r"concat\((.+)\)", expr, re.S)
        if m:
            self.parts = [a.strip()
                          for a in _split_quote_aware(m.group(1), ",")]
        else:
            self.parts = [expr]

    def _value(self, part: str, fields: Dict[str, bytes]) -> bytes:
        if part and part[0] in "'\"":
            return _unquote(part).encode()
        return fields.get(part, b"")

    def apply(self, group: PipelineEventGroup) -> None:
        sb = group.source_buffer
        cols = group.columns
        if cols is not None and not group._events:
            n = len(cols)
            raw = group.source_buffer.as_array()
            offs = np.zeros(n, dtype=np.int32)
            lens = np.full(n, -1, dtype=np.int32)
            span_cols = {name: cols.fields[name] for name in cols.fields}
            for i in range(n):
                fields = {}
                for name, (fo, fl) in span_cols.items():
                    if fl[i] >= 0:
                        o = int(fo[i])
                        fields[name] = raw[o : o + int(fl[i])].tobytes()
                if not cols.content_consumed:
                    o, l = int(cols.offsets[i]), int(cols.lengths[i])
                    fields["content"] = raw[o : o + l].tobytes()
                out = b"".join(self._value(p, fields) for p in self.parts)
                view = sb.copy_string(out)
                offs[i] = view.offset
                lens[i] = view.length
            cols.set_field(self.dst, offs, lens)
            return
        for ev in group.events:
            if not hasattr(ev, "contents"):
                continue
            fields = {k.to_str(): v.to_bytes() for k, v in ev.contents}
            out = b"".join(self._value(p, fields) for p in self.parts)
            ev.set_content(self.dst.encode(), sb.copy_string(out))


class _Rename(_Stage):
    def __init__(self, old: str, new: str):
        self.old, self.new = old, new

    def apply(self, group: PipelineEventGroup) -> None:
        cols = group.columns
        if cols is not None and not group._events:
            if self.old in cols.fields:
                cols.fields[self.new] = cols.fields.pop(self.old)
            return
        for ev in group.events:
            if hasattr(ev, "get_content"):
                v = ev.get_content(self.old.encode())
                if v is not None:
                    ev.set_content(self.new.encode(), v)
                    ev.del_content(self.old.encode())


class _Project(_Stage):
    def __init__(self, fields: List[str], away: bool):
        self.fields = fields
        self.away = away

    def apply(self, group: PipelineEventGroup) -> None:
        cols = group.columns
        if cols is not None and not group._events:
            if self.away:
                for f in self.fields:
                    cols.fields.pop(f, None)
            else:
                cols.fields = {k: v for k, v in cols.fields.items()
                               if k in self.fields}
                if "content" not in self.fields:
                    cols.content_consumed = True
            return
        keep = set(self.fields)
        for ev in group.events:
            if not hasattr(ev, "contents"):
                continue
            names = [k.to_bytes() for k, _ in ev.contents]
            for name in names:
                present = name.decode("utf-8", "replace") in keep
                if self.away == present:
                    ev.del_content(name)


class _Limit(_Stage):
    def __init__(self, n: int):
        self.n = n

    def apply(self, group: PipelineEventGroup) -> None:
        n = len(group)
        if n <= self.n:
            return
        keep = np.zeros(n, dtype=bool)
        keep[: self.n] = True
        _apply_keep(group, keep)


def _apply_keep(group: PipelineEventGroup, keep: np.ndarray) -> None:
    if keep.all():
        return
    cols = group.columns
    if cols is not None and not group._events:
        group.set_columns(compact_columns(cols, keep))
    else:
        group._events = [ev for i, ev in enumerate(group.events) if keep[i]]


def compile_spl(script: str) -> List[_Stage]:
    stages: List[_Stage] = []
    parts = [p.strip() for p in _split_quote_aware(script.strip(), "|")]
    if parts and parts[0].strip() in ("*", ""):
        parts = parts[1:]
    for part in parts:
        part = part.strip()
        if not part:
            continue
        if m := _WHERE_RE.fullmatch(part):
            stages.append(_Where(m.group(1), m.group(2), m.group(3)))
        elif m := _PARSE_RE.fullmatch(part):
            stages.append(_Parse(m.group(1), m.group(2)))
        elif m := _EXTEND_RE.fullmatch(part):
            stages.append(_Extend(m.group(1), m.group(2)))
        elif m := _RENAME_RE.fullmatch(part):
            stages.append(_Rename(m.group(1), m.group(2)))
        elif m := _PROJECT_RE.fullmatch(part):
            fields = [f.strip() for f in m.group(2).split(",")]
            stages.append(_Project(fields, away=bool(m.group(1))))
        elif m := _LIMIT_RE.fullmatch(part):
            stages.append(_Limit(int(m.group(1))))
        else:
            raise SPLError(f"unsupported SPL stage: {part!r}")
    return stages


class ProcessorSPL(Processor):
    name = "processor_spl"

    def __init__(self) -> None:
        super().__init__()
        self.stages: List[_Stage] = []

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        script = config.get("Script", "")
        if not script:
            return False
        try:
            self.stages = compile_spl(script)
        except (SPLError, re.error) as e:
            from ..utils.logger import get_logger
            get_logger("spl").error("SPL compile failed: %s", e)
            return False
        return True

    def process(self, group: PipelineEventGroup) -> None:
        for stage in self.stages:
            stage.apply(group)
