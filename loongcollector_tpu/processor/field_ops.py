"""Common field-manipulation processors from the Go long tail.

Reference: plugins/processor/addfields (static enrichment),
plugins/processor/rename, plugins/processor/drop (drop events whose field
matches), plugins/processor/strreplace. Columnar groups take span-level
paths (constant columns, field-map renames, device match + compact);
object events edit contents in place.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List

import numpy as np

from ..models import PipelineEventGroup
from ..ops.regex.engine import get_engine
from ..pipeline.plugin.interface import PluginContext, Processor
from .filter import compact_columns


def _event_field(ev, key: bytes):
    get = getattr(ev, "get_content", None)
    if get is None:
        return None
    v = get(key)
    return v.to_bytes() if v is not None else None


class ProcessorAddFields(Processor):
    """Static fields on every event (plugins/processor/addfields).
    IgnoreIfExist preserves an existing value."""

    name = "processor_add_fields"
    supports_columnar = True

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.fields = {str(k): str(v)
                       for k, v in (config.get("Fields") or {}).items()}
        self.ignore_if_exist = bool(config.get("IgnoreIfExist", False))
        return bool(self.fields)

    def process(self, group: PipelineEventGroup) -> None:
        sb = group.source_buffer
        cols = group.columns
        if cols is not None and not group._events:
            n = len(cols)
            for k, v in self.fields.items():
                view = sb.copy_string(v.encode())
                if self.ignore_if_exist and k in cols.fields:
                    # fill only rows where the field is ABSENT (lens < 0) —
                    # per-event semantics, matching the object path
                    offs, lens = cols.fields[k]
                    missing = lens < 0
                    if not missing.any():
                        continue
                    offs = np.where(missing, view.offset, offs).astype(
                        np.int32)
                    lens = np.where(missing, view.length, lens).astype(
                        np.int32)
                    cols.set_field(k, offs, lens)
                    continue
                cols.set_field(k,
                               np.full(n, view.offset, np.int32),
                               np.full(n, view.length, np.int32))
            return
        for ev in group.events:
            if not hasattr(ev, "set_content"):
                continue
            for k, v in self.fields.items():
                if self.ignore_if_exist and ev.get_content(k.encode()):
                    continue
                ev.set_content(sb.copy_string(k.encode()),
                               sb.copy_string(v.encode()))


class ProcessorRenameFields(Processor):
    """Field renames (plugins/processor/rename): SourceKeys → DestKeys."""

    name = "processor_rename"
    supports_columnar = True

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        src = config.get("SourceKeys") or []
        dst = config.get("DestKeys") or []
        self.mapping = dict(zip(map(str, src), map(str, dst)))
        return bool(self.mapping) and len(src) == len(dst)

    def process(self, group: PipelineEventGroup) -> None:
        cols = group.columns
        if cols is not None and not group._events:
            for old, new in self.mapping.items():
                if old in cols.fields:
                    cols.fields[new] = cols.fields.pop(old)
                elif old == "content" and not cols.content_consumed:
                    # the raw-content pseudo-field renames like any other
                    cols.set_field(new, np.array(cols.offsets, copy=True),
                                   np.array(cols.lengths, copy=True))
                    cols.content_consumed = True
            return
        for ev in group.events:
            if not hasattr(ev, "get_content"):
                continue
            for old, new in self.mapping.items():
                v = ev.get_content(old.encode())
                if v is not None:
                    ev.set_content(new.encode(), v)
                    ev.del_content(old.encode())


class ProcessorDrop(Processor):
    """Two drop modes sharing the Go plugin's name:

    * `DropKeys: [field, ...]` — remove FIELDS from every event (the Go
      plugins/processor/drop semantics);
    * `Match: {field: regex}` — drop whole EVENTS whose field full-matches
      (the match runs on the device tier when the pattern compiles there).
    """

    name = "processor_drop"
    supports_columnar = True

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.drop_keys = [str(k) for k in (config.get("DropKeys") or [])]
        self.conditions = [(str(k).encode(), get_engine(str(p)))
                           for k, p in (config.get("Match") or {}).items()]
        return bool(self.drop_keys) or bool(self.conditions)

    def _drop_fields(self, group: PipelineEventGroup) -> None:
        cols = group.columns
        if cols is not None and not group._events:
            for k in self.drop_keys:
                cols.fields.pop(k, None)
                if k == "content":
                    cols.content_consumed = True
            return
        for ev in group.events:
            if hasattr(ev, "del_content"):
                for k in self.drop_keys:
                    ev.del_content(k.encode())

    def process(self, group: PipelineEventGroup) -> None:
        if self.drop_keys:
            self._drop_fields(group)
        if not self.conditions:
            return
        cols = group.columns
        if cols is not None and not group._events:
            n = len(cols)
            arena = group.source_buffer.as_array()
            drop = np.zeros(n, dtype=bool)
            for key, eng in self.conditions:
                name = key.decode()
                spans = cols.fields.get(name)
                if spans is None:
                    if name == "content" and not cols.content_consumed:
                        spans = (cols.offsets, cols.lengths)
                    else:
                        continue
                offs, lens = spans
                present = lens >= 0
                ok = eng.match_batch(arena,
                                     offs.astype(np.int64),
                                     np.maximum(lens, 0))
                drop |= present & ok
            if drop.any():
                group.set_columns(compact_columns(cols, ~drop))
            return
        kept = []
        for ev in group.events:
            matched = False
            for key, eng in self.conditions:
                v = _event_field(ev, key)
                if v is None:
                    continue
                data = np.frombuffer(v, dtype=np.uint8)
                if bool(eng.match_batch(data, np.array([0], np.int64),
                                        np.array([len(v)], np.int32))[0]):
                    matched = True
                    break
            if not matched:
                kept.append(ev)
        group._events = kept
        group._columns = None


class ProcessorStrReplace(Processor):
    """Regex replacement on a field (plugins/processor/strreplace)."""

    name = "processor_strreplace"
    supports_columnar = True

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.source_key = str(config.get("SourceKey", "content")).encode()
        method = config.get("Method", "regex")
        match = str(config.get("Match", "") or "")
        self.replacement = str(config.get("ReplaceString", "")).encode()
        if not match:
            return False
        if method == "const":
            match = re.escape(match)
        try:
            self.rx = re.compile(match.encode())
        except (re.error, UnicodeEncodeError):
            return False
        return True

    def process(self, group: PipelineEventGroup) -> None:
        sb = group.source_buffer
        cols = group.columns
        name = self.source_key.decode()
        if cols is not None and not group._events:
            raw = group.source_buffer.as_array()
            spans = cols.fields.get(name)
            use_content = spans is None and name == "content" \
                and not cols.content_consumed
            if use_content:
                spans = (cols.offsets, cols.lengths)
            if spans is None:
                return
            offs, lens = spans
            n = len(cols)
            new_offs = np.array(offs, dtype=np.int32, copy=True)
            new_lens = np.array(lens, dtype=np.int32, copy=True)
            for i in range(n):
                if lens[i] < 0:
                    continue
                o = int(offs[i])
                val = raw[o:o + int(lens[i])].tobytes()
                rep = self.rx.sub(self.replacement, val)
                if rep != val:
                    view = sb.copy_string(rep)
                    new_offs[i], new_lens[i] = view.offset, view.length
            if use_content:
                cols.offsets, cols.lengths = new_offs, new_lens
            else:
                cols.set_field(name, new_offs, new_lens)
            return
        for ev in group.events:
            v = _event_field(ev, self.source_key)
            if v is None:
                continue
            rep = self.rx.sub(self.replacement, v)
            if rep != v:
                ev.set_content(self.source_key, sb.copy_string(rep))
