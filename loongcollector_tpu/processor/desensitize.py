"""processor_desensitize — mask sensitive spans in a field.

Reference: core/plugin/processor/ProcessorDesensitizeNative.cpp — const or
md5 replacement of the content matched after a regex prefix.  The reference
semantics: `Regex` matches a prefix group and the sensitive part
(`ReplacingString` replaces the second part).

Host substitution path (find-all on-device is a later kernel — fullmatch
kernels don't locate interior spans yet).
"""

from __future__ import annotations

import hashlib
import re
from typing import Any, Dict

from ..models import PipelineEventGroup
from ..pipeline.plugin.interface import PluginContext, Processor


class ProcessorDesensitize(Processor):
    name = "processor_desensitize_native"
    supports_columnar = True

    def __init__(self) -> None:
        super().__init__()
        self.source_key = b"content"
        self.method = "const"        # const | md5
        self.replacing = b"********"
        self.regex = None

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.source_key = config.get("SourceKey", "content").encode()
        self.method = config.get("Method", "const")
        self.replacing = config.get("ReplacingString", "********").encode()
        pattern = config.get("Regex", "")
        if not pattern:
            return False
        self.regex = re.compile(pattern.encode())
        return True

    def _mask(self, m: "re.Match") -> bytes:
        # group 1 is kept as-is; group 2 (the sensitive span) is replaced
        prefix = m.group(1) if m.lastindex and m.lastindex >= 1 else b""
        if self.method == "md5":
            target = m.group(2) if m.lastindex and m.lastindex >= 2 else m.group(0)
            return prefix + hashlib.md5(target).hexdigest().encode()
        return prefix + self.replacing

    def process(self, group: PipelineEventGroup) -> None:
        sb = group.source_buffer
        cols = group.columns
        if cols is not None and not group._events:
            skey = self.source_key.decode()
            target = cols.fields.get(skey)
            if target is None and not cols.fields:
                # operate on raw content spans
                offs, lens = cols.offsets, cols.lengths
            elif target is None:
                return
            else:
                offs, lens = target
            raw = group.source_buffer.as_array()
            new_offs = offs.copy()
            new_lens = lens.copy()
            for i in range(len(offs)):
                ln = int(lens[i])
                if ln < 0:
                    continue
                o = int(offs[i])
                data = raw[o : o + ln].tobytes()
                masked = self.regex.sub(self._mask, data)
                if masked != data:
                    view = sb.copy_string(masked)
                    new_offs[i] = view.offset
                    new_lens[i] = view.length
                    raw = group.source_buffer.as_array()  # arena may have grown
            if target is None and not cols.fields:
                cols.offsets, cols.lengths = new_offs, new_lens
            else:
                cols.set_field(skey, new_offs, new_lens)
            return
        for ev in group.events:
            if not hasattr(ev, "get_content"):
                continue
            v = ev.get_content(self.source_key)
            if v is None:
                continue
            data = v.to_bytes()
            masked = self.regex.sub(self._mask, data)
            if masked != data:
                ev.set_content(self.source_key, sb.copy_string(masked))
