"""processor_filter — keep/drop events by field regex conditions.

Reference: core/plugin/processor/ProcessorFilterNative.cpp — Include map
(field → full-match regex, all must match) and Exclude map (any match drops).

TPU path: per-field match via RegexEngine.match_batch (segment/DFA tier on
device); columnar groups drop events by boolean-mask compaction of the span
columns — no per-event objects.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ..models import ColumnarLogs, PipelineEventGroup
from ..ops.regex.engine import RegexEngine, get_engine
from ..pipeline.plugin.interface import PluginContext, Processor
from .common import extract_source


def compact_columns(cols: ColumnarLogs, keep: np.ndarray) -> ColumnarLogs:
    out = ColumnarLogs(cols.offsets[keep], cols.lengths[keep],
                       cols.timestamps[keep])
    for name, (offs, lens) in cols.fields.items():
        out.set_field(name, offs[keep], lens[keep])
    if cols.parse_ok is not None:
        out.parse_ok = cols.parse_ok[keep]
    out.content_consumed = cols.content_consumed
    return out


class ProcessorFilter(Processor):
    name = "processor_filter_native"
    supports_columnar = True

    def __init__(self) -> None:
        super().__init__()
        self.include: List = []   # [(key bytes, engine)]
        self.exclude: List = []

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        for k, pattern in (config.get("Include") or {}).items():
            self.include.append((k.encode(), get_engine(pattern)))
        for k, pattern in (config.get("Exclude") or {}).items():
            self.exclude.append((k.encode(), get_engine(pattern)))
        return True

    def fused_stage_spec(self, ctx):
        """loongresident: the whole Include/Exclude condition set joins a
        fused pipeline program as ONE ``keep`` stage — each condition a
        DFA/Tier-1 match over the packed source rows or, for a field a
        prior member's extract stage produced, a span-bound DFA over that
        stage's DEVICE-RESIDENT capture column.  The combined keep mask
        is computed on device; the apply is pure column compaction.  Any
        condition that cannot bind statically (field minted outside the
        run, consumed source, CPU-tier pattern with no DFA form) refuses
        fusion and the filter keeps its per-stage path."""
        if not self.include and not self.exclude:
            return None
        from ..ops import fused_pipeline as fp
        from ..ops.regex.dfa import DFAUnsupported, compile_dfa
        from ..ops.regex.program import PatternTier
        from ..pipeline.fused_chain import FusedMemberStage
        conds = []
        for negate, pairs in ((False, self.include), (True, self.exclude)):
            for key, engine in pairs:
                binding = ctx.resolve(key)
                if binding is None:
                    return None
                if binding == "source":
                    if not ctx.bind_source(key):
                        return None
                    if engine.tier is PatternTier.SEGMENT:
                        conds.append(fp.StageCond(
                            "extract_ok", engine._segment_kernel.program,
                            ["extract_ok", engine.pattern, negate],
                            negate=negate, staged=engine._segment_kernel))
                    elif engine.tier is PatternTier.DFA:
                        conds.append(fp.StageCond(
                            "match", engine._dfa_kernel.dfa,
                            ["match", engine.pattern, negate],
                            negate=negate, staged=engine._dfa_kernel))
                    else:
                        return None
                else:
                    _tag, prod, cap = binding
                    try:
                        dfa = compile_dfa(engine.pattern)
                    except DFAUnsupported:
                        return None
                    from ..ops.kernels.dfa_scan import LazySpanMatchKernel
                    conds.append(fp.StageCond(
                        "span_match", dfa,
                        ["span_match", engine.pattern, prod, cap, negate],
                        binding=(prod, cap), negate=negate,
                        staged=LazySpanMatchKernel(dfa)))
        spec = fp.StageSpec("keep", conds,
                            ["keep"] + [list(c.ident) for c in conds],
                            label="filter")
        return FusedMemberStage(spec, self._fused_apply)

    def _fused_apply(self, group, src, out, rowmap):
        keep = np.asarray(out[0], dtype=bool)[rowmap]
        if keep.all():
            return rowmap
        cols = group.columns
        if cols is not None and not group._events:
            group.set_columns(compact_columns(cols, keep))
        else:
            group._events = [ev for i, ev in enumerate(group.events)
                             if keep[i]]
        return rowmap[keep]

    def _match_field(self, group: PipelineEventGroup, key: bytes,
                     engine: RegexEngine, n: int) -> np.ndarray:
        src = extract_source(group, key)
        if src is None:
            return np.zeros(n, dtype=bool)
        ok = engine.match_batch(src.arena, src.offsets, src.lengths)
        return ok & src.present

    def process(self, group: PipelineEventGroup) -> None:
        n = len(group)
        if n == 0:
            return
        keep = np.ones(n, dtype=bool)
        for key, engine in self.include:
            keep &= self._match_field(group, key, engine, n)
        for key, engine in self.exclude:
            keep &= ~self._match_field(group, key, engine, n)
        if keep.all():
            return
        cols = group.columns
        if cols is not None and not group._events:
            group.set_columns(compact_columns(cols, keep))
        else:
            group._events = [ev for i, ev in enumerate(group.events) if keep[i]]
