"""processor_filter — keep/drop events by field regex conditions.

Reference: core/plugin/processor/ProcessorFilterNative.cpp — Include map
(field → full-match regex, all must match) and Exclude map (any match drops).

TPU path: per-field match via RegexEngine.match_batch (segment/DFA tier on
device); columnar groups drop events by boolean-mask compaction of the span
columns — no per-event objects.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ..models import ColumnarLogs, PipelineEventGroup
from ..ops.regex.engine import RegexEngine, get_engine
from ..pipeline.plugin.interface import PluginContext, Processor
from .common import extract_source


def compact_columns(cols: ColumnarLogs, keep: np.ndarray) -> ColumnarLogs:
    out = ColumnarLogs(cols.offsets[keep], cols.lengths[keep],
                       cols.timestamps[keep])
    for name, (offs, lens) in cols.fields.items():
        out.set_field(name, offs[keep], lens[keep])
    if cols.parse_ok is not None:
        out.parse_ok = cols.parse_ok[keep]
    out.content_consumed = cols.content_consumed
    return out


class ProcessorFilter(Processor):
    name = "processor_filter_native"
    supports_columnar = True

    def __init__(self) -> None:
        super().__init__()
        self.include: List = []   # [(key bytes, engine)]
        self.exclude: List = []

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        for k, pattern in (config.get("Include") or {}).items():
            self.include.append((k.encode(), get_engine(pattern)))
        for k, pattern in (config.get("Exclude") or {}).items():
            self.exclude.append((k.encode(), get_engine(pattern)))
        return True

    def _match_field(self, group: PipelineEventGroup, key: bytes,
                     engine: RegexEngine, n: int) -> np.ndarray:
        src = extract_source(group, key)
        if src is None:
            return np.zeros(n, dtype=bool)
        ok = engine.match_batch(src.arena, src.offsets, src.lengths)
        return ok & src.present

    def process(self, group: PipelineEventGroup) -> None:
        n = len(group)
        if n == 0:
            return
        keep = np.ones(n, dtype=bool)
        for key, engine in self.include:
            keep &= self._match_field(group, key, engine, n)
        for key, engine in self.exclude:
            keep &= ~self._match_field(group, key, engine, n)
        if keep.all():
            return
        cols = group.columns
        if cols is not None and not group._events:
            group.set_columns(compact_columns(cols, keep))
        else:
            group._events = [ev for i, ev in enumerate(group.events) if keep[i]]
