"""Long-tail processors, batch 2 — closes the remaining reference dirs.

Reference: plugins/processor/{anchor,appender,cloudmeta,csv,defaultone,
droplastkey,gotime,logtoslsmetric,md5,otel}/ with Go-compatible config
keys and semantics (differential tests in tests/test_longtail2.py).
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import time
from typing import Any, Dict, List, Optional

from ..models import PipelineEventGroup
from ..models.events import MetricEvent, SpanEvent
from ..pipeline.plugin.interface import PluginContext, Processor
from ..utils.logger import get_logger

log = get_logger("longtail2")


def _replace_events(group: PipelineEventGroup, out_events: list) -> None:
    """Swap the group's event list, clearing any columnar view — stale
    columns would re-materialize dropped events on the next access."""
    group.events[:] = out_events
    group._columns = None


def each_log_event(group: PipelineEventGroup):
    """LogEvents only (materializes columnar groups — these processors
    mutate per-event fields)."""
    for ev in group.events:
        if hasattr(ev, "contents"):
            yield ev


# ------------------------------------------------------------------- anchor


class ProcessorAnchor(Processor):
    """processor_anchor (plugins/processor/anchor/anchor.go): per anchor,
    extract the substring between Start and Stop from SourceKey into
    FieldName; FieldType json + ExpondJSON flattens the parsed object into
    FieldName<connector>sub keys up to MaxExpondDepth."""

    name = "processor_anchor"

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.source_key = str(config.get("SourceKey", "content")).encode()
        self.keep_source = bool(config.get("KeepSource", True))
        self.anchors = []
        for a in config.get("Anchors", []):
            self.anchors.append({
                "start": str(a.get("Start", "")).encode(),
                "stop": str(a.get("Stop", "")).encode(),
                "field": str(a.get("FieldName", "")).encode(),
                "json": str(a.get("FieldType", "string")) == "json",
                "expand": bool(a.get("ExpondJSON", False)),
                "conn": str(a.get("ExpondConnecter", "_")),
                "depth": int(a.get("MaxExpondDepth", 0)) or 100,
            })
        return bool(self.anchors)

    def _expand(self, ev, sb, prefix: str, doc, conn: str,
                depth: int) -> None:
        if depth <= 0 or not isinstance(doc, (dict, list)):
            val = (doc if isinstance(doc, str)
                   else json.dumps(doc, separators=(",", ":")))
            ev.set_content(sb.copy_string(prefix.encode()),
                           sb.copy_string(val.encode()))
            return
        items = (doc.items() if isinstance(doc, dict)
                 else enumerate(doc))
        for k, v in items:
            self._expand(ev, sb, f"{prefix}{conn}{k}", v, conn, depth - 1)

    def process(self, group: PipelineEventGroup) -> None:
        sb = group.source_buffer
        for ev in each_log_event(group):
            src = ev.get_content(self.source_key)
            if src is None:
                continue
            data = src.to_bytes()
            cursor = 0      # sequential scan: each anchor starts after the
            for a in self.anchors:      # previous one's match (Go plugin)
                i = data.find(a["start"], cursor) if a["start"] else cursor
                if i < 0:
                    continue
                i += len(a["start"])
                j = data.find(a["stop"], i) if a["stop"] else len(data)
                if j < 0:
                    continue
                val = data[i:j]
                cursor = j
                if a["json"] and a["expand"]:
                    try:
                        doc = json.loads(val)
                    except ValueError:
                        continue
                    self._expand(ev, sb, a["field"].decode(), doc,
                                 a["conn"], a["depth"])
                else:
                    ev.set_content(sb.copy_string(a["field"]),
                                   sb.copy_string(val))
            if not self.keep_source:
                ev.del_content(self.source_key)


# ----------------------------------------------------------------- appender


class ProcessorAppender(Processor):
    """processor_appender: append Value to Key's existing value, with
    {{__hostname__}} / {{__ip__}} / {{env.NAME}} platform substitution."""

    name = "processor_appender"

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.key = str(config.get("Key", "")).encode()
        self.value = self._substitute(str(config.get("Value", "")))
        return bool(self.key) and bool(self.value)

    @staticmethod
    def _substitute(val: str) -> bytes:
        import socket
        out = val.replace("{{__hostname__}}", socket.gethostname())
        if "{{__ip__}}" in out:
            try:
                ip = socket.gethostbyname(socket.gethostname())
            except OSError:
                ip = ""
            out = out.replace("{{__ip__}}", ip)
        out = re.sub(r"\{\{env\.(\w+)\}\}",
                     lambda m: os.environ.get(m.group(1), ""), out)
        return out.encode()

    def process(self, group: PipelineEventGroup) -> None:
        sb = group.source_buffer
        for ev in each_log_event(group):
            old = ev.get_content(self.key)
            merged = (old.to_bytes() if old is not None else b"") + self.value
            ev.set_content(sb.copy_string(self.key), sb.copy_string(merged))


# ---------------------------------------------------------------- cloudmeta


class ProcessorCloudMeta(Processor):
    """processor_cloud_meta: stamp host/cloud identity metadata onto events
    (reference reads the ECS metadata service; this implementation reads
    env overrides ALIYUN_* / standard envs with hostname/ip fallbacks —
    metadata-server access is deployment-specific and injectable here)."""

    name = "processor_cloud_meta"

    _META = ("instance_id", "instance_name", "region", "zone", "hostname",
             "ip")

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        import socket
        want = config.get("Metadata") or list(self._META)
        prefix = str(config.get("KeyPrefix", "__cloud_"))
        values = {
            "instance_id": os.environ.get("ALIYUN_INSTANCE_ID", ""),
            "instance_name": os.environ.get("ALIYUN_INSTANCE_NAME", ""),
            "region": os.environ.get("ALIYUN_REGION_ID", ""),
            "zone": os.environ.get("ALIYUN_ZONE_ID", ""),
            "hostname": socket.gethostname(),
        }
        try:
            values["ip"] = socket.gethostbyname(socket.gethostname())
        except OSError:
            values["ip"] = ""
        self.fields = {(prefix + k + "__").encode(): values[k].encode()
                       for k in want if k in values and values[k]}
        return bool(self.fields)

    def process(self, group: PipelineEventGroup) -> None:
        sb = group.source_buffer
        for ev in each_log_event(group):
            for k, v in self.fields.items():
                ev.set_content(sb.copy_string(k), sb.copy_string(v))


# --------------------------------------------------------------------- csv


class ProcessorCSV(Processor):
    """processor_csv: parse SourceKey as one CSV record into SplitKeys
    (quote-aware); surplus columns keep ExpandKeyPrefix<N> names when
    ExpandOthers, else are dropped; missing keys honored by NoKeyError."""

    name = "processor_csv"

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.source_key = str(config.get("SourceKey", "content")).encode()
        self.split_keys = [str(k).encode()
                           for k in config.get("SplitKeys", [])]
        self.sep = str(config.get("SplitSep", ","))
        self.trim = bool(config.get("TrimLeadingSpace", False))
        self.keep_source = bool(config.get("KeepSource", False))
        self.expand_others = bool(config.get("ExpandOthers", False))
        self.expand_prefix = str(config.get("ExpandKeyPrefix", "expand_"))
        return bool(self.split_keys) and len(self.sep) == 1

    def process(self, group: PipelineEventGroup) -> None:
        import csv
        import io
        sb = group.source_buffer
        for ev in each_log_event(group):
            src = ev.get_content(self.source_key)
            if src is None:
                continue
            text = src.to_bytes().decode("utf-8", "replace")
            reader = csv.reader(io.StringIO(text), delimiter=self.sep,
                                skipinitialspace=self.trim)
            row = next(reader, [])
            for i, val in enumerate(row):
                if i < len(self.split_keys):
                    key = self.split_keys[i]
                elif self.expand_others:
                    key = (f"{self.expand_prefix}"
                           f"{i - len(self.split_keys) + 1}").encode()
                else:
                    break
                ev.set_content(sb.copy_string(key),
                               sb.copy_string(val.encode()))
            if not self.keep_source:
                ev.del_content(self.source_key)


# --------------------------------------------------------------- defaultone


class ProcessorDefault(Processor):
    """processor_default: explicit passthrough (the Go runtime's default
    pipeline stage when no processors are configured)."""

    name = "processor_default"

    def process(self, group: PipelineEventGroup) -> None:
        return


# ------------------------------------------------------------- droplastkey


class ProcessorDropLastKey(Processor):
    """processor_drop_last_key: once processing added keys beyond the
    Include set, the raw DropKey has served its purpose — remove it."""

    name = "processor_drop_last_key"

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.drop_key = str(config.get("DropKey", "")).encode()
        self.include = {str(k).encode() for k in config.get("Include", [])}
        return bool(self.drop_key) and bool(self.include)

    def process(self, group: PipelineEventGroup) -> None:
        for ev in each_log_event(group):
            keys = {bytes(k) for k, _ in ev.contents}
            if keys - self.include - {self.drop_key}:
                ev.del_content(self.drop_key)


# ------------------------------------------------------------------ gotime


_GO_TOKENS = [          # longest-first: Go reference layout → strptime
    ("2006", "%Y"), ("01", "%m"), ("02", "%d"), ("15", "%H"),
    ("04", "%M"), ("05", "%S"), ("Monday", "%A"), ("Mon", "%a"),
    ("January", "%B"), ("Jan", "%b"), ("PM", "%p"), ("03", "%I"),
    ("-0700", "%z"), ("MST", "%Z"), ("06", "%y"),
]


def go_layout_to_strptime(layout: str) -> str:
    out = layout
    for go, py in _GO_TOKENS:
        out = out.replace(go, py)
    out = re.sub(r"\.0+", lambda m: ".%f", out)   # .000... → fractional
    return out


class ProcessorGotime(Processor):
    """processor_gotime: parse SourceKey using a Go time layout (or the
    fixed 'seconds'/'milliseconds'/'microseconds' timestamp patterns),
    write DestKey in DestFormat, optionally SetTime on the event."""

    name = "processor_gotime"

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.source_key = str(config.get("SourceKey", "")).encode()
        self.source_format = str(config.get("SourceFormat", ""))
        self.source_loc = config.get("SourceLocation")   # tz offset hours
        self.dest_loc = config.get("DestLocation")       # tz offset hours
        self.dest_key = str(config.get("DestKey", "")).encode()
        self.dest_format = str(config.get("DestFormat", ""))
        self.set_time = bool(config.get("SetTime", True))
        self.keep_source = bool(config.get("KeepSource", True))
        if not (self.source_key and self.source_format and self.dest_key
                and self.dest_format):
            return False
        self._fixed = self.source_format in ("seconds", "milliseconds",
                                             "microseconds")
        if not self._fixed:
            self._py_src = go_layout_to_strptime(self.source_format)
        self._py_dst = go_layout_to_strptime(self.dest_format)
        return True

    def _parse(self, raw: bytes) -> Optional[float]:
        try:
            if self._fixed:
                v = float(raw)
                scale = {"seconds": 1.0, "milliseconds": 1e3,
                         "microseconds": 1e6}[self.source_format]
                return v / scale
            import calendar
            import datetime as dt
            t = dt.datetime.strptime(raw.decode("utf-8", "replace"),
                                     self._py_src)
            if t.tzinfo is not None:
                return t.timestamp()
            epoch = calendar.timegm(t.timetuple()) + t.microsecond / 1e6
            if self.source_loc is not None:
                return epoch - float(self.source_loc) * 3600.0
            # no location: interpret in machine-local time (Go default)
            return time.mktime(t.timetuple()) + t.microsecond / 1e6
        except (ValueError, OverflowError):
            return None

    def process(self, group: PipelineEventGroup) -> None:
        sb = group.source_buffer
        for ev in each_log_event(group):
            src = ev.get_content(self.source_key)
            if src is None:
                continue
            epoch = self._parse(src.to_bytes())
            if epoch is None:
                continue
            import datetime as dt
            # datetime.strftime supports %f (fractional layouts) and
            # DestLocation shifts the rendered wall clock (Go plugin)
            shift = float(self.dest_loc) * 3600.0 \
                if self.dest_loc is not None else 0.0
            when = dt.datetime.fromtimestamp(epoch + shift,
                                             dt.timezone.utc)
            out = when.strftime(self._py_dst)
            ev.set_content(sb.copy_string(self.dest_key),
                           sb.copy_string(out.encode()))
            if self.set_time:
                ev.timestamp = int(epoch)
            if not self.keep_source:
                ev.del_content(self.source_key)


# --------------------------------------------------------- logtoslsmetric


class ProcessorLogToSlsMetric(Processor):
    """processor_log_to_sls_metric: reshape log events into MetricEvents —
    MetricLabelKeys become labels, each MetricValues {nameKey: valueKey}
    pair emits one metric named by the nameKey field's VALUE, plus
    CustomMetricLabels constants; MetricTimeKey overrides the timestamp
    (nanoseconds or seconds)."""

    name = "processor_log_to_sls_metric"

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.time_key = str(config.get("MetricTimeKey", "")).encode()
        self.label_keys = [str(k).encode()
                           for k in config.get("MetricLabelKeys", [])]
        self.values = {str(k).encode(): str(v).encode()
                       for k, v in (config.get("MetricValues") or {}).items()}
        self.custom_labels = {str(k).encode(): str(v).encode()
                              for k, v in
                              (config.get("CustomMetricLabels") or {}).items()}
        return bool(self.values)

    def process(self, group: PipelineEventGroup) -> None:
        sb = group.source_buffer
        out_events = []
        for ev in group.events:
            if not hasattr(ev, "contents"):
                out_events.append(ev)
                continue
            fields = {bytes(k): v.to_bytes() for k, v in ev.contents}
            ts = ev.timestamp
            if self.time_key and self.time_key in fields:
                try:
                    raw_ts = int(fields[self.time_key])
                    ts = raw_ts // 10**9 if raw_ts > 10**12 else raw_ts
                except ValueError:
                    pass
            for name_key, value_key in self.values.items():
                name = fields.get(name_key)
                raw = fields.get(value_key)
                if name is None or raw is None:
                    continue
                try:
                    value = float(raw)
                except ValueError:
                    continue
                m = MetricEvent(timestamp=ts)
                m.set_name(sb.copy_string(name))
                m.set_value(value)
                for lk in self.label_keys:
                    lv = fields.get(lk)
                    if lv is not None:
                        m.set_tag(lk, sb.copy_string(lv))
                for ck, cv in self.custom_labels.items():
                    m.set_tag(ck, sb.copy_string(cv))
                out_events.append(m)
        _replace_events(group, out_events)


# --------------------------------------------------------------------- md5


class ProcessorMD5(Processor):
    """processor_md5: DestKey = md5hex(SourceKey value)."""

    name = "processor_md5"

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.source_key = str(config.get("SourceKey", "")).encode()
        self.dest_key = str(config.get("DestKey", "")).encode()
        return bool(self.source_key) and bool(self.dest_key)

    def process(self, group: PipelineEventGroup) -> None:
        sb = group.source_buffer
        for ev in each_log_event(group):
            src = ev.get_content(self.source_key)
            if src is None:
                continue
            digest = hashlib.md5(src.to_bytes()).hexdigest().encode()
            ev.set_content(sb.copy_string(self.dest_key),
                           sb.copy_string(digest))


# -------------------------------------------------------------------- otel


class ProcessorOtelTrace(Processor):
    """processor_otel_trace: logs carrying trace-shaped fields (traceID,
    spanID, parentSpanID, spanName/operationName, startTime, endTime,
    statusCode, kind, attributes JSON) become native SpanEvents."""

    name = "processor_otel_trace"

    _KIND = {b"server": 2, b"client": 3, b"producer": 4, b"consumer": 5,
             b"internal": 1}

    def process(self, group: PipelineEventGroup) -> None:
        out = []
        for ev in group.events:
            if not hasattr(ev, "contents"):
                out.append(ev)
                continue
            fields = {bytes(k): v.to_bytes() for k, v in ev.contents}
            trace_id = fields.get(b"traceID") or fields.get(b"traceId")
            span_id = fields.get(b"spanID") or fields.get(b"spanId")
            if not trace_id or not span_id:
                out.append(ev)          # not a trace row: pass through
                continue
            span = SpanEvent(timestamp=ev.timestamp)
            span.trace_id = trace_id
            span.span_id = span_id
            span.parent_span_id = (fields.get(b"parentSpanID")
                                   or fields.get(b"parentSpanId") or b"")
            span.name = (fields.get(b"spanName")
                         or fields.get(b"operationName") or b"")
            for key, attr in ((b"startTime", "start_time_ns"),
                              (b"endTime", "end_time_ns")):
                raw = fields.get(key)
                if raw is not None:
                    try:
                        v = int(raw)
                        setattr(span, attr,
                                v * 1000 if v < 10**16 else v)  # µs → ns
                    except ValueError:
                        pass
            span.kind = SpanEvent.Kind(
                self._KIND.get(fields.get(b"kind", b"").lower(), 0))
            status = fields.get(b"statusCode", b"").upper()
            if status in (b"ERROR", b"2"):
                span.status = SpanEvent.Status.ERROR
            elif status in (b"OK", b"1"):
                span.status = SpanEvent.Status.OK
            attrs = fields.get(b"attribute") or fields.get(b"attributes")
            if attrs:
                try:
                    for k, v in json.loads(attrs).items():
                        span.set_attribute(str(k).encode(),
                                           str(v).encode())
                except (ValueError, AttributeError):
                    pass
            out.append(span)
        _replace_events(group, out)


class ProcessorOtelMetric(Processor):
    """processor_otel_metric: logs in SLS metric shape (__name__,
    __value__, __labels__ 'k#$#v|k#$#v', __time_nano__) become native
    MetricEvents."""

    name = "processor_otel_metric"

    def process(self, group: PipelineEventGroup) -> None:
        sb = group.source_buffer
        out = []
        for ev in group.events:
            if not hasattr(ev, "contents"):
                out.append(ev)
                continue
            fields = {bytes(k): v.to_bytes() for k, v in ev.contents}
            name = fields.get(b"__name__")
            raw = fields.get(b"__value__")
            if not name or raw is None:
                out.append(ev)
                continue
            try:
                value = float(raw)
            except ValueError:
                out.append(ev)
                continue
            ts = ev.timestamp
            tn = fields.get(b"__time_nano__")
            if tn is not None:
                try:
                    ts = int(tn) // 10**9
                except ValueError:
                    pass
            m = MetricEvent(timestamp=ts)
            m.set_name(sb.copy_string(name))
            m.set_value(value)
            for pair in (fields.get(b"__labels__") or b"").split(b"|"):
                k, sep, v = pair.partition(b"#$#")
                if sep and k:
                    m.set_tag(bytes(k), sb.copy_string(v))
            out.append(m)
        _replace_events(group, out)


ALL = [ProcessorAnchor, ProcessorAppender, ProcessorCloudMeta,
       ProcessorCSV, ProcessorDefault, ProcessorDropLastKey,
       ProcessorGotime, ProcessorLogToSlsMetric, ProcessorMD5,
       ProcessorOtelTrace, ProcessorOtelMetric]
