"""processor_grok — grok pattern field extraction.

Reference: plugins/processor/grok/ (Go) — pattern library + %{NAME:field}
expansion; multiple Match patterns are tried IN ORDER per event until one
fully matches.  Expansion feeds the tiered RegexEngine, so kernel-friendly
grok runs on the Tier-1 device kernel; each fallback pattern runs as its own
device batch over the still-unmatched subset.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

from ..models import PipelineEventGroup
from ..ops.regex.engine import RegexEngine, get_engine
from ..ops.regex.grok import GrokError, expand
from ..pipeline.plugin.interface import PluginContext, Processor
from .common import RAW_LOG_KEY, extract_source


class ProcessorGrok(Processor):
    name = "processor_grok"
    supports_columnar = True

    def __init__(self) -> None:
        super().__init__()
        self.source_key = b"content"
        self.keep_source_on_fail = True
        self.renamed_source_key = RAW_LOG_KEY
        self._engines: List[Tuple[RegexEngine, List[str]]] = []
        self._fused_set = None

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        match = config.get("Match", [])
        if isinstance(match, str):
            match = [match]
        if not match:
            return False
        custom = config.get("CustomPatterns", {}) or {}
        self.source_key = config.get("SourceKey", "content").encode()
        self.keep_source_on_fail = bool(
            config.get("KeepingSourceWhenParseFail", True))
        import re as _re
        for pattern in match:
            try:
                regex = expand(pattern, custom)
                engine = get_engine(regex)
            except (GrokError, _re.error):
                return False
            # only NAMED groups become fields (grok semantics)
            keys = [engine.group_names.get(i, "") for i in range(engine.num_caps)]
            self._engines.append((engine, keys))
        # loongfuse: with several Match patterns, one fused scan classifies
        # them all — each event runs ONLY its first-matching pattern's
        # extract program instead of trying every engine in order.  A lone
        # pattern already fuses inside its own engine.
        self._fused_set = None
        if len(self._engines) > 1:
            from ..ops.regex.fuse import try_build_set
            self._fused_set = try_build_set(
                [e.pattern for e, _ in self._engines],
                names=[f"match{i}" for i in range(len(self._engines))])
        return True

    def process(self, group: PipelineEventGroup) -> None:
        src = extract_source(group, self.source_key)
        if src is None:
            return
        n = len(src.offsets)
        if n == 0:
            return
        if src.columnar:
            member_masks = None
            if self._fused_set is not None:
                tags = self._fused_set.classify(
                    src.arena, src.offsets.astype(np.int64), src.lengths)
                member_masks = self._fused_set.member_masks(tags)
            self._apply_columnar(group, src, member_masks)
            return

        self._process_rows(group)

    def fused_stage_spec(self, ctx):
        """loongresident: the multi-pattern classify scan joins a fused
        pipeline program as a ``scan`` stage (one tag bitmask per row);
        extraction still runs per matching subset afterwards — the scan
        is the stage that used to cost one dispatch per pattern.  Grok's
        dynamic fields never register as capture bindings (they are
        extracted host-side), so later members cannot bind them — by
        design, not by accident."""
        fs = self._fused_set
        if fs is None or not fs.fdfa.device_ok:
            return None
        if not ctx.bind_source(self.source_key):
            return None
        from ..ops import fused_pipeline as fp
        from ..pipeline.fused_chain import FusedMemberStage
        spec = fp.StageSpec("scan", fs.fdfa,
                            ["scan"] + list(fs.fdfa.patterns),
                            staged=fs._device_kernel(),
                            label="grok-classify")
        ctx.note_consumed(self.source_key)
        return FusedMemberStage(spec, self._fused_apply)

    def _fused_apply(self, group, src, out, rowmap):
        from .common import subset_source
        tags = np.asarray(out[0]).astype(np.uint32)[rowmap]
        masks = self._fused_set.member_masks(tags)
        self._apply_columnar(group, subset_source(src, rowmap), masks)
        return rowmap

    def _apply_columnar(self, group, src, member_masks) -> None:
        n = len(src.offsets)
        cols = group.columns
        remaining = src.present.copy()
        matched = np.zeros(n, dtype=bool)
        field_offs: Dict[str, np.ndarray] = {}
        field_lens: Dict[str, np.ndarray] = {}
        for pat_i, (engine, keys) in enumerate(self._engines):
            if not remaining.any():
                break
            if member_masks is not None \
                    and member_masks[pat_i] is not None:
                # fused member: the scan already classified it — run
                # its extract program only on its matching rows.
                # Demoted members (mask None) keep the per-pattern
                # probe over everything still unmatched.
                idx = np.nonzero(remaining & member_masks[pat_i])[0]
                if not len(idx):
                    continue
            else:
                idx = np.nonzero(remaining)[0]
            res = engine.parse_batch(src.arena, src.offsets[idx],
                                     src.lengths[idx])
            hit = idx[res.ok]
            if not len(hit):
                continue
            for g, key in enumerate(keys):
                if not key:
                    continue
                if key not in field_offs:
                    field_offs[key] = np.zeros(n, dtype=np.int32)
                    field_lens[key] = np.full(n, -1, dtype=np.int32)
                field_offs[key][hit] = res.cap_off[res.ok, g]
                field_lens[key][hit] = res.cap_len[res.ok, g]
            matched[hit] = True
            remaining[hit] = False
        for key in field_offs:
            cols.set_field(key, field_offs[key], field_lens[key])
        if self.keep_source_on_fail:
            fail = (~matched) & src.present
            if fail.any():
                cols.set_field(self.renamed_source_key,
                               src.offsets.astype(np.int32),
                               np.where(fail, src.lengths, -1).astype(np.int32))
        cols.parse_ok = matched
        if src.from_content:
            cols.content_consumed = True

    def _process_rows(self, group: PipelineEventGroup) -> None:
        # row path — shared reference keep/discard ordering
        from .common import finish_row_keep
        sb = group.source_buffer
        renamed = self.renamed_source_key.encode()
        for i, ev in enumerate(group.events):
            if not hasattr(ev, "get_content"):
                continue
            raw = ev.get_content(self.source_key)
            if raw is None:
                continue
            data = raw.to_bytes()
            hit = False
            overwritten = False
            for engine, keys in self._engines:
                m = engine._re.fullmatch(data)
                if m is None:
                    continue
                hit = True
                for g, key in enumerate(keys):
                    if key and m.group(g + 1) is not None:
                        kb = key.encode()
                        ev.set_content(kb, sb.copy_string(m.group(g + 1)))
                        if kb == self.source_key:
                            overwritten = True
                break
            finish_row_keep(ev, raw, hit, self.source_key, overwritten,
                            self.keep_source_on_fail, False, renamed)
