"""Shared processor helpers: columnar source extraction.

The data plane keeps groups columnar; processors that parse a source field
need (arena, offsets, lengths) triples.  For columnar groups that's free;
for per-event groups the sources are packed into a scratch arena first.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from ..models import ColumnarLogs, LogEvent, PipelineEventGroup, RawEvent

DEFAULT_CONTENT_KEY = b"content"
RAW_LOG_KEY = "rawLog"


@dataclass
class SourceColumns:
    arena: np.ndarray            # uint8 flat
    offsets: np.ndarray          # int64 [N]
    lengths: np.ndarray          # int32 [N]
    columnar: bool               # True → spans index the group's arena
    present: np.ndarray          # bool [N] source field existed
    from_content: bool = False   # True → spans are the raw content column


def extract_source(group: PipelineEventGroup,
                   source_key: bytes = DEFAULT_CONTENT_KEY
                   ) -> Optional[SourceColumns]:
    """Returns the source field of every event as span columns."""
    cols = group.columns
    if cols is not None and not group._events:
        skey = source_key.decode() if isinstance(source_key, bytes) else source_key
        from_content = False
        if skey in cols.fields:
            offs, lens = cols.fields[skey]
            present = lens >= 0
        elif (skey == "content" and not cols.content_consumed) or not cols.fields:
            offs, lens = cols.offsets, cols.lengths
            present = np.ones(len(cols), dtype=bool)
            from_content = True
        else:
            return None
        arena = group.source_buffer.as_array()
        return SourceColumns(arena, offs.astype(np.int64), lens, True, present,
                             from_content)

    # row path: pack source values into a scratch arena
    values: List[bytes] = []
    present: List[bool] = []
    for ev in group.events:
        if isinstance(ev, LogEvent):
            v = ev.get_content(source_key)
        elif isinstance(ev, RawEvent):
            v = ev.content
        else:
            v = None
        if v is None:
            values.append(b"")
            present.append(False)
        else:
            values.append(v.to_bytes())
            present.append(True)
    if not values:
        return None
    blob = b"".join(values)
    arena = np.frombuffer(blob, dtype=np.uint8) if blob else np.zeros(0, np.uint8)
    lengths = np.array([len(v) for v in values], dtype=np.int32)
    offsets = np.concatenate([[0], np.cumsum(lengths[:-1], dtype=np.int64)]) \
        if len(values) else np.zeros(0, np.int64)
    return SourceColumns(arena, offsets.astype(np.int64), lengths, False,
                         np.array(present, dtype=bool))


def subset_source(src: SourceColumns, rowmap: np.ndarray) -> SourceColumns:
    """Row-subset view of a SourceColumns (loongresident: a fused run's
    member applies after a filter member compacted the group — the
    original packed-row arrays re-index through the run's rowmap)."""
    if len(rowmap) == len(src.offsets) \
            and bool((rowmap == np.arange(len(rowmap))).all()):
        return src
    return SourceColumns(src.arena, src.offsets[rowmap],
                         src.lengths[rowmap], src.columnar,
                         src.present[rowmap], src.from_content)


def apply_parse_spans(group, src, res, keys, keep_on_fail: bool,
                      keep_on_success: bool, renamed_source_key: str,
                      source_key=None) -> None:
    """Columnar install of device parse results — shared by the regex and
    delimiter processors so the subtle parts (all-ok fast path, span_matrix
    preservation, keep-source mask algebra, content consumption) cannot
    diverge between them."""
    import numpy as np

    cols = group.columns
    ok = res.ok & src.present
    nkeys = min(len(keys), res.cap_len.shape[1])
    # one [N, K] mask at most; all-matched groups (the steady state) install
    # the kernel matrices as-is and keep the serializer's zero-transpose
    # span_matrix fast path
    all_ok = bool(ok.all())
    if all_ok:
        len_mat = res.cap_len[:, :nkeys]
    else:
        len_mat = np.where(ok[:, None], res.cap_len[:, :nkeys],
                           np.int32(-1))
    cols.set_fields_matrix(keys[:nkeys], res.cap_off[:, :nkeys], len_mat)
    # consume a NAMED source BEFORE the keep machinery re-adds the raw
    # bytes — with RenamedSourceKey == SourceKey the re-added field must
    # survive (reference DelContent-then-AddLog ordering)
    if not src.from_content and source_key is not None:
        consume_named_source(cols, source_key, keys[:nkeys])
    # source retention
    if keep_on_fail and keep_on_success:
        keep = src.present
    elif keep_on_fail:
        keep = (~ok) & src.present
    elif keep_on_success:
        keep = ok & src.present
    else:
        keep = np.zeros(len(ok), dtype=bool)
    if keep.any():
        cols.set_field(renamed_source_key, src.offsets.astype(np.int32),
                       np.where(keep, src.lengths, -1).astype(np.int32))
    cols.parse_ok = ok
    if src.from_content:
        cols.content_consumed = True
    if not all_ok and bool((~ok & src.present).any()):
        from ..monitor.alarms import AlarmLevel, AlarmManager, AlarmType
        AlarmManager.instance().send_alarm(
            AlarmType.PARSE_LOG_FAIL,
            "events failed to parse (kept as rawLog when configured)",
            AlarmLevel.WARNING)


def finish_row_keep(ev, raw, parse_ok: bool, source_key: bytes,
                    overwritten: bool, keep_on_fail: bool,
                    keep_on_success: bool, renamed: bytes) -> None:
    """Row-path keep/discard tail shared by the regex and delimiter
    processors (reference ProcessEvent ordering): delete the source unless
    a successful parse overwrote it, then re-add the captured raw bytes
    under the renamed key per the keep flags."""
    if parse_ok:
        if not overwritten:
            ev.del_content(source_key)
        if keep_on_success and raw is not None:
            ev.set_content(renamed, raw)
    else:
        ev.del_content(source_key)
        if keep_on_fail and raw is not None:
            ev.set_content(renamed, raw)


def append_side_arena(source_buffer, side, arena_len: int) -> int:
    """loongstruct side-arena install, shared by the JSON and delimiter
    processors so the sentinel contract cannot diverge: the native parse
    emits rewritten bytes (escape decodes, CSV collapses/joins) into a
    side buffer with span offsets encoded as arena_len + side_offset;
    append those bytes to the source buffer ONCE and return the rebase
    delta for rebase_side_spans.  A zero return is valid (the side bytes
    happened to land exactly at arena_len)."""
    if not len(side):
        return 0
    base = source_buffer.allocate(len(side))
    source_buffer.write_at(base, side.tobytes())
    return base - arena_len


def rebase_side_spans(offs: np.ndarray, lens: np.ndarray, arena_len: int,
                      rebase: int) -> np.ndarray:
    """Shift side-sentinel offsets (>= arena_len, len >= 0) by `rebase`,
    vectorised; returns offs unchanged when nothing needs shifting.
    Absent slots (len < 0) may hold uninitialised offsets and must never
    be touched."""
    if not rebase:
        return offs
    sidep = (lens >= 0) & (offs >= arena_len)
    if not sidep.any():
        return offs
    return offs + np.where(sidep, np.int32(rebase), 0)


def consume_named_source(cols, source_key, parsed_key_names) -> None:
    """Reference DelContent for a NAMED source field: drop it unless one of
    the parsed keys overwrote that very name.  Callers must run this
    BEFORE re-adding the kept raw source under RenamedSourceKey, or the
    RenamedSourceKey == SourceKey configuration destroys what it kept."""
    skey = source_key.decode("utf-8", "replace") \
        if isinstance(source_key, bytes) else source_key
    if skey not in parsed_key_names:
        cols.fields.pop(skey, None)
        cols.span_matrix = None
