"""Inner processor: decode PB-encoded LogGroups back into events.

Reference: core/plugin/processor/inner/ProcessorParseFromPBNative.cpp —
the forward path (gRPC ingest, agent-to-agent transfer) carries serialized
SLS LogGroup bytes; this processor expands them into ordinary events so
the rest of the pipeline sees what the sending agent saw.

Decoding reuses the serializer module's wire reader (the exact inverse of
the SLS serializer, differentially tested against it).
"""

from __future__ import annotations

from typing import Any, Dict, List

from ..models import LogEvent, PipelineEventGroup, RawEvent
from ..pipeline.plugin.interface import PluginContext, Processor
from ..pipeline.serializer.sls_serializer import parse_loggroup
from ..utils.logger import get_logger

log = get_logger("parse_from_pb")


class ProcessorParseFromPB(Processor):
    name = "processor_parse_from_pb_native"

    def __init__(self) -> None:
        super().__init__()
        self.source_key = b"content"

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.source_key = config.get("SourceKey", "content").encode()
        return True

    def process(self, group: PipelineEventGroup) -> None:
        payloads: List[bytes] = []
        keep = []
        for ev in group.events:
            data = None
            if isinstance(ev, RawEvent) and ev.content is not None:
                data = ev.content.to_bytes()
            elif isinstance(ev, LogEvent):
                v = ev.get_content(self.source_key)
                if v is not None:
                    data = v.to_bytes()
            if data is None:
                keep.append(ev)
                continue
            payloads.append(data)
        if not payloads:
            return
        group._events = keep
        for data in payloads:
            try:
                # decode straight into THIS group's buffer: each string is
                # copied exactly once on the forward ingest path
                parse_loggroup(data, group=group)
            except (ValueError, IndexError) as e:
                log.warning("undecodable LogGroup payload (%d bytes): %s",
                            len(data), e)
