"""Parse-fallback observability (loongstruct satellite).

The structural-index plane keeps well-formed rows off per-row Python; the
rows it CANNOT prove well-formed fall back per row — correct, but 100-1000x
slower per row.  A sustained malformed-row rate is therefore a silent
throughput collapse in the making (the same failure mode loongfuse's
`regex_tier_demotions` exists to surface on the regex tier), so every
fallback row is counted here:

* ``parse_fallback_rows_total`` / ``parse_rows_total`` counters on a
  per-processor MetricsRecord (exported through the exposition endpoint
  with ``processor=<plugin>`` labels);
* a one-shot ``PARSE_FALLBACK_DEGRADED`` alarm per (processor, pipeline)
  once the observed fallback rate is sustained (>= MIN_ROWS rows seen AND
  fallback fraction >= RATE_THRESHOLD), naming the pipeline and plugin;
* ``status()`` feeds the ``parse`` section of /debug/status.
"""

from __future__ import annotations

import threading
from typing import Dict, Tuple

#: alarm once a processor/pipeline has seen this many rows...
MIN_ROWS = 1024
#: ...with at least this fraction falling back per row
RATE_THRESHOLD = 0.05

_lock = threading.Lock()
_rows: Dict[Tuple[str, str], int] = {}
_fallback: Dict[Tuple[str, str], int] = {}
_drift: Dict[Tuple[str, str], int] = {}
_alarmed: set = set()
_records: Dict[str, object] = {}


def _metrics(processor: str):
    rec = _records.get(processor)
    if rec is None:
        # double-checked under the module lock: MetricsRecord.__init__
        # registers itself in WriteMetrics, so a racing double-create
        # would leave an orphaned duplicate series on /metrics
        from ..monitor.metrics import MetricsRecord
        with _lock:
            rec = _records.get(processor)
            if rec is None:
                rec = MetricsRecord(category="component",
                                    labels={"component": "loongstruct",
                                            "processor": processor})
                _records[processor] = rec
    return rec


def note_rows(processor: str, pipeline: str, total: int,
              fallback: int, drift: int = 0) -> None:
    """Account one group's parse outcome.  `fallback` = rows that left the
    structural plane for per-row Python; `drift` = rows parsed on-plane
    with schema drift (extras columns)."""
    if total <= 0:
        return
    try:
        rec = _metrics(processor)
        rec.counter("parse_rows_total").add(total)
        if fallback:
            rec.counter("parse_fallback_rows_total").add(fallback)
        if drift:
            rec.counter("parse_drift_rows_total").add(drift)
    except Exception:  # noqa: BLE001 — accounting must never break parsing
        pass
    key = (processor, pipeline)
    fire = False
    with _lock:
        _rows[key] = _rows.get(key, 0) + total
        _fallback[key] = _fallback.get(key, 0) + fallback
        if drift:
            _drift[key] = _drift.get(key, 0) + drift
        seen, fb = _rows[key], _fallback[key]
        if key not in _alarmed and seen >= MIN_ROWS \
                and fb >= seen * RATE_THRESHOLD:
            _alarmed.add(key)
            fire = True
    if fire:
        # outside _lock (loonglint blocking-under-lock rule)
        try:
            from ..monitor.alarms import AlarmLevel, AlarmManager, AlarmType
            AlarmManager.instance().send_alarm(
                AlarmType.PARSE_FALLBACK_DEGRADED,
                f"sustained per-row parse fallback on {processor}: "
                f"{fb}/{seen} rows off the structural plane",
                AlarmLevel.ERROR, pipeline=pipeline,
                details={"processor": processor,
                         "fallback_rows": str(fb), "rows": str(seen)})
        except Exception:  # noqa: BLE001
            pass


def status() -> Dict[str, object]:
    """The /debug/status `parse` section: per-(processor, pipeline) row /
    fallback / drift totals plus which pairs have alarmed."""
    with _lock:
        rows = dict(_rows)
        fallback = dict(_fallback)
        drift = dict(_drift)
        alarmed = set(_alarmed)
    out = {}
    for key, seen in rows.items():
        label = "/".join(k for k in key if k) or key[0]
        out[label] = {
            "rows": seen,
            "fallback_rows": fallback.get(key, 0),
            "drift_rows": drift.get(key, 0),
            "degraded": key in alarmed,
        }
    return out


def reset_for_testing() -> None:
    """Clear accumulated state (counters records persist — they are
    process-lifetime instruments, like shared_histogram's)."""
    with _lock:
        _rows.clear()
        _fallback.clear()
        _drift.clear()
        _alarmed.clear()
