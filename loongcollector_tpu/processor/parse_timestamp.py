"""processor_parse_timestamp — event-time rewrite from a time field.

Reference: core/plugin/processor/ProcessorParseTimestampNative.cpp
(strptime-class parsing via common/Strptime.h, rewrites event timestamps).

Host execution with a per-batch memo: log streams repeat second-resolution
timestamps heavily, so unique-value caching makes this one strptime per
distinct string (the reference relies on a similar cached-second fast path).
"""

from __future__ import annotations

import time
from typing import Any, Dict

import numpy as np

from ..monitor.alarms import (AlarmLevel, AlarmManager,
                              AlarmType)
from ..models import PipelineEventGroup
from ..pipeline.plugin.interface import PluginContext, Processor
from .common import extract_source


class ProcessorParseTimestamp(Processor):
    name = "processor_parse_timestamp_native"
    supports_columnar = True

    def __init__(self) -> None:
        super().__init__()
        self.source_key = b"time"
        self.source_format = "%Y-%m-%d %H:%M:%S"
        self.source_timezone_offset = None  # seconds east of UTC, None=local
        self._memo: Dict[bytes, int] = {}

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.source_key = config.get("SourceKey", "time").encode()
        self.source_format = config.get("SourceFormat", "%Y-%m-%d %H:%M:%S")
        tz = config.get("SourceTimezone")  # e.g. "GMT+08:00"
        if tz:
            sign = 1 if "+" in tz else -1
            hh_mm = tz.split("+")[-1].split("-")[-1]
            try:
                hh, mm = hh_mm.split(":")
                self.source_timezone_offset = sign * (int(hh) * 3600 + int(mm) * 60)
            except ValueError:
                self.source_timezone_offset = None
        return True

    def _alarm_fail(self) -> None:
        AlarmManager.instance().send_alarm(
            AlarmType.PARSE_TIME_FAIL,
            f"timestamp parse failed (format {self.source_format!r})",
            AlarmLevel.WARNING)

    def _parse_one(self, data: bytes) -> int:
        ts = self._memo.get(data)
        if ts is not None:
            if ts < 0:
                # memoized FAILURE: still alarm, or the aggregated count
                # undercounts a stream of identical bad values by memo-hits
                self._alarm_fail()
            return ts
        try:
            st = time.strptime(data.decode("utf-8", "replace"), self.source_format)
            if self.source_timezone_offset is not None:
                import calendar
                ts = int(calendar.timegm(st)) - self.source_timezone_offset
            else:
                ts = int(time.mktime(st))
        except ValueError:
            ts = -1
            self._alarm_fail()
        if len(self._memo) > 4096:
            self._memo.clear()
        self._memo[data] = ts
        return ts

    def process(self, group: PipelineEventGroup) -> None:
        src = extract_source(group, self.source_key)
        if src is None:
            return
        if src.columnar:
            cols = group.columns
            raw = src.arena
            tss = cols.timestamps
            for i in range(len(src.offsets)):
                if not src.present[i]:
                    continue
                o, ln = int(src.offsets[i]), int(src.lengths[i])
                ts = self._parse_one(raw[o : o + ln].tobytes())
                if ts >= 0:
                    tss[i] = ts
            return
        for ev in group.events:
            if not hasattr(ev, "get_content"):
                continue
            v = ev.get_content(self.source_key)
            if v is None:
                continue
            ts = self._parse_one(v.to_bytes())
            if ts >= 0:
                ev.timestamp = ts
