"""processor_timestamp_filter — drop events outside a time window.

Reference: core/plugin/processor/ProcessorTimestampFilterNative.cpp (260 LoC)
— relative or absolute bounds on event time.  Columnar path is a pure
vectorised compare + compaction.
"""

from __future__ import annotations

import time
from typing import Any, Dict

import numpy as np

from ..models import PipelineEventGroup
from ..pipeline.plugin.interface import PluginContext, Processor
from .filter import compact_columns


class ProcessorTimestampFilter(Processor):
    name = "processor_timestamp_filter_native"
    supports_columnar = True

    def __init__(self) -> None:
        super().__init__()
        self.start = None   # absolute epoch seconds
        self.end = None
        self.relative_window = None  # keep events within last N seconds

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        if "StartTime" in config:
            self.start = int(config["StartTime"])
        if "EndTime" in config:
            self.end = int(config["EndTime"])
        if "RelativeWindowSeconds" in config:
            self.relative_window = int(config["RelativeWindowSeconds"])
        return (self.start is not None or self.end is not None
                or self.relative_window is not None)

    def process(self, group: PipelineEventGroup) -> None:
        now = int(time.time())
        lo = self.start if self.start is not None else -(1 << 62)
        hi = self.end if self.end is not None else (1 << 62)
        if self.relative_window is not None:
            lo = max(lo, now - self.relative_window)
        cols = group.columns
        if cols is not None and not group._events:
            ts = cols.timestamps
            keep = (ts >= lo) & (ts <= hi)
            if not keep.all():
                group.set_columns(compact_columns(cols, np.asarray(keep)))
            return
        group._events = [ev for ev in group.events
                         if lo <= ev.timestamp <= hi]
