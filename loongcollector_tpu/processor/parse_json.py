"""processor_parse_json — expand a JSON-object field into event fields.

Reference: core/plugin/processor/ProcessorParseJsonNative.cpp (rapidjson
parse of one key into fields, keep/discard semantics shared with regex
parser).

Execution: stable-schema events extract in one native C pass with zero-copy
value spans (raw source tokens: numbers/bools keep their source spelling);
events with escaped strings, schema drift or malformed JSON fall back to the
host json parser, whose values are canonicalised (str()/json.dumps) — the
two representations differ only in number/whitespace spelling of unusual
inputs.
"""

from __future__ import annotations

import json
from typing import Any, Dict

import numpy as np

from ..models import ColumnarLogs, PipelineEventGroup
from ..pipeline.plugin.interface import PluginContext, Processor
from .common import RAW_LOG_KEY, extract_source


class ProcessorParseJson(Processor):
    name = "processor_parse_json_tpu"
    supports_columnar = True

    def __init__(self) -> None:
        super().__init__()
        self.source_key = b"content"
        self.keep_source_on_fail = True
        self.keep_source_on_success = False
        self.renamed_source_key = RAW_LOG_KEY

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.source_key = config.get("SourceKey", "content").encode()
        self.keep_source_on_fail = bool(config.get("KeepingSourceWhenParseFail", True))
        self.keep_source_on_success = bool(config.get("KeepingSourceWhenParseSucceed", False))
        self.renamed_source_key = config.get("RenamedSourceKey", RAW_LOG_KEY)
        return True

    def process(self, group: PipelineEventGroup) -> None:
        src = extract_source(group, self.source_key)
        if src is None:
            return
        n = len(src.offsets)
        if src.columnar:
            sb = group.source_buffer
            cols = group.columns
            ok = np.zeros(n, dtype=bool)
            field_offs: Dict[str, np.ndarray] = {}
            field_lens: Dict[str, np.ndarray] = {}
            raw = src.arena

            # native fast path: discover the schema from the first parseable
            # event, then extract all stable-schema events in one C pass;
            # escaped strings / unknown keys / malformed events fall back
            # per-event below
            todo = np.nonzero(src.present)[0]
            keys = self._discover_schema(raw, src, todo)
            if keys is not None:
                from .. import native as _native
                res = _native.json_extract(raw, src.offsets, src.lengths, keys)
                if res is not None:
                    f_offs, f_lens, c_ok, _ = res
                    c_ok = c_ok & src.present
                    for fi, k in enumerate(keys):
                        name = k.decode("utf-8", "replace")
                        field_offs[name] = f_offs[fi].copy()
                        field_lens[name] = np.where(c_ok, f_lens[fi], -1)
                    ok |= c_ok
                    todo = np.nonzero(src.present & ~c_ok)[0]
            for i in todo:
                o, ln = int(src.offsets[i]), int(src.lengths[i])
                try:
                    obj = json.loads(raw[o : o + ln].tobytes())
                    if not isinstance(obj, dict):
                        raise ValueError
                except Exception:  # noqa: BLE001
                    continue
                ok[i] = True
                for k, v in obj.items():
                    if k not in field_offs:
                        field_offs[k] = np.zeros(n, dtype=np.int32)
                        field_lens[k] = np.full(n, -1, dtype=np.int32)
                    if isinstance(v, str):
                        vb = v.encode("utf-8")
                    elif isinstance(v, (dict, list)):
                        vb = json.dumps(v, ensure_ascii=False).encode("utf-8")
                    elif isinstance(v, bool):
                        vb = b"true" if v else b"false"
                    elif v is None:
                        vb = b"null"
                    else:
                        vb = str(v).encode("utf-8")
                    view = sb.copy_string(vb)
                    field_offs[k][i] = view.offset
                    field_lens[k][i] = view.length
            for k in field_offs:
                cols.set_field(k, field_offs[k], field_lens[k])
            if not src.from_content:
                from .common import consume_named_source
                consume_named_source(cols, self.source_key,
                                     set(field_offs))
            self._retain_source(cols, src, ok)
            cols.parse_ok = ok
            if src.from_content:
                cols.content_consumed = True
            return

        # row path keep/discard: the shared reference ordering (capture
        # raw, delete unless overwritten, re-add under the renamed key)
        from .common import finish_row_keep
        sb = group.source_buffer
        renamed = self.renamed_source_key.encode()
        for ev in group.events:
            if not hasattr(ev, "get_content"):
                continue
            raw = ev.get_content(self.source_key)
            if raw is None:
                continue
            try:
                obj = json.loads(raw.to_bytes())
                if not isinstance(obj, dict):
                    raise ValueError
            except Exception:  # noqa: BLE001
                finish_row_keep(ev, raw, False, self.source_key, False,
                                self.keep_source_on_fail,
                                self.keep_source_on_success, renamed)
                continue
            overwritten = False
            for k, val in obj.items():
                if not isinstance(val, str):
                    val = json.dumps(val, ensure_ascii=False) \
                        if isinstance(val, (dict, list)) else \
                        ("true" if val is True else "false" if val is False
                         else "null" if val is None else str(val))
                kb = k.encode() if isinstance(k, str) else k
                ev.set_content(sb.copy_string(kb), sb.copy_string(val))
                if kb == self.source_key:
                    overwritten = True
            finish_row_keep(ev, raw, True, self.source_key, overwritten,
                            self.keep_source_on_fail,
                            self.keep_source_on_success, renamed)

    @staticmethod
    def _discover_schema(raw, src, candidates):
        for i in candidates[:4]:
            o, ln = int(src.offsets[i]), int(src.lengths[i])
            try:
                obj = json.loads(raw[o : o + ln].tobytes())
            except ValueError:
                continue
            if isinstance(obj, dict) and obj and len(obj) <= 128:
                return [k.encode("utf-8") for k in obj.keys()]
        return None

    def _retain_source(self, cols: ColumnarLogs, src, ok: np.ndarray) -> None:
        if self.keep_source_on_fail and self.keep_source_on_success:
            keep = src.present
        elif self.keep_source_on_fail:
            keep = (~ok) & src.present
        elif self.keep_source_on_success:
            keep = ok & src.present
        else:
            keep = np.zeros(len(ok), dtype=bool)
        if keep.any():
            cols.set_field(self.renamed_source_key,
                           src.offsets.astype(np.int32),
                           np.where(keep, src.lengths, -1).astype(np.int32))
