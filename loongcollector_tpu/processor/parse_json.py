"""processor_parse_json — expand a JSON-object field into event fields.

Reference: core/plugin/processor/ProcessorParseJsonNative.cpp (rapidjson
parse of one key into fields, keep/discard semantics shared with regex
parser).

Execution (loongstruct): columnar groups parse on the structural-index
plane — `lct_json_struct_parse` classifies every row into per-bit
structural bitmaps (simdjson-style escape-carry + in-string prefix-XOR)
and emits field spans straight from the index, so schema-stable AND
schema-drifting AND escape-bearing rows all stay on the columnar
zero-materialization path: string values keep zero-copy spans, escaped
values decode ONCE into a per-group side arena (appended to the source
buffer in one allocation, never per event), unknown keys install from the
CSR extras stream.  Rows the index cannot prove well-formed fall back to
per-row `json.loads` — counted in `parse_fallback_rows_total` and alarmed
via PARSE_FALLBACK_DEGRADED when sustained (docs/performance.md
"Structural-index parsing").  Values are raw source tokens
(numbers/bools keep their source spelling); the fallback canonicalises
via str()/json.dumps — the two differ only in number/whitespace spelling
of unusual inputs.  ``LOONG_STRUCT=0`` disables the structural plane
(the pre-loongstruct schema-discovery path; the bench's r09-style
comparator).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict

import numpy as np

from ..models import ColumnarLogs, PipelineEventGroup
from ..pipeline.plugin.interface import PluginContext, Processor
from .common import RAW_LOG_KEY, extract_source


def _struct_enabled() -> bool:
    return os.environ.get("LOONG_STRUCT", "1") != "0"


class ProcessorParseJson(Processor):
    name = "processor_parse_json_tpu"
    supports_columnar = True

    def __init__(self) -> None:
        super().__init__()
        self.source_key = b"content"
        self.keep_source_on_fail = True
        self.keep_source_on_success = False
        self.renamed_source_key = RAW_LOG_KEY
        self._pipeline = ""
        self._struct = _struct_enabled()

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.source_key = config.get("SourceKey", "content").encode()
        self.keep_source_on_fail = bool(config.get("KeepingSourceWhenParseFail", True))
        self.keep_source_on_success = bool(config.get("KeepingSourceWhenParseSucceed", False))
        self.renamed_source_key = config.get("RenamedSourceKey", RAW_LOG_KEY)
        self._pipeline = getattr(context, "pipeline_name", "") or ""
        self._struct = _struct_enabled()
        return True

    def process(self, group: PipelineEventGroup) -> None:
        src = extract_source(group, self.source_key)
        if src is None:
            return
        n = len(src.offsets)
        if src.columnar:
            cols = group.columns
            ok = np.zeros(n, dtype=bool)
            field_offs: Dict[str, np.ndarray] = {}
            field_lens: Dict[str, np.ndarray] = {}
            raw = src.arena

            todo = np.nonzero(src.present)[0]
            keys = self._discover_schema(raw, src, todo)
            handled = False
            drift_rows = 0
            if keys is not None and self._struct:
                handled, drift_rows = self._process_struct(
                    group, src, raw, keys, ok, field_offs, field_lens)
            if not handled and keys is not None:
                # r09-style plane (LOONG_STRUCT=0 / native unavailable):
                # one stable-schema native pass, everything else per row
                from .. import native as _native
                res = _native.json_extract(raw, src.offsets, src.lengths,
                                           keys)
                if res is not None:
                    f_offs, f_lens, c_ok, _ = res
                    c_ok = c_ok & src.present
                    for fi, k in enumerate(keys):
                        name = k.decode("utf-8", "replace")
                        field_offs[name] = f_offs[fi].copy()
                        field_lens[name] = np.where(c_ok, f_lens[fi], -1)
                    ok |= c_ok
            todo = np.nonzero(src.present & ~ok)[0]
            self._fallback_rows(group, src, raw, todo, ok,
                                field_offs, field_lens, count=handled,
                                drift_rows=drift_rows)
            for k in field_offs:
                cols.set_field(k, field_offs[k], field_lens[k])
            if not src.from_content:
                from .common import consume_named_source
                consume_named_source(cols, self.source_key,
                                     set(field_offs))
            self._retain_source(cols, src, ok)
            cols.parse_ok = ok
            if src.from_content:
                cols.content_consumed = True
            return

        self._process_rows(group)

    # -- structural-index plane --------------------------------------------

    def _process_struct(self, group, src, raw, keys, ok,
                        field_offs, field_lens) -> bool:
        """Columnar parse via lct_json_struct_parse.  Returns
        (handled, drift_row_count); handled False when the native plane is
        unavailable (caller uses the r09-style path).  On success,
        `ok`/field dicts hold every row except the counted per-row
        fallbacks (still False in `ok`)."""
        from .. import native as _native
        res = _native.json_struct_parse(raw, src.offsets, src.lengths, keys)
        if res is None:
            return False, 0
        f_offs, f_lens, status, side, extras = res
        arena_len = len(raw)
        n = len(status)
        sb = group.source_buffer

        # one side-arena append per group: decoded escape bytes land in the
        # source buffer ONCE; side-sentinel offsets rebase vectorised
        from .common import append_side_arena, rebase_side_spans
        rebase = append_side_arena(sb, side, arena_len)
        c_ok = (status != 1) & src.present
        all_ok = bool(c_ok.all())
        for fi, k in enumerate(keys):
            name = k.decode("utf-8", "replace")
            lens_f = f_lens[fi]
            offs_f = rebase_side_spans(f_offs[fi], lens_f, arena_len,
                                       rebase)
            field_offs[name] = offs_f
            # steady state (every row parsed): install the kernel columns
            # as-is instead of re-masking them per field
            field_lens[name] = lens_f if all_ok \
                else np.where(c_ok, lens_f, -1)
        # schema drift: unknown keys arrive as a CSR extras stream of raw
        # spans — installed as columns without touching json.loads
        e_rows, e_koffs, e_klens, e_voffs, e_vlens = extras
        for j in range(len(e_rows)):
            i = int(e_rows[j])
            kb = raw[int(e_koffs[j]): int(e_koffs[j]) + int(e_klens[j])]
            name = kb.tobytes().decode("utf-8", "replace")
            if name not in field_offs:
                field_offs[name] = np.zeros(n, dtype=np.int32)
                field_lens[name] = np.full(n, -1, dtype=np.int32)
            vo = int(e_voffs[j])
            if vo >= arena_len:
                vo += rebase
            field_offs[name][i] = vo
            field_lens[name][i] = int(e_vlens[j])
        ok |= c_ok
        return True, int((status == 2).sum())

    def _fallback_rows(self, group, src, raw, todo, ok,
                       field_offs, field_lens, count: bool,
                       drift_rows: int = 0) -> None:
        """Per-row json.loads for rows the index could not prove
        well-formed.  The ONLY per-row Python left on this processor —
        counted, and alarmed when sustained."""
        n = len(src.offsets)
        sb = group.source_buffer
        n_fallback = 0
        for i in todo:
            n_fallback += 1
            o, ln = int(src.offsets[i]), int(src.lengths[i])
            try:
                # the counted fallback tier the structural plane demotes
                # malformed rows to (parse_fallback_rows_total)
                # loonglint: disable=per-row-parse
                obj = json.loads(raw[o : o + ln].tobytes())
                if not isinstance(obj, dict):
                    raise ValueError
            except Exception:  # noqa: BLE001
                continue
            ok[i] = True
            for k, v in obj.items():
                if k not in field_offs:
                    field_offs[k] = np.zeros(n, dtype=np.int32)
                    field_lens[k] = np.full(n, -1, dtype=np.int32)
                if isinstance(v, str):
                    vb = v.encode("utf-8")
                elif isinstance(v, (dict, list)):
                    vb = json.dumps(v, ensure_ascii=False).encode("utf-8")
                elif isinstance(v, bool):
                    vb = b"true" if v else b"false"
                elif v is None:
                    vb = b"null"
                else:
                    vb = str(v).encode("utf-8")
                view = sb.copy_string(vb)
                field_offs[k][i] = view.offset
                field_lens[k][i] = view.length
        if count:
            from . import parse_telemetry
            parse_telemetry.note_rows(self.name, self._pipeline,
                                      int(src.present.sum()), n_fallback,
                                      drift=drift_rows)

    # -- row path -----------------------------------------------------------

    def _process_rows(self, group: PipelineEventGroup) -> None:
        # row path keep/discard: the shared reference ordering (capture
        # raw, delete unless overwritten, re-add under the renamed key)
        from .common import finish_row_keep
        sb = group.source_buffer
        renamed = self.renamed_source_key.encode()
        for ev in group.events:
            if not hasattr(ev, "get_content"):
                continue
            raw = ev.get_content(self.source_key)
            if raw is None:
                continue
            try:
                # non-columnar groups (per-event plugins upstream) have no
                # arena to index
                # loonglint: disable=per-row-parse
                obj = json.loads(raw.to_bytes())
                if not isinstance(obj, dict):
                    raise ValueError
            except Exception:  # noqa: BLE001
                finish_row_keep(ev, raw, False, self.source_key, False,
                                self.keep_source_on_fail,
                                self.keep_source_on_success, renamed)
                continue
            overwritten = False
            for k, val in obj.items():
                if not isinstance(val, str):
                    val = json.dumps(val, ensure_ascii=False) \
                        if isinstance(val, (dict, list)) else \
                        ("true" if val is True else "false" if val is False
                         else "null" if val is None else str(val))
                kb = k.encode() if isinstance(k, str) else k
                ev.set_content(sb.copy_string(kb), sb.copy_string(val))
                if kb == self.source_key:
                    overwritten = True
            finish_row_keep(ev, raw, True, self.source_key, overwritten,
                            self.keep_source_on_fail,
                            self.keep_source_on_success, renamed)

    @staticmethod
    def _discover_schema(raw, src, candidates):
        for i in candidates[:4]:
            o, ln = int(src.offsets[i]), int(src.lengths[i])
            try:
                # bounded schema probe (<= 4 rows per group), not a tail
                # loonglint: disable=per-row-parse
                obj = json.loads(raw[o : o + ln].tobytes())
            except ValueError:
                continue
            if isinstance(obj, dict) and obj and len(obj) <= 128:
                return [k.encode("utf-8") for k in obj.keys()]
        return None

    def _retain_source(self, cols: ColumnarLogs, src, ok: np.ndarray) -> None:
        if self.keep_source_on_fail and self.keep_source_on_success:
            keep = src.present
        elif self.keep_source_on_fail:
            keep = (~ok) & src.present
        elif self.keep_source_on_success:
            keep = ok & src.present
        else:
            keep = np.zeros(len(ok), dtype=bool)
        if keep.any():
            cols.set_field(self.renamed_source_key,
                           src.offsets.astype(np.int32),
                           np.where(keep, src.lengths, -1).astype(np.int32))
