"""Inner processor: split a raw file chunk into per-line events — columnar.

Reference: core/plugin/processor/inner/ProcessorSplitLogStringNative.cpp —
the file reader emits ONE RawEvent per read chunk (zero-copy,
LogFileReader.cpp:2726); this processor slices it into per-line events.

TPU-first: the output is a ColumnarLogs (offset/length arrays over the SAME
arena) — no per-line Python objects, ready for device batch packing.  Line
boundary discovery is one vectorised numpy pass (np.where on the byte
array), the host-side analogue of a memchr sweep.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List

import numpy as np

from ..models import ColumnarLogs, PipelineEventGroup, RawEvent
from ..native import split_lines as native_split
from ..pipeline.plugin.interface import PluginContext, Processor


def split_chunk_spans(arena: np.ndarray, start: int, ln: int,
                      split_char: int):
    """Line spans (offsets int64, lengths int32) of one chunk at
    [start, start+ln) in the arena — native pass with the vectorised
    numpy fallback.  Shared with the file reader's columnar group
    assembly (loongcolumn) so reader-side and processor-side splitting
    cannot diverge."""
    seg = arena[start : start + ln]
    spans = native_split(seg, split_char, start)
    if spans is not None:
        offs, lens = spans
        return offs.astype(np.int64), lens
    nl = np.nonzero(seg == split_char)[0].astype(np.int64)
    # line starts: 0 and nl+1; line ends: nl and ln (if trailing bytes)
    starts = np.concatenate([[0], nl + 1])
    ends = np.concatenate([nl, [ln]])
    # empty lines between separators are kept (reference behaviour);
    # only the zero-length tail produced by a trailing \n is dropped
    if len(starts) > 1 and starts[-1] >= ln:
        starts = starts[:-1]
        ends = ends[:-1]
    return starts + start, (ends - starts).astype(np.int32)


class ProcessorSplitLogString(Processor):
    name = "processor_split_log_string_native"
    supports_columnar = True

    def __init__(self) -> None:
        super().__init__()
        self.split_char = ord("\n")
        self.append_new_line_when_missing = False

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        ch = config.get("SplitChar", "\n")
        self.split_char = ord(ch) if isinstance(ch, str) else int(ch)
        return True

    def process(self, group: PipelineEventGroup) -> None:
        if group.columns is not None and not group._events:
            return  # already split
        raw_events = [ev for ev in group.events if isinstance(ev, RawEvent)]
        if not raw_events:
            return
        arena = group.source_buffer.as_array()
        all_offsets: List[np.ndarray] = []
        all_lengths: List[np.ndarray] = []
        all_ts: List[np.ndarray] = []
        now = int(time.time())
        for ev in raw_events:
            sv = ev.content
            if sv is None or sv.length == 0:
                continue
            offs, lens = split_chunk_spans(arena, sv.offset, sv.length,
                                           self.split_char)
            all_offsets.append(offs)
            all_lengths.append(lens)
            ts = ev.timestamp if ev.timestamp else now
            all_ts.append(np.full(len(offs), ts, dtype=np.int64))
        if not all_offsets:
            group.set_columns(ColumnarLogs(np.zeros(0, np.int32),
                                           np.zeros(0, np.int32)))
            return
        cols = ColumnarLogs(
            offsets=np.concatenate(all_offsets).astype(np.int32),
            lengths=np.concatenate(all_lengths),
            timestamps=np.concatenate(all_ts))
        group.set_columns(cols)
