"""Inner processor: containerd / docker-json stdout unwrap — columnar.

Reference: core/plugin/processor/inner/ProcessorParseContainerLogNative.cpp
(and the reader's per-format GetLastLine parsers, LogFileReader.cpp:2401-2525):

containerd (CRI) lines:  `2024-01-02T03:04:05.999999999Z stdout P partial…`
  → time, stream (stdout/stderr), flag (P = partial, F = full), content
docker json-file lines:  `{"log":"…\\n","stream":"stdout","time":"…"}`

Partial CRI lines mark `_partial_` for the downstream merge processor
(processor_merge_multiline_log_native, flag mode).

TPU-first: CRI unwrap is pure span arithmetic over the columnar form — the
timestamp/stream/flag fields sit at delimiter-separated offsets, so the
content span is the original arena span minus a computed prefix; no copies.
"""

from __future__ import annotations

import json
from typing import Any, Dict

import numpy as np

from ..models import ColumnarLogs, PipelineEventGroup
from ..pipeline.plugin.interface import PluginContext, Processor
from .merge_multiline import PARTIAL_FLAG_FIELD

_STDOUT = b"stdout"
_STDERR = b"stderr"


class ProcessorParseContainerLog(Processor):
    name = "processor_parse_container_log_native"
    supports_columnar = True
    requires_columnar = True

    def __init__(self) -> None:
        super().__init__()
        self.format = "containerd_text"  # or docker_json-file
        self.ignore_stdout = False
        self.ignore_stderr = False
        self.keep_time = False  # KeepTimestamp: emit _time_ (CRI time span)

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.format = config.get("Format", "containerd_text")
        self.ignore_stdout = bool(config.get("IgnoringStdout", False))
        self.ignore_stderr = bool(config.get("IgnoringStderr", False))
        self.keep_time = bool(config.get("KeepTimestamp", False))
        return True

    def process(self, group: PipelineEventGroup) -> None:
        cols = group.columns
        if cols is None or group._events:
            return
        if self.format == "docker_json-file":
            self._process_docker_json(group, cols)
        else:
            self._process_cri(group, cols)

    # -- containerd CRI text ------------------------------------------------

    def _process_cri(self, group: PipelineEventGroup, cols: ColumnarLogs) -> None:
        arena = group.source_buffer.as_array()
        n = len(cols)
        offs = cols.offsets.astype(np.int64)
        lens = cols.lengths.astype(np.int64)
        keep = np.ones(n, dtype=bool)
        new_offs = cols.offsets.copy()
        new_lens = cols.lengths.copy()
        part_offs = np.zeros(n, dtype=np.int32)
        part_lens = np.full(n, -1, dtype=np.int32)
        stream_offs = np.zeros(n, dtype=np.int32)
        stream_lens = np.full(n, -1, dtype=np.int32)
        time_offs = np.zeros(n, dtype=np.int32)
        time_lens = np.full(n, -1, dtype=np.int32)
        sb = group.source_buffer
        sv_stdout = sb.copy_string(_STDOUT)
        sv_stderr = sb.copy_string(_STDERR)
        sv_partial = sb.copy_string(b"P")
        for i in range(n):
            o, ln = int(offs[i]), int(lens[i])
            line = arena[o : o + ln].tobytes()
            sp1 = line.find(b" ")
            if sp1 < 0:
                continue  # not CRI: leave as-is
            sp2 = line.find(b" ", sp1 + 1)
            if sp2 < 0:
                continue
            stream = line[sp1 + 1 : sp2]
            if stream not in (_STDOUT, _STDERR):
                continue
            if (stream == _STDOUT and self.ignore_stdout) or \
               (stream == _STDERR and self.ignore_stderr):
                keep[i] = False
                continue
            sp3 = line.find(b" ", sp2 + 1)
            flag = line[sp2 + 1 : sp3] if sp3 > 0 else b"F"
            content_start = (sp3 + 1) if sp3 > 0 and flag in (b"P", b"F") else sp2 + 1
            new_offs[i] = o + content_start
            new_lens[i] = ln - content_start
            if flag == b"P":
                part_offs[i] = sv_partial.offset
                part_lens[i] = sv_partial.length
            if self.keep_time:
                time_offs[i] = o
                time_lens[i] = sp1  # zero-copy CRI timestamp span
            sv = sv_stdout if stream == _STDOUT else sv_stderr
            stream_offs[i] = sv.offset
            stream_lens[i] = sv.length
        cols.offsets = new_offs
        cols.lengths = new_lens
        cols.set_field(PARTIAL_FLAG_FIELD, part_offs, part_lens)
        cols.set_field("_source_", stream_offs, stream_lens)
        if self.keep_time:
            cols.set_field("_time_", time_offs, time_lens)
        if not keep.all():
            from .filter import compact_columns
            group.set_columns(compact_columns(cols, keep))

    # -- docker json-file ---------------------------------------------------

    def _process_docker_json(self, group: PipelineEventGroup,
                             cols: ColumnarLogs) -> None:
        arena = group.source_buffer.as_array()
        sb = group.source_buffer
        n = len(cols)
        keep = np.ones(n, dtype=bool)
        new_offs = cols.offsets.copy()
        new_lens = cols.lengths.copy()
        stream_offs = np.zeros(n, dtype=np.int32)
        stream_lens = np.full(n, -1, dtype=np.int32)
        for i in range(n):
            o, ln = int(cols.offsets[i]), int(cols.lengths[i])
            try:
                # docker json-file rows: schema {log,stream,time} is a
                # loongstruct migration candidate (pay-down: route through
                # native.json_struct_parse like processor_parse_json_tpu)
                # loonglint: disable=per-row-parse
                obj = json.loads(arena[o : o + ln].tobytes())
            except ValueError:
                continue
            stream = obj.get("stream", "stdout")
            if (stream == "stdout" and self.ignore_stdout) or \
               (stream == "stderr" and self.ignore_stderr):
                keep[i] = False
                continue
            content = obj.get("log", "")
            if content.endswith("\n"):
                content = content[:-1]
            view = sb.copy_string(content)
            new_offs[i] = view.offset
            new_lens[i] = view.length
            svs = sb.copy_string(stream)
            stream_offs[i] = svs.offset
            stream_lens[i] = svs.length
        cols.offsets = new_offs
        cols.lengths = new_lens
        cols.set_field("_source_", stream_offs, stream_lens)
        if not keep.all():
            from .filter import compact_columns
            group.set_columns(compact_columns(cols, keep))
