"""processor_parse_apsara — Alibaba Apsara log format parser.

Reference: core/plugin/processor/ProcessorParseApsaraNative.cpp — lines like
  [2024-01-02 03:04:05.123456]\t[LEVEL]\t[thread]\t[file:line]\tk1:v1\tk2:v2
Leading microsecond timestamp in brackets, bracketed level/thread/location,
then tab-separated key:value pairs.  Sets the pipeline topic flag in the
reference (CollectionPipeline.cpp:147-149).
"""

from __future__ import annotations

import calendar
import time
from typing import Any, Dict

import numpy as np

from ..models import PipelineEventGroup
from ..pipeline.plugin.interface import PluginContext, Processor
from .common import RAW_LOG_KEY, extract_source


class ProcessorParseApsara(Processor):
    name = "processor_parse_apsara_native"
    supports_columnar = True

    def __init__(self) -> None:
        super().__init__()
        self.source_key = b"content"
        self.keep_source_on_fail = True
        self.renamed_source_key = RAW_LOG_KEY
        self.timezone_offset = None
        self._memo: Dict[bytes, int] = {}

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.source_key = config.get("SourceKey", "content").encode()
        self.keep_source_on_fail = bool(config.get("KeepingSourceWhenParseFail", True))
        tz = config.get("SourceTimezone")
        if tz and ("+" in tz or "-" in tz):
            sign = 1 if "+" in tz else -1
            hh_mm = tz.split("+")[-1].split("-")[-1]
            try:
                hh, mm = hh_mm.split(":")
                self.timezone_offset = sign * (int(hh) * 3600 + int(mm) * 60)
            except ValueError:
                self.timezone_offset = None
        return True

    def _parse_time(self, data: bytes) -> int:
        ts = self._memo.get(data)
        if ts is not None:
            return ts
        txt = data.decode("ascii", "replace")
        try:
            if txt.isdigit():  # epoch (s or us)
                ts = int(txt[:10])
            else:
                st = time.strptime(txt[:19], "%Y-%m-%d %H:%M:%S")
                if self.timezone_offset is not None:
                    ts = int(calendar.timegm(st)) - self.timezone_offset
                else:
                    ts = int(time.mktime(st))
        except ValueError:
            ts = -1
        if len(self._memo) > 4096:
            self._memo.clear()
        self._memo[data] = ts
        return ts

    def _parse_line(self, data: bytes):
        """Returns (ts, fields: list[(k, v)]) or None."""
        if not data.startswith(b"["):
            return None
        end = data.find(b"]")
        if end < 0:
            return None
        ts = self._parse_time(data[1:end])
        if ts < 0:
            return None
        fields = []
        rest = data[end + 1:]
        # bracketed positional fields: level, thread, file:line
        positional = [b"__LEVEL__", b"__THREAD__", b"__FILE__"]
        pi = 0
        while rest.startswith(b"\t[") and pi < len(positional):
            e = rest.find(b"]")
            if e < 0:
                break
            val = rest[2:e]
            if pi == 2 and b":" in val:
                f, _, ln = val.rpartition(b":")
                fields.append((b"__FILE__", f))
                fields.append((b"__LINE__", ln))
            else:
                fields.append((positional[pi], val))
            pi += 1
            rest = rest[e + 1:]
        for part in rest.split(b"\t"):
            if not part:
                continue
            k, sep, v = part.partition(b":")
            if sep:
                fields.append((k, v))
        return ts, fields

    def process(self, group: PipelineEventGroup) -> None:
        src = extract_source(group, self.source_key)
        if src is None:
            return
        sb = group.source_buffer
        if src.columnar:
            cols = group.columns
            n = len(src.offsets)
            raw = src.arena
            field_offs: Dict[bytes, "np.ndarray"] = {}
            field_lens: Dict[bytes, "np.ndarray"] = {}
            ok = np.zeros(n, dtype=bool)
            for i in range(n):
                if not src.present[i]:
                    continue
                o, ln = int(src.offsets[i]), int(src.lengths[i])
                parsed = self._parse_line(raw[o : o + ln].tobytes())
                if parsed is None:
                    continue
                ok[i] = True
                ts, fields = parsed
                cols.timestamps[i] = ts
                for k, v in fields:
                    if k not in field_offs:
                        field_offs[k] = np.zeros(n, dtype=np.int32)
                        field_lens[k] = np.full(n, -1, dtype=np.int32)
                    view = sb.copy_string(v)
                    field_offs[k][i] = view.offset
                    field_lens[k][i] = view.length
            for k in field_offs:
                cols.set_field(k.decode("utf-8", "replace"),
                               field_offs[k], field_lens[k])
            if self.keep_source_on_fail and (~ok & src.present).any():
                cols.set_field(self.renamed_source_key,
                               src.offsets.astype("int32"),
                               np.where(~ok & src.present, src.lengths,
                                        -1).astype("int32"))
            cols.parse_ok = ok
            if src.from_content:
                cols.content_consumed = True
            return
        from .common import finish_row_keep
        renamed = self.renamed_source_key.encode()
        for ev in group.events:
            if not hasattr(ev, "get_content"):
                continue
            raw = ev.get_content(self.source_key)
            if raw is None:
                continue
            parsed = self._parse_line(raw.to_bytes())
            if parsed is None:
                # shared reference ordering: the source is consumed either
                # way; keep_fail re-adds it under the renamed key
                finish_row_keep(ev, raw, False, self.source_key, False,
                                self.keep_source_on_fail, False, renamed)
                continue
            ts, fields = parsed
            ev.timestamp = ts
            overwritten = False
            for k, val in fields:
                kb = k if isinstance(k, bytes) else k.encode()
                ev.set_content(sb.copy_string(kb), sb.copy_string(val))
                if kb == self.source_key:
                    overwritten = True
            finish_row_keep(ev, raw, True, self.source_key, overwritten,
                            self.keep_source_on_fail, False, renamed)
