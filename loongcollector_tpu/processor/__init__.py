"""Processor plugins.

Reference inventory: core/plugin/processor/ (SURVEY.md §2.3).  Names keep the
reference's `_native` suffix for drop-in config compatibility; the regex /
json / delimiter parsers execute on TPU via ops/ kernels with transparent
CPU fallback (the `_tpu` aliases are also registered).
"""


def register_all(registry) -> None:
    from .field_ops import (ProcessorAddFields, ProcessorDrop,
                            ProcessorRenameFields, ProcessorStrReplace)
    from .split_log_string import ProcessorSplitLogString
    from .parse_regex import ProcessorParseRegex
    from .parse_json import ProcessorParseJson
    from .parse_delimiter import ProcessorParseDelimiter
    from .parse_timestamp import ProcessorParseTimestamp
    from .filter import ProcessorFilter
    from .desensitize import ProcessorDesensitize
    from .tag import ProcessorTag
    from .merge_multiline import ProcessorMergeMultilineLog
    from .split_multiline import ProcessorSplitMultilineLogString
    from .grok import ProcessorGrok
    from .parse_apsara import ProcessorParseApsara
    from .parse_container_log import ProcessorParseContainerLog
    from .timestamp_filter import ProcessorTimestampFilter
    from .classify_url import ProcessorClassifyUrl
    from ..pipeline.plugin.dynamic import (DynamicCProcessor,
                                           DynamicPythonProcessor)
    from .spl import ProcessorSPL
    from .longtail import (ProcessorBase64Decoding, ProcessorBase64Encoding,
                           ProcessorDictMap, ProcessorEncrypt,
                           ProcessorFieldsWithCondition, ProcessorGeoIP,
                           ProcessorPackJson, ProcessorPickKey,
                           ProcessorRateLimit)

    registry.register_processor("processor_split_log_string_native",
                                ProcessorSplitLogString)
    registry.register_processor("processor_split_multiline_log_string_native",
                                ProcessorSplitMultilineLogString)
    registry.register_processor("processor_merge_multiline_log_native",
                                ProcessorMergeMultilineLog)
    registry.register_processor("processor_parse_regex_native", ProcessorParseRegex)
    registry.register_processor("processor_parse_regex_tpu", ProcessorParseRegex)
    registry.register_processor("processor_parse_json_native", ProcessorParseJson)
    registry.register_processor("processor_parse_json_tpu", ProcessorParseJson)
    registry.register_processor("processor_parse_delimiter_native",
                                ProcessorParseDelimiter)
    registry.register_processor("processor_parse_delimiter_tpu",
                                ProcessorParseDelimiter)
    registry.register_processor("processor_parse_timestamp_native",
                                ProcessorParseTimestamp)
    registry.register_processor("processor_filter_native", ProcessorFilter)
    registry.register_processor("processor_desensitize_native", ProcessorDesensitize)
    registry.register_processor("processor_tag_native", ProcessorTag)
    registry.register_processor("processor_grok", ProcessorGrok)
    registry.register_processor("processor_parse_apsara_native",
                                ProcessorParseApsara)
    registry.register_processor("processor_parse_container_log_native",
                                ProcessorParseContainerLog)
    registry.register_processor("processor_timestamp_filter_native",
                                ProcessorTimestampFilter)
    registry.register_processor("processor_classify_url_tpu",
                                ProcessorClassifyUrl)
    registry.register_processor("processor_classify_url_native",
                                ProcessorClassifyUrl)
    registry.register_processor("processor_dynamic", DynamicPythonProcessor)
    registry.register_processor("processor_dynamic_c", DynamicCProcessor)
    registry.register_processor("processor_spl", ProcessorSPL)
    registry.register_processor("processor_add_fields", ProcessorAddFields)
    registry.register_processor("processor_rename", ProcessorRenameFields)
    registry.register_processor("processor_drop", ProcessorDrop)
    registry.register_processor("processor_strreplace", ProcessorStrReplace)
    registry.register_processor("processor_dict_map", ProcessorDictMap)
    registry.register_processor("processor_pick_key", ProcessorPickKey)
    registry.register_processor("processor_packjson", ProcessorPackJson)
    registry.register_processor("processor_base64_encoding",
                                ProcessorBase64Encoding)
    registry.register_processor("processor_base64_decoding",
                                ProcessorBase64Decoding)
    registry.register_processor("processor_encrypt", ProcessorEncrypt)
    registry.register_processor("processor_rate_limit", ProcessorRateLimit)
    registry.register_processor("processor_fields_with_condition",
                                ProcessorFieldsWithCondition)
    registry.register_processor("processor_geoip", ProcessorGeoIP)
    from .prom_inner import (ProcessorPromParseMetric,
                             ProcessorPromRelabelMetric)
    registry.register_processor("processor_prom_parse_metric_native",
                                ProcessorPromParseMetric)
    registry.register_processor("processor_prom_relabel_metric_native",
                                ProcessorPromRelabelMetric)
    from .parse_from_pb import ProcessorParseFromPB
    registry.register_processor("processor_parse_from_pb_native",
                                ProcessorParseFromPB)
    from .longtail2 import ALL as _LONGTAIL2
    for _cls in _LONGTAIL2:
        registry.register_processor(_cls.name, _cls)
