"""processor_parse_delimiter — delimited fields via the TPU segment kernel.

Reference: core/plugin/processor/ProcessorParseDelimiterNative.cpp (single /
multi-char separators; quote mode via the CSV FSM in
core/parser/DelimiterModeFsmParser.h:27-56).

TPU redesign: a non-quoted delimiter split IS a Tier-1 segment program —
`([^d]*)d([^d]*)d...(.*)` — so it runs on the same gather-free extraction
kernel as regex parse.  Quote mode (loongstruct) runs on the
structural-index plane: `lct_delim_struct_parse` derives field spans from
quote/separator bitmaps with the doubled-quote rule resolved in the same
carry pass, retiring the per-row Python FSM for columnar groups — fields
needing byte rewrites (doubled quotes, quoted-head + tail) decode once
into a per-group side arena.  Without the native library, the numpy twin
(ops/kernels/struct_index.py) indexes the batch and a vectorised emitter
covers the RFC4180-clean subset; only index-deviant rows walk the
reference FSM per row (counted in `parse_fallback_rows_total`).
`_csv_fsm_split` remains the per-row semantic reference and the row-group
/ deviant-row tier.
"""

from __future__ import annotations

import os
import re as _re
from typing import Any, Dict, List

import numpy as np

from ..models import PipelineEventGroup
from ..ops.regex.engine import RegexEngine, get_engine
from ..pipeline.plugin.interface import PluginContext, Processor
from .common import (RAW_LOG_KEY, apply_parse_spans,
                     extract_source, finish_row_keep)


class _SpanResult:
    """BatchParseResult-shaped container for apply_parse_spans."""

    __slots__ = ("ok", "cap_off", "cap_len")

    def __init__(self, ok, cap_off, cap_len):
        self.ok = ok
        self.cap_off = cap_off
        self.cap_len = cap_len


def _csv_fsm_split(data: bytes, sep: bytes, quote: int = 0x22) -> List[bytes]:
    """Quote-mode split (reference DelimiterModeFsmParser state table):
    fields may be quoted; doubled quotes inside quoted fields escape."""
    fields: List[bytes] = []
    cur = bytearray()
    in_quote = False
    i, n = 0, len(data)
    s = sep[0]
    while i < n:
        b = data[i]
        if in_quote:
            if b == quote:
                if i + 1 < n and data[i + 1] == quote:
                    cur.append(quote)
                    i += 1
                else:
                    in_quote = False
            else:
                cur.append(b)
        elif b == quote and not cur:
            in_quote = True
        elif b == s and data[i : i + len(sep)] == sep:
            fields.append(bytes(cur))
            cur = bytearray()
            i += len(sep) - 1
        else:
            cur.append(b)
        i += 1
    fields.append(bytes(cur))
    return fields


class ProcessorParseDelimiter(Processor):
    name = "processor_parse_delimiter_tpu"
    supports_columnar = True

    def __init__(self) -> None:
        super().__init__()
        self.source_key = b"content"
        self.separator = b","
        self.quote_mode = False
        self.keys: List[str] = []
        self.keep_source_on_fail = True
        self.keep_source_on_success = False
        self.renamed_source_key = RAW_LOG_KEY
        self.engine: RegexEngine = None  # type: ignore
        self.allow_not_enough = False
        self._pipeline = ""

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.source_key = config.get("SourceKey", "content").encode()
        sep = config.get("Separator", ",")
        self.separator = sep.encode() if isinstance(sep, str) else bytes(sep)
        self.quote_mode = bool(config.get("Quote", "")) or \
            config.get("Mode", "") == "quote"
        self.keys = list(config.get("Keys", []))
        self.keep_source_on_fail = bool(config.get("KeepingSourceWhenParseFail", True))
        self.keep_source_on_success = bool(config.get("KeepingSourceWhenParseSucceed", False))
        self.renamed_source_key = config.get("RenamedSourceKey", RAW_LOG_KEY)
        self.allow_not_enough = bool(config.get("AcceptNoEnoughKeys", False))
        self._pipeline = getattr(context, "pipeline_name", "") or ""
        if not self.keys:
            return False
        if not self.quote_mode:
            # ([^s]*)s([^s]*)s...s(.*)  — Tier-1; last field takes the rest
            esc = _re.escape(self.separator.decode("latin-1"))
            neg = f"[^{esc}]" if len(self.separator) == 1 else None
            if neg is not None:
                parts = [f"({neg}*)"] * (len(self.keys) - 1) + ["(.*)"] \
                    if len(self.keys) > 1 else ["(.*)"]
                pattern = esc.join(parts)
                self.engine = get_engine(pattern)
        return True

    supports_async_dispatch = True

    def fused_stage_spec(self, ctx):
        """loongresident: the non-quote delimiter split IS a Tier-1
        segment program, so it joins a fused pipeline program exactly
        like regex extraction — same stage kind, same content identity
        (two plugins with the same derived pattern share one compiled
        program).  Quote mode keeps the structural-index plane."""
        from ..ops.regex.program import PatternTier
        eng = self.engine
        if self.quote_mode or self.allow_not_enough or eng is None \
                or eng.tier is not PatternTier.SEGMENT \
                or eng._segment_kernel is None:
            return None
        if not ctx.bind_source(self.source_key):
            return None
        from ..ops import fused_pipeline as fp
        from ..pipeline.fused_chain import FusedMemberStage
        spec = fp.StageSpec("extract", eng._segment_kernel.program,
                            ["extract", eng.pattern],
                            staged=eng._segment_kernel,
                            label=f"extract:{self.name}")
        ctx.note_fields(ctx.n_stages, self.keys[:eng.num_caps])
        ctx.note_consumed(self.source_key)
        return FusedMemberStage(spec, self._fused_apply)

    def _fused_apply(self, group, src, out, rowmap):
        from .common import subset_source
        ok, off, ln = out
        self._apply_device(group, subset_source(src, rowmap),
                           _SpanResult(ok[rowmap], off[rowmap], ln[rowmap]))
        return rowmap

    def process_dispatch(self, group: PipelineEventGroup):
        """Async device plane (same split as processor_parse_regex_tpu):
        the delimiter segment program dispatches now, the spans apply in
        process_complete while the device moves on to the next group.
        Quote-mode columnar groups take the synchronous structural-index
        plane instead (span derivation IS the whole computation there)."""
        if self.quote_mode and len(self.separator) == 1 and self.keys:
            # row groups skip the source pack entirely (extract_source
            # would copy every event's bytes just to be discarded) and go
            # straight to the per-event host tier
            if group.columns is None or group._events:
                self._process_host(group)
                return None
            src = extract_source(group, self.source_key)
            if src is None:
                return None
            if src.columnar and self._process_quote_struct(group, src):
                return None
            self._process_host(group)
            return None
        if self.engine is None or self.quote_mode or self.allow_not_enough:
            # configs that can never take the device path skip the source
            # row-pack entirely (extract_source copies every event's bytes
            # on row groups just to be discarded here otherwise)
            self._process_host(group)
            return None
        src = extract_source(group, self.source_key)
        if src is None:
            return None
        if not src.columnar:
            self._process_host(group)
            return None
        pending = self.engine.parse_batch_async(
            src.arena, src.offsets, src.lengths)
        if pending.done:
            self._apply_device(group, src, pending.result())
            return None
        return src, pending

    def process_complete(self, group: PipelineEventGroup, token) -> None:
        if token is None:
            return
        src, pending = token
        self._apply_device(group, src, pending.result())

    def process(self, group: PipelineEventGroup) -> None:
        self.process_complete(group, self.process_dispatch(group))

    def _apply_device(self, group: PipelineEventGroup, src, res) -> None:
        apply_parse_spans(group, src, res, self.keys,
                          self.keep_source_on_fail,
                          self.keep_source_on_success,
                          self.renamed_source_key,
                          source_key=self.source_key)

    # -- quote mode: structural-index plane ---------------------------------

    def _process_quote_struct(self, group: PipelineEventGroup, src) -> bool:
        """Quote-mode CSV from the structural index: native fused walk
        when the library is loaded, else numpy-twin masks + the vectorised
        clean-subset emitter with a counted per-row FSM tier for deviant
        rows.  Returns False only when no structural tier applies (caller
        falls back to the per-row host path wholesale)."""
        if os.environ.get("LOONG_STRUCT", "1") == "0":
            return False
        from .. import native as _native
        F = len(self.keys)
        n = len(src.offsets)
        sep = self.separator[0]
        sb = group.source_buffer
        arena_len = len(src.arena)
        n_fallback = 0

        res = _native.delim_struct_parse(src.arena, src.offsets,
                                         src.lengths, sep, 0x22, F)
        if res is not None:
            from .common import append_side_arena, rebase_side_spans
            cap_off, cap_len, nfields, side = res
            rebase = append_side_arena(sb, side, arena_len)
            cap_off = rebase_side_spans(cap_off, cap_len, arena_len,
                                        rebase)
        else:
            emitted = self._quote_struct_numpy(group, src, F, sep)
            if emitted is None:
                return False
            cap_off, cap_len, nfields, n_fallback = emitted
        ok = nfields >= F
        if self.allow_not_enough:
            ok = nfields >= 1
        self._apply_device(group, src,
                           _SpanResult(ok & src.present, cap_off, cap_len))
        from . import parse_telemetry
        parse_telemetry.note_rows(self.name, self._pipeline,
                                  int(src.present.sum()), n_fallback)
        return True

    def _quote_struct_numpy(self, group, src, F: int, sep: int):
        """No-native tier: numpy-twin index + vectorised emission; rows
        the clean-subset emitter cannot express (doubled quotes, literal
        mid-field quotes, joins) run the reference FSM per row — counted.
        Returns (cap_off, cap_len, nfields, n_fallback) or None."""
        from ..ops.kernels import struct_index as _si
        n = len(src.offsets)
        lengths = np.asarray(src.lengths, dtype=np.int32)
        L = max(1, int(lengths.max()) if n else 1)
        rows = np.zeros((n, L), dtype=np.uint8)
        arena = src.arena
        for i in range(n):
            o, ln = int(src.offsets[i]), int(lengths[i])
            if ln > 0:
                rows[i, :ln] = arena[o : o + ln]
        masks = _si.struct_index_numpy(rows, lengths, mode=_si.MODE_DELIM,
                                       sep=int(sep))
        quote_bits = _si.unpack16(masks[3], L)
        sep_bits = _si.unpack16(masks[1], L)
        cap_off, cap_len, nfields, deviant = _si.emit_delim_spans(
            arena, src.offsets, lengths, quote_bits, sep_bits, F)
        sb = group.source_buffer
        n_fallback = 0
        sep_b = bytes([sep])
        for i in np.nonzero(deviant & src.present)[0]:
            n_fallback += 1
            o, ln = int(src.offsets[i]), int(lengths[i])
            # the counted deviant-row tier under the numpy index (no
            # native library loaded) — parse_fallback_rows_total
            # loonglint: disable=per-row-parse
            fields = _csv_fsm_split(arena[o : o + ln].tobytes(), sep_b)
            nfields[i] = len(fields)
            if len(fields) > F:
                fields = fields[: F - 1] + [sep_b.join(fields[F - 1:])]
            for k in range(F):
                if k < len(fields):
                    view = sb.copy_string(fields[k])
                    cap_off[i, k] = view.offset
                    cap_len[i, k] = view.length
                else:
                    cap_len[i, k] = -1
        return cap_off, cap_len, nfields, n_fallback

    def _process_host(self, group: PipelineEventGroup) -> None:
        # host path: quote-mode FSM or row groups.  Keep/discard follows
        # the reference ordering shared with apply_parse_spans: capture the
        # raw source, delete it unless a key overwrote it, re-add under the
        # renamed key per the keep flags.
        sb = group.source_buffer
        key_bytes = [k.encode() for k in self.keys]
        renamed = self.renamed_source_key.encode()
        for ev in group.events:
            if not hasattr(ev, "get_content"):
                continue
            raw = ev.get_content(self.source_key)
            if raw is None:
                continue
            data = raw.to_bytes()
            # row-path groups (per-event plugins upstream) have no arena
            # to index; the FSM is the semantic reference tier
            # loonglint: disable=per-row-parse
            fields = (_csv_fsm_split(data, self.separator)
                      if self.quote_mode else data.split(self.separator))
            if len(fields) < len(self.keys) and not self.allow_not_enough:
                finish_row_keep(ev, raw, False, self.source_key, False,
                                self.keep_source_on_fail,
                                self.keep_source_on_success, renamed)
                continue
            if len(fields) > len(self.keys):
                head = fields[: len(self.keys) - 1]
                tail = self.separator.join(fields[len(self.keys) - 1:])
                fields = head + [tail]
            overwritten = False
            for key, val in zip(key_bytes, fields):
                ev.set_content(key, sb.copy_string(val))
                if key == self.source_key:
                    overwritten = True
            finish_row_keep(ev, raw, True, self.source_key, overwritten,
                            self.keep_source_on_fail,
                            self.keep_source_on_success, renamed)
