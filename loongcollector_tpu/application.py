"""Application: the agent process.

Reference: core/application/Application.cpp — Init (:96: identity, dirs,
app_info), Start (:222: monitors → config providers → runners sink-to-source
→ registry → 1 Hz supervision loop :313-398), Exit (:417: ordered stop with
a flush-out budget); core/logtail.cpp:154 (main: flags, signal handlers).

Run: python -m loongcollector_tpu --config <dir> [--once]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time

from .config.common_provider import CommonConfigProvider
from .config.onetime import OnetimeConfigInfoManager
from .config.watcher import PipelineConfigWatcher
from .input.file.file_server import FileServer
from .monitor.alarms import AlarmManager
from .monitor.metrics import WriteMetrics
from .monitor.watchdog import LoongCollectorMonitor
from .pipeline.batch.timeout_flush_manager import TimeoutFlushManager
from .pipeline.pipeline_manager import CollectionPipelineManager
from .pipeline.queue.process_queue_manager import ProcessQueueManager
from .pipeline.queue.sender_queue import SenderQueueManager
from .runner.disk_buffer import DiskBufferWriter
from .runner.flusher_runner import FlusherRunner
from .runner.http_sink import HttpSink
from .runner.processor_runner import ProcessorRunner
from .utils import flags
from .utils.crash_backtrace import (check_previous_crash,
                                    init_crash_backtrace, record_crash)
from .utils.logger import get_logger

log = get_logger("application")

# process_thread_count is defined by runner.processor_runner (loongshard
# default >1); app-config overrides still apply through the flag registry
flags.DEFINE_FLAG_INT32("config_scan_interval", "config rescan seconds", 10)
# checkpoint_dump_interval is defined by input.file.file_server (the dump
# cadence is the file server's knob); app-config overrides apply through
# the flag registry as usual
flags.DEFINE_FLAG_DOUBLE("exit_flush_timeout", "flush-out budget on exit (s)", 20.0)
flags.DEFINE_FLAG_STRING("config_server_address", "remote ConfigServer endpoint", "")
flags.DEFINE_FLAG_STRING("config_server_protocol",
                         "ConfigServer protocol: v2 (default) or v1", "v2")


class Application:
    def __init__(self, config_dir: str, data_dir: str = ""):
        self.config_dir = config_dir
        self.data_dir = data_dir or os.path.join(
            os.path.expanduser("~"), ".loongcollector_tpu")
        # app-level config overrides flags and must load BEFORE any
        # component reads them (thread counts, config server address...)
        self._load_app_config()
        self.process_queue_manager = ProcessQueueManager()
        self.sender_queue_manager = SenderQueueManager()
        self.pipeline_manager = CollectionPipelineManager(
            self.process_queue_manager, self.sender_queue_manager)
        self.http_sink = HttpSink()
        from .utils.payload_crypto import PayloadCipher
        try:
            spill_cipher = PayloadCipher(
                os.path.join(self.data_dir, "spill_key"))
        except (OSError, ValueError) as e:
            # a broken key file must not take the agent down — run with
            # plaintext spill and alarm loudly (existing encrypted files
            # are kept untouched until the key is restored)
            log.error("spill cipher unavailable (%s); disk buffer will "
                      "write PLAINTEXT", e)
            spill_cipher = None
        self.disk_buffer = DiskBufferWriter(
            os.path.join(self.data_dir, "buffer"),
            cipher=spill_cipher)
        from .flusher.async_sink import set_default_disk_buffer
        set_default_disk_buffer(self.disk_buffer)
        self.flusher_runner = FlusherRunner(self.sender_queue_manager,
                                            self.http_sink,
                                            disk_buffer=self.disk_buffer)
        # loongchaos: LOONG_CHAOS_SEED activates the deterministic fault
        # plane for this process (docs/robustness.md); no-op otherwise
        from . import chaos
        if chaos.install_from_env():
            log.warning("chaos plane ACTIVE (seed from %s)", chaos.ENV_SEED)
        # loongtrace: LOONG_TRACE=1 activates the span layer (sampling via
        # LOONG_TRACE_SAMPLE/LOONG_TRACE_SEED); LOONG_EXPO_PORT serves the
        # Prometheus-text endpoint (docs/observability.md)
        from . import trace
        if trace.install_from_env():
            log.info("loongtrace ACTIVE (sample=%s)",
                     trace.active_tracer().config.sample_rate)
        # loongprof: LOONG_PROF=1 starts the sampling profiler
        # (LOONG_PROF_HZ shapes the rate); the flight recorder is always
        # on and dumps on SIGTERM / watchdog breach / crash
        from . import prof
        if prof.install_from_env():
            log.info("loongprof ACTIVE (%.0f Hz)",
                     prof.active_profiler().hz)
        # loongledger: LOONG_LEDGER=1 turns on event-conservation
        # accounting; LOONG_LEDGER_AUDIT=1 additionally runs the
        # continuous zero-loss auditor (docs/observability.md)
        from .monitor import ledger
        if ledger.install_from_env():
            log.info("loongledger ACTIVE (audit=%s)",
                     ledger.auditor() is not None)
        # loongslo: LOONG_SLO=1 turns on the end-to-end freshness SLO
        # plane — ingest-stamped sojourn, burn-rate alerts, /debug/slo
        # (docs/observability.md)
        from .monitor import slo
        if slo.install_from_env():
            log.info("loongslo ACTIVE (evaluator=%s)",
                     slo.evaluator() is not None)
        # loongxprof: LOONG_XPROF=1 records the per-dispatch device
        # timeline (h2d/submit/exec/d2h legs, /debug/timeline); compile
        # and device-memory accounting are always on (docs/observability.md)
        from .ops import xprof
        if xprof.install_from_env():
            log.info("loongxprof ACTIVE")
        from .monitor.exposition import start_from_env as _expo_from_env
        self.exposition = _expo_from_env()
        from .runner.processor_runner import resolve_thread_count
        self.processor_runner = ProcessorRunner(
            self.process_queue_manager, self.pipeline_manager,
            thread_count=resolve_thread_count())
        self.config_watcher = PipelineConfigWatcher()
        from .config.instance_config import (InstanceConfigManager,
                                             InstanceConfigWatcher)
        self.instance_watcher = InstanceConfigWatcher()
        self.instance_manager = InstanceConfigManager.instance()
        self.remote_provider = None
        endpoint = flags.get_flag("config_server_address")
        if endpoint:
            proto = flags.get_flag("config_server_protocol").strip().lower()
            if proto == "v1":
                from .config.legacy_provider import LegacyConfigProvider
                provider_cls = LegacyConfigProvider
            else:
                if proto not in ("", "v2"):
                    log.error("unknown config_server_protocol %r; "
                              "falling back to v2", proto)
                provider_cls = CommonConfigProvider
            self.remote_provider = provider_cls(
                endpoint, os.path.join(self.data_dir, "remote_config"))
        self.watchdog = LoongCollectorMonitor(
            on_limit_breach=self._on_limit_breach)
        self._sig_stop = threading.Event()
        self._sig_received = None   # signum, set async-safely by the handler

    def _load_app_config(self) -> None:
        """Agent-level config file (reference loongcollector_config.json +
        AppConfig): a flat dict of flag overrides in the data or config
        dir."""
        for d in (self.data_dir, self.config_dir):
            path = os.path.join(d, "loongcollector_config.json")
            if not os.path.exists(path):
                continue
            try:
                with open(path) as f:
                    overrides = json.load(f)
            except (OSError, ValueError) as e:
                log.error("bad app config %s: %s", path, e)
                continue
            for k, v in overrides.items():
                if flags.has_flag(k):
                    flags.set_flag(k, v)
                    log.info("app config: %s = %r", k, v)
            return

    def init(self) -> None:
        os.makedirs(self.data_dir, exist_ok=True)
        check_previous_crash(self.data_dir)
        init_crash_backtrace(self.data_dir)
        # unsolicited flight dumps (signals, watchdog, crash) land next to
        # the crash backtrace so one directory holds the whole post-mortem
        from .prof import flight
        flight.set_dump_dir(self.data_dir)
        # loongcrash: detect unclean shutdown, load the acked-span journal
        # into the replay-duplicate window, sweep torn spill temps, and
        # start journaling this run's acks — BEFORE any reader opens (the
        # suppression window must be live when the first re-read arrives)
        from . import recovery
        recovery.begin(self.data_dir,
                       os.path.join(self.data_dir, "buffer"))
        # loongfuse: fused multi-pattern automata persist under
        # <data_dir>/dfa_cache/ — restarts and pipeline hot-reloads load
        # the compiled DFA by pattern-set content hash instead of paying
        # determinize+minimize again
        from .ops.regex import fuse
        fuse.set_cache_dir(self.data_dir)
        # loongresident: fused pipeline-program plan records persist under
        # <data_dir>/fused_cache/ — restarts skip plan construction and
        # recover the observed jit geometries for AOT warm
        from .ops import fused_pipeline
        fused_pipeline.set_cache_dir(self.data_dir)
        from .pipeline.plugin.checkpoint import (PluginCheckpointStore,
                                                 set_default_store)
        set_default_store(PluginCheckpointStore(
            os.path.join(self.data_dir, "plugin_checkpoints.json")))
        self.onetime_manager = OnetimeConfigInfoManager(
            os.path.join(self.data_dir, "onetime_state.json"))
        self.onetime_manager.load()
        self.pipeline_manager.onetime_manager = self.onetime_manager
        from .input.file.checkpoint_v2 import get_default_manager
        eo_mgr = get_default_manager(
            os.path.join(self.data_dir, "checkpoint_v2.db"))
        # snapshot uncommitted ranges NOW — before any pipeline starts and
        # new sends INSERT OR REPLACE over the same slot keys
        self._eo_pending = list(eo_mgr.uncommitted()) if eo_mgr else []
        # EO ranges subsume reader offsets: bump v1 checkpoints past every
        # uncommitted range BEFORE any reader opens, so the normal tail path
        # never re-reads bytes the EO replay will re-inject (that overlap
        # would double-deliver after a hard crash).
        if self._eo_pending:
            from .input.file.reader import ReaderCheckpoint, SIGNATURE_SIZE
            fs = FileServer.instance()
            fs.checkpoints.path = os.path.join(self.data_dir,
                                               "checkpoints.json")
            fs.checkpoints.load()
            bumped = False
            for cp in self._eo_pending:
                if not cp.file_path or cp.read_length <= 0:
                    continue
                end = cp.read_offset + cp.read_length
                # Find the live v1 entry. Legacy EO records carry dev=0 (the
                # reader only exported inode then), so (cp.dev, cp.inode) may
                # not be a real key — fall back to path lookup, and reject a
                # path hit whose inode disagrees (file was rotated since).
                v1 = None
                if cp.dev and cp.inode:
                    v1 = fs.checkpoints.get(cp.dev, cp.inode)
                if v1 is None:
                    v1 = fs.checkpoints.get_by_path(cp.file_path)
                    if v1 is not None and cp.inode and v1.inode != cp.inode:
                        v1 = None
                if v1 is None or v1.offset < end:
                    # bump IN PLACE: keep the found entry's real (dev, inode)
                    # key — keying by the EO record's possibly-zero dev would
                    # write a dead entry the reader never restores
                    dev, inode = ((v1.dev, v1.inode) if v1 is not None
                                  else (cp.dev, cp.inode))
                    try:
                        pst = os.stat(cp.file_path)
                    except OSError:
                        pst = None
                    if not inode or not dev:
                        if pst is None:
                            continue  # file gone: nothing to protect
                        dev, inode = pst.st_dev, pst.st_ino
                    sig = v1.signature if v1 is not None else ""
                    if not sig and pst is not None and \
                            (pst.st_dev, pst.st_ino) == (dev, inode):
                        # capture the head as the rotation signature — but
                        # only while the path still IS this (dev, inode);
                        # after rotation the path holds a different file
                        # whose head would poison the signature check
                        try:
                            with open(cp.file_path, "rb") as f:
                                sig = f.read(SIGNATURE_SIZE).hex()
                        except OSError:
                            sig = ""
                    fs.checkpoints.update(ReaderCheckpoint(
                        path=cp.file_path, offset=end,
                        dev=dev, inode=inode,
                        signature=sig, signature_size=len(sig) // 2))
                    bumped = True
            if bumped:
                fs.checkpoints.dump()
        # warm the native library (and its one-shot build) here so the first
        # data batch never stalls behind a compiler invocation
        from . import native as _native
        _native.get_lib()
        # declarative runner matrix (reference PluginRegistry.cpp:162-196):
        # every singleton input runner gets wired — and later stopped —
        # through the registry, so new runners need no Application edits
        from .runner.input_registry import (InputRunnerRegistry,
                                            register_builtin_runners)
        register_builtin_runners()
        InputRunnerRegistry.wire_all(self.process_queue_manager)
        fs = FileServer.instance()
        fs.checkpoints.path = os.path.join(self.data_dir, "checkpoints.json")
        fs.cpu_level_provider = lambda: self.watchdog.cpu_level
        self.config_watcher.add_source(self.config_dir)
        # instance configs: agent-level flag overrides applied live,
        # without pipeline restarts (instance_config/ beside the pipeline
        # dir; reference InstanceConfigWatcher.cpp)
        cfg_abs = os.path.abspath(self.config_dir)
        self.instance_watcher.add_source(
            os.path.join(os.path.dirname(cfg_abs), "instance_config"))
        self.instance_watcher.add_source(
            os.path.join(cfg_abs, "instance_config"))
        if self.remote_provider is not None:
            self.config_watcher.add_source(self.remote_provider.config_dir)
            self.remote_provider.start()

    def start(self, once: bool = False) -> None:
        # sink-to-source: network sink → flusher runner → processor runner →
        # config/pipelines (which start inputs)
        self.http_sink.init()
        self.flusher_runner.init()
        self.processor_runner.init()
        self.watchdog.start()
        log.info("runners started; watching %s", self.config_dir)
        scan_interval = flags.get_flag("config_scan_interval")
        last_scan = 0.0
        while not self._sig_stop.is_set():
            now = time.monotonic()
            if now - last_scan >= (0 if last_scan == 0 else scan_interval):
                last_scan = now
                diff = self.config_watcher.check_config_diff()
                if not diff.empty():
                    self.pipeline_manager.update_pipelines(diff)
                # a control-plane-faulted removal must complete even if
                # the config dir never changes again (loongtenant)
                self.pipeline_manager.retry_pending_removals()
                idiff = self.instance_watcher.check_config_diff()
                if not idiff.empty():
                    self.instance_manager.update(idiff)
                self.sender_queue_manager.gc_marked()
                WriteMetrics.instance().gc_deleted()
                self.disk_buffer.replay(self._resolve_buffered_flusher)
                from .pipeline.plugin.checkpoint import get_default_store
                get_default_store().flush()
                self.pipeline_manager.check_onetime_completion(
                    self.process_queue_manager, self.sender_queue_manager)
                if self._eo_pending:
                    self._replay_exactly_once()
            if once:
                # drain mode for one-shot runs: wait until queues idle
                time.sleep(1.0)
                if (self.process_queue_manager.all_empty()
                        and self.sender_queue_manager.all_empty()):
                    break
            else:
                self._sig_stop.wait(1.0)
        if self._sig_received is not None:
            # a signalled agent leaves its last seconds on disk: the
            # flight ring (alarms, injections, breaker flips, stalls) +
            # final stacks.  Runs HERE, on the main loop after the wait
            # returned — never inside the signal handler, where the ring
            # or logging lock may already be held by the interrupted frame
            signum = self._sig_received
            log.info("signal %d received", signum)
            from .prof import flight
            flight.record("signal", signum=signum)
            flight.dump(reason=f"signal_{signum}")
        self.exit()

    def exit(self) -> None:
        """Ordered source-to-sink shutdown (reference Application::Exit +
        CollectionPipeline::Stop :491-532): inputs stop first, the processor
        runner drains the process queues THROUGH the pipelines, and only then
        are batchers final-flushed and the send path drained."""
        log.info("exiting: stopping inputs and draining")
        if self.remote_provider is not None:
            self.remote_provider.stop()
        self.watchdog.stop()
        from .runner.input_registry import InputRunnerRegistry
        InputRunnerRegistry.stop_all()
        self.processor_runner.stop()          # drains process queues
        self.pipeline_manager.stop_all()      # flush batchers, stop flushers
        TimeoutFlushManager.instance().flush_timeout_batches()
        self.flusher_runner.stop(
            drain=True, timeout=flags.get_flag("exit_flush_timeout"))
        self.http_sink.stop()
        if getattr(self, "exposition", None) is not None:
            self.exposition.stop()
        from . import prof
        prof.disable()                        # stop sampler, retire records
        from .monitor import slo
        slo.stop_evaluator()                  # SLO burn-rate thread, if any
        from .pipeline.plugin.checkpoint import get_default_store
        get_default_store().flush()
        # final checkpoint dump AFTER the flusher drain: FileServer.stop
        # dumped before the send path quiesced, so the watermark on disk is
        # stale by every ack the drain just completed — without this dump a
        # clean restart would re-read (and have to dedup) the whole window
        fs = FileServer.instance()
        if fs.checkpoints.path:
            try:
                fs.checkpoints.dump()
            except OSError:
                log.exception("final checkpoint dump failed")
        # everything drained and dumped: compact the ack journal and drop
        # the crash marker — the next start is a clean start
        from . import recovery
        recovery.mark_clean_exit()
        log.info("exit complete")

    def _replay_exactly_once(self) -> None:
        """Re-read and re-inject file ranges whose send never committed
        (crash between serialize and ack), from the snapshot taken at init.
        Entries wait until their pipeline loads (remote configs arrive
        asynchronously) and survive full queues; deletes are sequence-
        conditioned so a fresh in-flight range reusing the key is never
        clobbered.  Groups are marked IS_REPLAY so downstream may dedupe."""
        from .input.file.checkpoint_v2 import get_default_manager
        from .models import EventGroupMetaKey, PipelineEventGroup, SourceBuffer
        mgr = get_default_manager()
        if mgr is None:
            self._eo_pending = []
            return
        for cp in list(self._eo_pending):
            if not cp.file_path or cp.read_length <= 0:
                mgr.delete_if_sequence(cp.key, cp.sequence_id)
                self._eo_pending.remove(cp)
                continue
            pipeline_name = cp.key.split(":", 1)[0]
            p = self.pipeline_manager.find_pipeline(pipeline_name)
            if p is None:
                continue  # pipeline may still be loading (remote config)
            try:
                fd = os.open(cp.file_path, os.O_RDONLY)
                st = os.fstat(fd)
                if cp.inode and st.st_ino != cp.inode:
                    os.close(fd)
                    mgr.delete_if_sequence(cp.key, cp.sequence_id)
                    self._eo_pending.remove(cp)  # rotated: unrecoverable
                    continue
                data = os.pread(fd, cp.read_length, cp.read_offset)
                os.close(fd)
            except OSError:
                mgr.delete_if_sequence(cp.key, cp.sequence_id)
                self._eo_pending.remove(cp)
                continue
            # the normal read path transcodes GBK→UTF-8; the replayed raw
            # range must match or exactly the replayed events ship mojibake
            for icfg in (getattr(p, "config", None) or {}).get("inputs", []):
                if icfg.get("Type") == "input_file" and \
                        str(icfg.get("FileEncoding", "utf8")).lower() == "gbk":
                    from .input.file.reader import LogFileReader
                    data, _ = LogFileReader._transcode_gbk(
                        data, force_flush=True)
                    break
            sb = SourceBuffer(len(data) + 256)
            view = sb.copy_string(data)
            group = PipelineEventGroup(sb)
            ev = group.add_raw_event(int(time.time()))
            ev.set_content(view)
            group.set_metadata(EventGroupMetaKey.LOG_FILE_PATH, cp.file_path)
            group.set_metadata(EventGroupMetaKey.LOG_FILE_INODE,
                               str(cp.inode))
            group.set_metadata(EventGroupMetaKey.LOG_FILE_OFFSET,
                               str(cp.read_offset))
            group.set_metadata(EventGroupMetaKey.LOG_FILE_LENGTH,
                               str(cp.read_length))
            group.set_metadata(EventGroupMetaKey.IS_REPLAY, "true")
            if not self.process_queue_manager.push_queue(
                    p.process_queue_key, group):
                continue  # queue full: retry next supervision round
            mgr.delete_if_sequence(cp.key, cp.sequence_id)
            self._eo_pending.remove(cp)
            log.info("exactly-once replay: %s [%d,+%d)", cp.file_path,
                     cp.read_offset, cp.read_length)

    def _resolve_buffered_flusher(self, identity: dict):
        """Find the live flusher matching a spilled payload's identity
        (plugin_id disambiguates same-type flushers in one pipeline)."""
        p = self.pipeline_manager.find_pipeline(identity.get("pipeline", ""))
        if p is None:
            return None
        want_id = identity.get("plugin_id", "")
        for f in p.flushers:
            if want_id and f.plugin_id == want_id:
                return f.plugin
        if not want_id:  # legacy buffers without plugin_id
            for f in p.flushers:
                if f.plugin.name == identity.get("flusher_type"):
                    return f.plugin
        return None

    def _on_limit_breach(self, reason: str) -> None:
        """Sustained resource breach: log critically and exit for the
        supervisor to restart (reference watchdog suicide-and-restart)."""
        log.critical("resource limit breached: %s — exiting for restart", reason)
        self._sig_stop.set()

    def handle_signal(self, signum, frame) -> None:  # noqa: ARG002
        # Python signal handlers run on the main thread between bytecodes:
        # taking ANY non-reentrant lock here (the flight ring's, logging's)
        # can deadlock against the interrupted frame.  Only async-safe
        # work happens here — the flight dump runs from the main loop
        # right after the wait returns (see start()).
        self._sig_received = signum
        self._sig_stop.set()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="loongcollector_tpu")
    parser.add_argument("--config", required=True,
                        help="pipeline config directory")
    parser.add_argument("--data-dir", default="",
                        help="checkpoint/state directory")
    parser.add_argument("--once", action="store_true",
                        help="process available data then exit")
    parser.add_argument("--cpu", action="store_true",
                        help="force the CPU backend (skip the device probe)")
    args = parser.parse_args(argv)

    # A wedged TPU tunnel hangs the first jax op; degrade to CPU rather than
    # wedging the whole agent (SURVEY.md §5.3: backend outage must cost
    # throughput, never liveness). The probe overlaps with init() — nothing
    # before start() touches jax — so a healthy agent doesn't pay for it.
    probe = None
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    else:
        from .utils.backend import ensure_live_backend
        probe = threading.Thread(target=ensure_live_backend, daemon=True)
        probe.start()

    app = Application(args.config, args.data_dir)
    signal.signal(signal.SIGTERM, app.handle_signal)
    signal.signal(signal.SIGINT, app.handle_signal)
    app.init()
    if probe is not None:
        probe.join()  # backend decision must land before the first jax op
    try:
        app.start(once=args.once)
    except Exception:  # noqa: BLE001 - persist the trace for restart report
        import traceback
        trace = traceback.format_exc()
        log.critical("unhandled exception in main loop:\n%s", trace)
        record_crash(app.data_dir, trace)
        from .prof import flight
        flight.record("crash", error=trace.strip().rsplit("\n", 1)[-1][:200])
        flight.dump(reason="crash")
        try:
            # the orderly drain is still possible — flush what we can before
            # the supervisor restarts us
            app.exit()
        except Exception:  # noqa: BLE001
            log.exception("drain after crash failed")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
