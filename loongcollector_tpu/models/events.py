"""Pipeline event types: LOG / METRIC / SPAN / RAW.

Reference: core/models/PipelineEvent.h (4 event kinds), LogEvent
(core/models/LogEvent.h:64 — content order preserved, :120-122),
MetricEvent + MetricValue (untyped double / typed multi-value), SpanEvent,
RawEvent.  Events hold StringViews into the owning group's SourceBuffer.
"""

from __future__ import annotations

import enum
from typing import Dict, Iterable, List, Optional, Tuple

from ..utils.stringview import AnyStr, StringView, as_bytes


class EventType(enum.IntEnum):
    NONE = 0
    LOG = 1
    METRIC = 2
    SPAN = 3
    RAW = 4


class PipelineEvent:
    """Base event. `Is<T>/Cast<T>` of the reference's tagged PipelineEventPtr
    become isinstance checks; `GetType()` is the `type` attribute."""

    __slots__ = ("timestamp", "timestamp_ns")
    type: EventType = EventType.NONE

    def __init__(self, timestamp: int = 0, timestamp_ns: Optional[int] = None):
        self.timestamp = timestamp
        self.timestamp_ns = timestamp_ns

    def set_timestamp(self, ts: int, ns: Optional[int] = None) -> None:
        self.timestamp = ts
        self.timestamp_ns = ns


class LogEvent(PipelineEvent):
    """Ordered key→value contents (order preserved, LogEvent.h:120-122).

    Contents are stored as a list of (key, value) StringView pairs plus a
    dict index for O(1) lookup; both stay in sync.
    """

    __slots__ = ("_contents", "_index", "level", "file_offset")
    type = EventType.LOG

    def __init__(self, timestamp: int = 0, timestamp_ns: Optional[int] = None):
        super().__init__(timestamp, timestamp_ns)
        self._contents: List[Tuple[StringView, StringView]] = []
        self._index: Dict[bytes, int] = {}
        self.level: Optional[StringView] = None
        self.file_offset: int = 0

    def set_content(self, key: AnyStr, value: AnyStr) -> None:
        """Copy-free when key/value are already StringViews into the arena
        (the reference's SetContentNoCopy); str/bytes are wrapped as-is."""
        kv = key if isinstance(key, StringView) else StringView(as_bytes(key))
        vv = value if isinstance(value, StringView) else StringView(as_bytes(value))
        kb = kv.to_bytes()
        idx = self._index.get(kb)
        if idx is None:
            self._index[kb] = len(self._contents)
            self._contents.append((kv, vv))
        else:
            self._contents[idx] = (kv, vv)

    def get_content(self, key: AnyStr) -> Optional[StringView]:
        idx = self._index.get(as_bytes(key))
        return self._contents[idx][1] if idx is not None else None

    def has_content(self, key: AnyStr) -> bool:
        return as_bytes(key) in self._index

    def del_content(self, key: AnyStr) -> None:
        kb = as_bytes(key)
        idx = self._index.pop(kb, None)
        if idx is not None:
            del self._contents[idx]
            for k, i in self._index.items():
                if i > idx:
                    self._index[k] = i - 1

    def clear_contents(self) -> None:
        self._contents = []
        self._index = {}

    @property
    def contents(self) -> List[Tuple[StringView, StringView]]:
        return self._contents

    def __len__(self) -> int:
        return len(self._contents)

    def empty(self) -> bool:
        return not self._contents


class MetricValue:
    """Untyped single double or typed multi-value (reference MetricValue)."""

    __slots__ = ("value", "values")

    def __init__(self, value: Optional[float] = None,
                 values: Optional[Dict[bytes, float]] = None):
        self.value = value
        self.values = values

    def is_multi(self) -> bool:
        return self.values is not None


class MetricEvent(PipelineEvent):
    __slots__ = ("name", "value", "tags")
    type = EventType.METRIC

    def __init__(self, timestamp: int = 0, timestamp_ns: Optional[int] = None):
        super().__init__(timestamp, timestamp_ns)
        self.name: Optional[StringView] = None
        self.value: MetricValue = MetricValue(0.0)
        self.tags: Dict[bytes, StringView] = {}

    def set_name(self, name: AnyStr) -> None:
        self.name = name if isinstance(name, StringView) else StringView(as_bytes(name))

    def set_value(self, v: float) -> None:
        self.value = MetricValue(float(v))

    def set_multi_value(self, values: Dict[AnyStr, float]) -> None:
        self.value = MetricValue(values={as_bytes(k): float(v) for k, v in values.items()})

    def set_tag(self, key: AnyStr, value: AnyStr) -> None:
        vv = value if isinstance(value, StringView) else StringView(as_bytes(value))
        self.tags[as_bytes(key)] = vv

    def get_tag(self, key: AnyStr) -> Optional[StringView]:
        return self.tags.get(as_bytes(key))


class SpanEvent(PipelineEvent):
    """Trace span (reference core/models/SpanEvent.h)."""

    __slots__ = ("trace_id", "span_id", "parent_span_id", "name", "kind",
                 "start_time_ns", "end_time_ns", "status", "attributes",
                 "events", "links", "trace_state")
    type = EventType.SPAN

    class Kind(enum.IntEnum):
        UNSPECIFIED = 0
        INTERNAL = 1
        SERVER = 2
        CLIENT = 3
        PRODUCER = 4
        CONSUMER = 5

    class Status(enum.IntEnum):
        UNSET = 0
        OK = 1
        ERROR = 2

    def __init__(self, timestamp: int = 0, timestamp_ns: Optional[int] = None):
        super().__init__(timestamp, timestamp_ns)
        self.trace_id = b""
        self.span_id = b""
        self.parent_span_id = b""
        self.name = b""
        self.kind = SpanEvent.Kind.UNSPECIFIED
        self.start_time_ns = 0
        self.end_time_ns = 0
        self.status = SpanEvent.Status.UNSET
        self.attributes: Dict[bytes, StringView] = {}
        self.events: List[dict] = []
        self.links: List[dict] = []
        self.trace_state = b""

    def set_attribute(self, key: AnyStr, value: AnyStr) -> None:
        vv = value if isinstance(value, StringView) else StringView(as_bytes(value))
        self.attributes[as_bytes(key)] = vv


class RawEvent(PipelineEvent):
    """A raw byte chunk (reference core/models/RawEvent.h) — e.g. one whole
    file-read chunk before line splitting (LogFileReader::GenerateEventGroup
    wraps the chunk as ONE event, reader/LogFileReader.cpp:2726)."""

    __slots__ = ("content",)
    type = EventType.RAW

    def __init__(self, timestamp: int = 0, timestamp_ns: Optional[int] = None):
        super().__init__(timestamp, timestamp_ns)
        self.content: Optional[StringView] = None

    def set_content(self, content: AnyStr) -> None:
        self.content = (content if isinstance(content, StringView)
                        else StringView(as_bytes(content)))


def metric_name_str(name) -> str:
    """Metric names arrive as bytes from inputs; str(bytes) would leak the
    b'…' repr into wire output and JSON exports. Single normalization rule
    shared by every serializer."""
    if not name:
        return ""
    if isinstance(name, bytes):
        return name.decode("utf-8", "replace")
    return str(name)
