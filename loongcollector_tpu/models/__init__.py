from .event_group import ColumnarLogs, EventGroupMetaKey, PipelineEventGroup
from .event_pool import EventPool, g_thread_event_pool
from .events import (EventType, LogEvent, MetricEvent, MetricValue,
                     PipelineEvent, RawEvent, SpanEvent)
from .source_buffer import SourceBuffer
