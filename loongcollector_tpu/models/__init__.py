from .event_group import (ColumnarLogs, EventGroupMetaKey,
                          PipelineEventGroup, churn_stats, columnar_enabled,
                          reset_churn_stats, set_columnar_enabled)
from .event_pool import EventPool, g_thread_event_pool
from .events import (EventType, LogEvent, MetricEvent, MetricValue,
                     PipelineEvent, RawEvent, SpanEvent)
from .source_buffer import SourceBuffer
