"""Event object recycling.

Reference: core/models/EventPool.h:117 — same-thread lock-free pool +
double-buffered cross-thread pool, GC'd from processor threads
(runner/ProcessorRunner.cpp:188).  In Python the win is smaller, but the
pool still avoids re-allocating LogEvent shells on the materialise path and
keeps API parity for plugins written against the reference semantics.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

from .events import LogEvent

_POOL_GC_INTERVAL_S = 60.0


class EventPool:
    def __init__(self, enable_lock: bool = True):
        self._enable_lock = enable_lock
        self._pool: List[LogEvent] = []
        self._swap_pool: List[LogEvent] = []  # cross-thread returns land here
        self._lock = threading.Lock()
        self._last_gc = time.monotonic()
        self._min_unused = len(self._pool)

    def acquire_log_event(self, timestamp: int = 0) -> LogEvent:
        ev: Optional[LogEvent] = None
        if self._pool:
            ev = self._pool.pop()
        elif self._swap_pool:
            if self._enable_lock:
                with self._lock:
                    self._pool, self._swap_pool = self._swap_pool, self._pool
            else:
                self._pool, self._swap_pool = self._swap_pool, self._pool
            if self._pool:
                ev = self._pool.pop()
        self._min_unused = min(self._min_unused, len(self._pool))
        if ev is None:
            return LogEvent(timestamp)
        ev._contents.clear()
        ev._index.clear()
        ev.timestamp = timestamp
        ev.timestamp_ns = None
        ev.level = None
        ev.file_offset = 0
        return ev

    def release(self, ev: LogEvent) -> None:
        if self._enable_lock:
            with self._lock:
                self._swap_pool.append(ev)
        else:
            self._pool.append(ev)

    def check_gc(self) -> None:
        """Shrink to the high-water mark of unused objects (reference
        EventPool.cpp:257 CheckGC)."""
        now = time.monotonic()
        if now - self._last_gc < _POOL_GC_INTERVAL_S:
            return
        self._last_gc = now
        with self._lock:
            # fold cross-thread returns in, then shrink by the interval's
            # low-water mark of unused objects (reference EventPool CheckGC)
            self._pool.extend(self._swap_pool)
            self._swap_pool.clear()
            if self._min_unused > 0:
                keep = len(self._pool) - self._min_unused
                del self._pool[max(keep, 0):]
            self._min_unused = len(self._pool)

    def size(self) -> int:
        return len(self._pool) + len(self._swap_pool)


g_thread_event_pool = EventPool(enable_lock=True)
