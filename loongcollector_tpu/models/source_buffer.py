"""DMA-friendly memory arena backing all event data.

Reference: core/common/memory/SourceBuffer.h (BufferAllocator::Alloc :98-131,
CopyString :165) — a bump allocator whose chunks double 4 KB → 128 KB.

TPU-first redesign: instead of a chunk list, ONE contiguous growable buffer
(amortised doubling).  Rationale (SURVEY.md §7 step 1): the whole arena must
transfer to HBM as a single contiguous copy for the device parse kernels, and
device-returned (offset, length) spans must index the original arena so that
zero-copy StringViews stay valid downstream.  Views hold (arena, offset), not
raw pointers, so growth-induced reallocation is safe.
"""

from __future__ import annotations

import numpy as np

from ..utils.stringview import StringView

_INITIAL_CAPACITY = 4096


class SourceBuffer:
    __slots__ = ("_data", "_size")

    def __init__(self, capacity: int = _INITIAL_CAPACITY):
        self._data = bytearray(capacity)
        self._size = 0

    # -- allocation ---------------------------------------------------------

    def _reserve(self, n: int) -> None:
        need = self._size + n
        cap = len(self._data)
        if need > cap:
            while cap < need:
                cap *= 2
            # Reallocate into a NEW bytearray rather than extending in place:
            # live numpy exports (as_array views held by columnar processors)
            # keep the old buffer alive and valid, so arena growth can never
            # raise BufferError mid-batch.  StringViews resolve through
            # `self._data` and see the new buffer.
            new = bytearray(cap)
            new[: self._size] = self._data[: self._size]
            self._data = new

    def allocate(self, n: int) -> int:
        """Bump-allocate n bytes; returns the offset."""
        self._reserve(n)
        off = self._size
        self._size += n
        return off

    def copy_string(self, data) -> StringView:
        """Copy bytes/str into the arena; returns a zero-copy view."""
        if isinstance(data, str):
            data = data.encode("utf-8")
        elif isinstance(data, StringView):
            data = data.to_bytes()
        n = len(data)
        off = self.allocate(n)
        self._data[off : off + n] = data
        return StringView(self, off, n)

    def write_at(self, offset: int, data: bytes) -> None:
        self._data[offset : offset + len(data)] = data

    def view(self, offset: int, length: int) -> StringView:
        return StringView(self, offset, length)

    # -- access -------------------------------------------------------------

    @property
    def raw(self) -> bytearray:
        return self._data

    @property
    def size(self) -> int:
        return self._size

    def __len__(self) -> int:
        return self._size

    def as_array(self) -> np.ndarray:
        """Zero-copy uint8 view of the used portion, for device transfer.
        Valid until the next allocation (growth may reallocate)."""
        return np.frombuffer(self._data, dtype=np.uint8, count=self._size)
