"""PipelineEventGroup — the unit that flows through pipelines.

Reference: core/models/PipelineEventGroup.h:80-158 — metadata map + tags +
vector<PipelineEventPtr> + shared SourceBuffer; plus the test-only JSON
round-trip (PipelineEventGroup.h:140-146) which we keep as a first-class
fixture format (SURVEY.md §4).

TPU-first redesign: groups additionally carry a **columnar** representation
(`ColumnarLogs`): per-event (offset, length, timestamp) numpy arrays over the
shared arena, plus parsed field span columns.  The device data plane operates
exclusively on columns — per-event Python objects are materialised only on
demand (tests, per-event plugins, JSON serialization).  Columnar groups are
what gets packed into fixed-width device batches.
"""

from __future__ import annotations

import enum
import json
import os
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils.stringview import AnyStr, StringView, as_bytes
from .events import (EventType, LogEvent, MetricEvent, PipelineEvent,
                     RawEvent, SpanEvent, metric_name_str)
from .source_buffer import SourceBuffer


# -- columnar mode + materialization accounting (loongcolumn) ---------------
#
# The data plane keeps groups columnar end-to-end; per-event LogEvent
# objects exist ONLY where a plugin that needs dict access forces them
# (ProcessorInstance/FlusherInstance materialize at that boundary).  Every
# such expansion is counted here so the bench (extra.alloc) and the
# equivalence gate can assert the fast path really is zero-materialization.
# ``LOONG_COLUMNAR=0`` disables the columnar fast path wholesale — every
# stage boundary materializes — which is the "dict path" half of the
# side-by-side bench and of scripts/columnar_equivalence.py.

_churn_lock = threading.Lock()
_materialized_events = 0
_materialized_groups = 0
_materialized_at: Dict[str, int] = {}

_columnar_enabled = os.environ.get("LOONG_COLUMNAR", "1") != "0"


def columnar_enabled() -> bool:
    """False ⇒ dict mode: treat every plugin boundary as non-columnar."""
    return _columnar_enabled


def set_columnar_enabled(on: bool) -> bool:
    """Flip the columnar fast path (bench side-by-side / equivalence gate);
    returns the previous value."""
    global _columnar_enabled
    prev = _columnar_enabled
    _columnar_enabled = bool(on)
    return prev


def _note_materialized(n_events: int, where: str) -> None:
    global _materialized_events, _materialized_groups
    with _churn_lock:
        _materialized_events += n_events
        _materialized_groups += 1
        if where:
            _materialized_at[where] = _materialized_at.get(where, 0) + n_events


def churn_stats() -> Dict[str, object]:
    """Process-lifetime materialization counters: how many per-event
    Python objects the lazy boundary actually minted, and at which plugin
    boundaries.  The columnar fast path's regression signal — see
    bench.py extra.alloc and docs/performance.md."""
    with _churn_lock:
        return {"materialized_events": _materialized_events,
                "materialized_groups": _materialized_groups,
                "by_boundary": dict(_materialized_at)}


def reset_churn_stats() -> None:
    global _materialized_events, _materialized_groups
    with _churn_lock:
        _materialized_events = 0
        _materialized_groups = 0
        _materialized_at.clear()


class EventGroupMetaKey(enum.Enum):
    """Reference: PipelineEventGroup.h metadata keys."""

    LOG_FILE_PATH = "log.file.path"
    LOG_FILE_PATH_RESOLVED = "log.file.path_resolved"
    LOG_FILE_INODE = "log.file.inode"
    LOG_FILE_DEV = "log.file.dev"
    # multiline stitch markers (reader ↔ split_multiline carry contract)
    ML_PARTIAL_TAIL = "log.file.ml_partial_tail"
    ML_CONTINUE = "log.file.ml_continue"
    LOG_FILE_OFFSET = "log.file.offset"
    LOG_FILE_LENGTH = "log.file.length"
    # crc32 of the SOURCE byte-span [offset, offset+length) — loongcrash
    # replay dedup verifies content identity, not just span containment
    LOG_FILE_CRC32 = "log.file.crc32"
    IS_REPLAY = "internal.is.replay"
    # loongslo: monotonic-ns ingest stamp minted at the B_INGEST admit —
    # derived groups must carry it (loonglint: stamp-propagation)
    INGEST_NS = "internal.ingest.ns"
    SOURCE_ID = "source_id"
    TOPIC = "topic"
    HOST_NAME = "host.name"
    HOST_IP = "host.ip"
    INTERNAL_DATA_TYPE = "internal.data.type"
    CONTAINER_INFO = "container.info"


class ColumnarLogs:
    """Columnar log events over a shared arena.

    offsets/lengths: int32 [N] — raw content span of each event in the arena.
    timestamps:      int64 [N]
    fields:          name -> (offsets int32 [N], lengths int32 [N]) parsed
                     field spans (device kernel output).  Length -1 marks
                     "field absent" (parse failed for that event).
    """

    __slots__ = ("offsets", "lengths", "timestamps", "fields", "parse_ok",
                 "content_consumed", "span_matrix")

    def __init__(self, offsets: np.ndarray, lengths: np.ndarray,
                 timestamps: Optional[np.ndarray] = None):
        self.offsets = np.asarray(offsets, dtype=np.int32)
        self.lengths = np.asarray(lengths, dtype=np.int32)
        if timestamps is None:
            timestamps = np.zeros(len(self.offsets), dtype=np.int64)
        self.timestamps = np.asarray(timestamps, dtype=np.int64)
        self.fields: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        self.parse_ok: Optional[np.ndarray] = None  # bool [N]
        # False until a parse processor replaces the raw content span with
        # extracted fields; until then `content` remains a live column even
        # when auxiliary fields exist (e.g. container stream tags)
        self.content_consumed = False
        # serializer fast path: when the parse kernel's [N, F] span matrices
        # cover the field dict exactly, serialization reads them directly
        # (no per-field slicing / restacking).  (names, off_mat, len_mat,
        # column_view_tuples); any later set_field invalidates it.
        self.span_matrix: Optional[
            Tuple[List, np.ndarray, np.ndarray, List]] = None

    def __len__(self) -> int:
        return int(self.offsets.shape[0])

    @property
    def total_bytes(self) -> int:
        return int(self.lengths.sum())

    def set_field(self, name: str, offsets: np.ndarray, lengths: np.ndarray) -> None:
        self.fields[name] = (np.asarray(offsets, dtype=np.int32),
                             np.asarray(lengths, dtype=np.int32))
        self.span_matrix = None

    def set_fields_matrix(self, names: List, off_mat: np.ndarray,
                          len_mat: np.ndarray) -> None:
        """Install parsed fields from [N, F] span matrices.  Field columns
        become views; when no other fields exist the serializer consumes the
        matrices without a transpose.  The exact column tuples are kept in
        span_matrix so the serializer can verify (by identity) that no
        processor replaced or renamed fields behind its back."""
        off_mat = np.ascontiguousarray(off_mat, dtype=np.int32)
        len_mat = np.ascontiguousarray(len_mat, dtype=np.int32)
        fresh = not self.fields
        views = []
        for g, name in enumerate(names):
            pair = (off_mat[:, g], len_mat[:, g])
            self.fields[name] = pair
            views.append(pair)
        self.span_matrix = ((list(names), off_mat, len_mat, views)
                            if fresh else None)


class PipelineEventGroup:
    __slots__ = ("_source_buffer", "_metadata", "_tags", "_events", "_columns",
                 "_exactly_once_checkpoint")

    def __init__(self, source_buffer: Optional[SourceBuffer] = None):
        self._source_buffer = source_buffer if source_buffer is not None else SourceBuffer()
        self._metadata: Dict[EventGroupMetaKey, StringView] = {}
        self._tags: Dict[bytes, StringView] = {}
        self._events: List[PipelineEvent] = []
        self._columns: Optional[ColumnarLogs] = None
        self._exactly_once_checkpoint = None

    # -- buffer -------------------------------------------------------------

    @property
    def source_buffer(self) -> SourceBuffer:
        return self._source_buffer

    # -- metadata / tags ----------------------------------------------------

    def set_metadata(self, key: EventGroupMetaKey, value: AnyStr) -> None:
        vv = value if isinstance(value, StringView) else self._source_buffer.copy_string(value)
        self._metadata[key] = vv

    def get_metadata(self, key: EventGroupMetaKey) -> Optional[StringView]:
        return self._metadata.get(key)

    def has_metadata(self, key: EventGroupMetaKey) -> bool:
        return key in self._metadata

    def del_metadata(self, key: EventGroupMetaKey) -> None:
        self._metadata.pop(key, None)

    @property
    def metadata(self) -> Dict[EventGroupMetaKey, StringView]:
        return self._metadata

    def set_tag(self, key: AnyStr, value: AnyStr) -> None:
        vv = value if isinstance(value, StringView) else self._source_buffer.copy_string(value)
        self._tags[as_bytes(key)] = vv

    def get_tag(self, key: AnyStr) -> Optional[StringView]:
        return self._tags.get(as_bytes(key))

    def del_tag(self, key: AnyStr) -> None:
        self._tags.pop(as_bytes(key), None)

    @property
    def tags(self) -> Dict[bytes, StringView]:
        return self._tags

    # -- events (row representation) ---------------------------------------

    @property
    def events(self) -> List[PipelineEvent]:
        if self._columns is not None and not self._events:
            self.materialize("events_property")
        return self._events

    def add_event(self, event: PipelineEvent) -> None:
        self._events.append(event)

    def add_log_event(self, timestamp: int = 0) -> LogEvent:
        ev = LogEvent(timestamp)
        self._events.append(ev)
        return ev

    def add_metric_event(self, timestamp: int = 0) -> MetricEvent:
        ev = MetricEvent(timestamp)
        self._events.append(ev)
        return ev

    def add_span_event(self, timestamp: int = 0) -> SpanEvent:
        ev = SpanEvent(timestamp)
        self._events.append(ev)
        return ev

    def add_raw_event(self, timestamp: int = 0) -> RawEvent:
        ev = RawEvent(timestamp)
        self._events.append(ev)
        return ev

    def __len__(self) -> int:
        if self._columns is not None and not self._events:
            return len(self._columns)
        return len(self._events)

    def empty(self) -> bool:
        return len(self) == 0

    def event_type(self) -> EventType:
        if self._columns is not None and not self._events:
            return EventType.LOG
        return self._events[0].type if self._events else EventType.NONE

    # -- columnar representation (TPU fast path) ----------------------------

    @property
    def columns(self) -> Optional[ColumnarLogs]:
        return self._columns

    def set_columns(self, columns: ColumnarLogs) -> None:
        self._columns = columns
        self._events = []

    def is_columnar(self) -> bool:
        return self._columns is not None

    def materialize(self, where: str = "") -> List[PipelineEvent]:
        """Expand columns into per-event LogEvent objects (slow path).

        ``where`` names the boundary that forced the expansion (plugin id /
        ``"events_property"``) — every call is counted in churn_stats(), so
        a hot path that silently falls off the columnar plane shows up in
        bench extra.alloc instead of just running slow."""
        cols = self._columns
        if cols is None:
            return self._events
        _note_materialized(len(cols), where)
        sb = self._source_buffer
        events: List[PipelineEvent] = []
        field_items = list(cols.fields.items())
        offs = cols.offsets
        lens = cols.lengths
        tss = cols.timestamps
        # consumed content NEVER resurrects, even when every field was
        # later dropped (all-failed + discard configs); the raw-tail case
        # (no parse ran) is exactly content_consumed == False
        emit_content = not cols.content_consumed
        for i in range(len(cols)):
            ev = LogEvent(int(tss[i]))
            if emit_content:
                ev.set_content(b"content", sb.view(int(offs[i]), int(lens[i])))
            for name, (foffs, flens) in field_items:
                flen = int(flens[i])
                if flen >= 0:
                    ev.set_content(name.encode() if isinstance(name, str) else name,
                                   sb.view(int(foffs[i]), flen))
            events.append(ev)
        self._events = events
        return events

    def data_size(self) -> int:
        if self._columns is not None and not self._events:
            return self._columns.total_bytes
        total = 0
        for ev in self._events:
            if isinstance(ev, LogEvent):
                for k, v in ev.contents:
                    total += len(k) + len(v)
            elif isinstance(ev, RawEvent) and ev.content is not None:
                total += len(ev.content)
            else:
                total += 64  # metric/span rough estimate
        return total

    # -- JSON round-trip (test fixture format, SURVEY.md §4) ----------------

    def to_json(self) -> str:
        out: dict = {
            "metadata": {k.value: str(v) for k, v in self._metadata.items()},
            "tags": {k.decode("utf-8", "replace"): str(v) for k, v in self._tags.items()},
            "events": [],
        }
        for ev in self.events:
            if isinstance(ev, LogEvent):
                out["events"].append({
                    "type": "log",
                    "timestamp": ev.timestamp,
                    "contents": {str(k): str(v) for k, v in ev.contents},
                })
            elif isinstance(ev, MetricEvent):
                item = {
                    "type": "metric",
                    "timestamp": ev.timestamp,
                    "name": metric_name_str(ev.name),
                    "tags": {k.decode("utf-8", "replace"): str(v) for k, v in ev.tags.items()},
                }
                if ev.value.is_multi():
                    item["values"] = {k.decode("utf-8", "replace"): v
                                      for k, v in ev.value.values.items()}
                else:
                    item["value"] = ev.value.value
                out["events"].append(item)
            elif isinstance(ev, SpanEvent):
                out["events"].append({
                    "type": "span",
                    "timestamp": ev.timestamp,
                    "traceId": ev.trace_id.decode("utf-8", "replace"),
                    "spanId": ev.span_id.decode("utf-8", "replace"),
                    "name": ev.name.decode("utf-8", "replace"),
                    "kind": int(ev.kind),
                    "startTimeNs": ev.start_time_ns,
                    "endTimeNs": ev.end_time_ns,
                    "attributes": {k.decode("utf-8", "replace"): str(v)
                                   for k, v in ev.attributes.items()},
                })
            elif isinstance(ev, RawEvent):
                out["events"].append({
                    "type": "raw",
                    "timestamp": ev.timestamp,
                    "content": str(ev.content) if ev.content else "",
                })
        return json.dumps(out, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "PipelineEventGroup":
        data = json.loads(text)
        group = cls()
        sb = group.source_buffer
        for k, v in data.get("metadata", {}).items():
            group.set_metadata(EventGroupMetaKey(k), v)
        for k, v in data.get("tags", {}).items():
            group.set_tag(k, v)
        for item in data.get("events", []):
            typ = item.get("type", "log")
            if typ == "log":
                ev = group.add_log_event(item.get("timestamp", 0))
                for k, v in item.get("contents", {}).items():
                    ev.set_content(sb.copy_string(k), sb.copy_string(v))
            elif typ == "metric":
                ev = group.add_metric_event(item.get("timestamp", 0))
                ev.set_name(sb.copy_string(item.get("name", "")))
                if "values" in item:
                    ev.set_multi_value(item["values"])
                else:
                    ev.set_value(item.get("value", 0.0))
                for k, v in item.get("tags", {}).items():
                    ev.set_tag(k, sb.copy_string(v))
            elif typ == "span":
                ev = group.add_span_event(item.get("timestamp", 0))
                ev.trace_id = item.get("traceId", "").encode()
                ev.span_id = item.get("spanId", "").encode()
                ev.name = item.get("name", "").encode()
                ev.kind = SpanEvent.Kind(item.get("kind", 0))
                ev.start_time_ns = item.get("startTimeNs", 0)
                ev.end_time_ns = item.get("endTimeNs", 0)
                for k, v in item.get("attributes", {}).items():
                    ev.set_attribute(k, sb.copy_string(v))
            elif typ == "raw":
                ev = group.add_raw_event(item.get("timestamp", 0))
                ev.set_content(sb.copy_string(item.get("content", "")))
        return group

    def copy_meta_to(self, other: "PipelineEventGroup") -> None:
        for k, v in self._metadata.items():
            other.set_metadata(k, v.to_bytes())
        for k, v in self._tags.items():
            other.set_tag(k, v.to_bytes())
