"""ctypes bridge to the C++ native data plane (native/).

Loads libloongcollector_native.so if present (building it once with the
repo's Makefile when a toolchain is available); every entry point has a
pure-numpy/Python fallback so the framework runs without the library.

Reference parity: the reference's equivalents are C++ (LogFileReader line
alignment, the batch staging copy, core/protobuf/sls/LogGroupSerializer).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

from .utils.logger import get_logger

log = get_logger("native")

_lib = None
_load_lock = threading.Lock()
_load_attempted = False

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libloongcollector_native.so")


def _so_path() -> str:
    """LOONG_NATIVE_LIB points the bridge at an alternate build — the
    sanitizer harness (scripts/sanitize.sh) loads its ASan/TSan
    instrumented library without touching the release artifact."""
    return os.environ.get("LOONG_NATIVE_LIB") or _SO_PATH


def _try_build() -> bool:
    makefile = os.path.join(_NATIVE_DIR, "Makefile")
    if not os.path.exists(makefile):
        return False
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR, "-s"], check=True,
                       timeout=120, capture_output=True)
        return os.path.exists(_SO_PATH)
    except (OSError, subprocess.SubprocessError):
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    if _lib is not None or _load_attempted:
        return _lib
    with _load_lock:
        if _lib is not None or _load_attempted:
            return _lib
        _load_attempted = True
        if os.environ.get("LOONG_DISABLE_NATIVE"):
            return None
        so_path = _so_path()
        overridden = so_path != _SO_PATH
        # an explicit override must load exactly what it names — never
        # fall back to (or rebuild over) the release artifact
        if not os.path.exists(so_path) and (overridden or not _try_build()):
            log.info("native library unavailable; using python fallbacks")
            return None
        try:
            lib = ctypes.CDLL(so_path)
        except OSError as e:
            log.warning("failed to load native library: %s", e)
            return None
        if not overridden and (
                not hasattr(lib, "lct_t1_exec")
                or not hasattr(lib, "lct_ndjson_serialize")
                or not hasattr(lib, "lct_struct_index")
                or not hasattr(lib, "lct_group_reduce")):
            # stale build predating the newest entry point: rebuild + reload
            if _try_build():
                try:
                    lib = ctypes.CDLL(so_path)
                except OSError:
                    pass
        # pointer params bind as c_void_p and calls pass raw addresses
        # (arr.ctypes.data): ctypes POINTER casts cost ~2 us each and the
        # hot wrappers pass ~20 pointers per group
        u8p = ctypes.c_void_p
        i32p = ctypes.c_void_p
        i64p = ctypes.c_void_p
        lib.lct_split_lines.restype = ctypes.c_int64
        lib.lct_split_lines.argtypes = [u8p, ctypes.c_int64, ctypes.c_uint8,
                                        ctypes.c_int64, i32p, i32p]
        lib.lct_pack_rows.restype = None
        lib.lct_pack_rows.argtypes = [u8p, ctypes.c_int64, i64p, i32p,
                                      ctypes.c_int64, ctypes.c_int64, u8p]
        lib.lct_json_extract.restype = None
        lib.lct_json_extract.argtypes = [u8p, ctypes.c_int64, i64p, i32p,
                                         ctypes.c_int64, u8p, i32p,
                                         ctypes.c_int64, i32p, i32p,
                                         u8p, u8p]
        lib.lct_sls_serialize.restype = ctypes.c_int64
        lib.lct_sls_serialize.argtypes = [u8p, ctypes.c_int64, i64p,
                                          ctypes.c_int64, ctypes.c_int64,
                                          u8p, i32p, i32p, i32p,
                                          u8p, ctypes.c_int64]
        if hasattr(lib, "lct_sls_serialize_strided"):
            lib.lct_sls_serialize_strided.restype = ctypes.c_int64
            lib.lct_sls_serialize_strided.argtypes = [
                u8p, ctypes.c_int64, i64p, ctypes.c_int64, ctypes.c_int64,
                u8p, i32p, i32p, i32p, ctypes.c_int64, ctypes.c_int64,
                u8p, ctypes.c_int64]
        if hasattr(lib, "lct_ndjson_serialize"):
            lib.lct_ndjson_serialize.restype = ctypes.c_int64
            lib.lct_ndjson_serialize.argtypes = [
                u8p, ctypes.c_int64, i64p, ctypes.c_int64, ctypes.c_int64,
                u8p, i32p, i32p, i32p, ctypes.c_int64, ctypes.c_int64,
                u8p, ctypes.c_int64, ctypes.c_int32,
                u8p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
                u8p, ctypes.c_int64, u8p, ctypes.c_int64]
        if hasattr(lib, "lct_struct_index"):
            lib.lct_struct_index.restype = None
            lib.lct_struct_index.argtypes = [
                u8p, ctypes.c_int64, i64p, i32p, ctypes.c_int64,
                ctypes.c_int32, ctypes.c_uint8, ctypes.c_uint8,
                ctypes.c_int64, u8p, u8p, u8p, u8p]
        if hasattr(lib, "lct_json_struct_parse"):
            lib.lct_json_struct_parse.restype = ctypes.c_int64
            lib.lct_json_struct_parse.argtypes = [
                u8p, ctypes.c_int64, i64p, i32p, ctypes.c_int64,
                u8p, i32p, ctypes.c_int64, i32p, i32p, u8p,
                u8p, ctypes.c_int64,
                i32p, i32p, i32p, i32p, i32p, ctypes.c_int64, i64p]
        if hasattr(lib, "lct_group_reduce"):
            lib.lct_group_reduce.restype = ctypes.c_int64
            lib.lct_group_reduce.argtypes = [
                u8p, ctypes.c_int64,
                i64p, i64p, i32p, i64p, i32p,
                ctypes.c_int64, ctypes.c_int64,
                ctypes.c_double, ctypes.c_int64,
                i32p, i32p, u8p, i64p, u8p, u8p, u8p,
                i64p, ctypes.c_int64]
        if hasattr(lib, "lct_delim_struct_parse"):
            lib.lct_delim_struct_parse.restype = ctypes.c_int64
            lib.lct_delim_struct_parse.argtypes = [
                u8p, ctypes.c_int64, i64p, i32p, ctypes.c_int64,
                ctypes.c_uint8, ctypes.c_uint8, ctypes.c_int64,
                i32p, i32p, i32p, u8p, ctypes.c_int64, i64p]
        for fn in ("lct_lz4_bound", "lct_lz4_compress", "lct_lz4_decompress",
                   "lct_snappy_bound", "lct_snappy_compress",
                   "lct_snappy_uncompressed_len", "lct_snappy_decompress"):
            f = getattr(lib, fn, None)
            if f is None:      # stale .so predating the codecs: rebuild once
                continue
            f.restype = ctypes.c_int64
            f.argtypes = ([ctypes.c_int64] if fn.endswith("bound")
                          else [u8p, ctypes.c_int64, u8p, ctypes.c_int64]
                          if not fn.endswith("uncompressed_len")
                          else [u8p, ctypes.c_int64])
        _lib = lib
        log.info("native library loaded: %s", so_path)
        return _lib


def _u8(a: np.ndarray) -> int:
    return a.ctypes.data


def _i32(a: np.ndarray) -> int:
    return a.ctypes.data


def _i64(a: np.ndarray) -> int:
    return a.ctypes.data


# ---------------------------------------------------------------------------
# wrappers (None return ⇒ caller should use its fallback)
# ---------------------------------------------------------------------------


_split_scratch = threading.local()


def split_lines(seg: np.ndarray, sep: int, base_offset: int
                ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    lib = get_lib()
    if lib is None or len(seg) == 0:
        return None
    seg = np.ascontiguousarray(seg)
    # worst case is one line per byte, so the span buffers are chunk-sized;
    # reuse a per-thread scratch instead of mapping/unmapping megabytes per
    # chunk and return right-sized copies (a few KB for real line counts)
    cap = len(seg) + 1
    sc = getattr(_split_scratch, "bufs", None)
    if sc is None or len(sc[0]) < cap:
        sc = (np.empty(cap, dtype=np.int32), np.empty(cap, dtype=np.int32))
        _split_scratch.bufs = sc
    offs, lens = sc
    n = lib.lct_split_lines(_u8(seg), len(seg), sep, base_offset,
                            _i32(offs), _i32(lens))
    return offs[:n].copy(), lens[:n].copy()


def pack_rows(arena: np.ndarray, offsets: np.ndarray, lengths: np.ndarray,
              L: int, B: int,
              out: Optional[np.ndarray] = None) -> Optional[np.ndarray]:
    lib = get_lib()
    if lib is None:
        return None
    arena = np.ascontiguousarray(arena)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    lengths = np.ascontiguousarray(lengths, dtype=np.int32)
    n = len(offsets)
    if out is not None:
        # batch-ring reuse: the C packer fully writes rows [0, n) (memcpy +
        # tail memset) but never touches the padding rows [n, B), which may
        # hold a previous generation's bytes — re-zero only those
        rows = out
        if n < B:
            rows[n:].fill(0)
    else:
        rows = np.zeros((B, L), dtype=np.uint8)
    lib.lct_pack_rows(_u8(arena), len(arena), _i64(offsets), _i32(lengths),
                      n, L, _u8(rows))
    return rows


def json_extract(arena: np.ndarray, offsets: np.ndarray,
                 lengths: np.ndarray, keys: list):
    """Flat-schema JSON field extraction.  keys: list[bytes] (≤128).
    Returns (offs [F,n] i32, lens [F,n] i32, ok [n] bool, fallback [n] bool)
    or None when the native lib is unavailable."""
    lib = get_lib()
    if lib is None or len(keys) > 128:
        return None
    arena = np.ascontiguousarray(arena)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    lengths = np.ascontiguousarray(lengths, dtype=np.int32)
    keys_blob = np.frombuffer(b"".join(keys) or b"\0", dtype=np.uint8).copy()
    key_lens = np.array([len(k) for k in keys], dtype=np.int32)
    n = len(offsets)
    F = len(keys)
    out_offs = np.zeros((F, n), dtype=np.int32)
    out_lens = np.full((F, n), -1, dtype=np.int32)
    ok = np.zeros(n, dtype=np.uint8)
    fallback = np.zeros(n, dtype=np.uint8)
    lib.lct_json_extract(_u8(arena), len(arena), _i64(offsets), _i32(lengths),
                         n, _u8(keys_blob), _i32(key_lens), F,
                         _i32(out_offs), _i32(out_lens), _u8(ok),
                         _u8(fallback))
    return out_offs, out_lens, ok.astype(bool), fallback.astype(bool)


STRUCT_MODE_JSON = 0
STRUCT_MODE_DELIM = 1


def struct_index(arena: np.ndarray, offsets: np.ndarray,
                 lengths: np.ndarray, mode: int = STRUCT_MODE_JSON,
                 sep: int = 0x2C, quote: int = 0x22,
                 W: Optional[int] = None):
    """Per-row structural bitmaps (loongstruct stage 1): uint64 [n, W]
    arrays (in_string, structural, escaped, quote) with row-local bit
    positions — the host reference the device twin
    (ops/kernels/struct_index.py) is differentially tested against.
    Returns None when the native library is unavailable."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "lct_struct_index"):
        return None
    arena = np.ascontiguousarray(arena)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    lengths = np.ascontiguousarray(lengths, dtype=np.int32)
    n = len(offsets)
    if W is None:
        W = max(1, (int(lengths.max()) + 63) // 64) if n else 1
    shape = (n, W)
    s_mask = np.zeros(shape, dtype=np.uint64)
    t_mask = np.zeros(shape, dtype=np.uint64)
    e_mask = np.zeros(shape, dtype=np.uint64)
    q_mask = np.zeros(shape, dtype=np.uint64)
    lib.lct_struct_index(_u8(arena), len(arena), _i64(offsets),
                         _i32(lengths), n, mode, sep, quote, W,
                         _u8(s_mask), _u8(t_mask), _u8(e_mask), _u8(q_mask))
    return s_mask, t_mask, e_mask, q_mask


def json_struct_parse(arena: np.ndarray, offsets: np.ndarray,
                      lengths: np.ndarray, keys: list,
                      extra_cap: Optional[int] = None):
    """Structural-index JSON parse (loongstruct stage 2).  keys:
    list[bytes] (<= 128).  Returns (offs [F,n] i32, lens [F,n] i32,
    status [n] u8 (0 parsed / 1 fallback / 2 parsed-with-extras),
    side bytes ndarray (the unescape arena, already right-sized),
    extras tuple of 5 int32 arrays (row, key_off, key_len, val_off,
    val_len)) or None when the native library is unavailable.  Span
    offsets >= len(arena) index into `side` at offset - len(arena)."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "lct_json_struct_parse") \
            or len(keys) > 128:
        return None
    arena = np.ascontiguousarray(arena)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    lengths = np.ascontiguousarray(lengths, dtype=np.int32)
    n = len(offsets)
    # side spans encode as arena_len + side_off in an int32
    total = int(lengths.clip(min=0).sum())
    if len(arena) + total >= 2**31 - 16:
        return None
    keys_blob, key_lens = _key_struct(tuple(keys))
    F = len(keys)
    # np.empty throughout: the C side fully writes status and every
    # out_lens slot (-1 default), and only the returned prefixes of the
    # side/extras buffers are exposed — zeroing here costs ~1 MB of
    # stores per group at bench rates for no observable difference
    out_offs = np.empty((F, n), dtype=np.int32)
    out_lens = np.empty((F, n), dtype=np.int32)
    status = np.empty(n, dtype=np.uint8)
    side = np.empty(max(total, 1), dtype=np.uint8)
    if extra_cap is None:
        extra_cap = 4 * n + 64
    extras = tuple(np.empty(extra_cap, dtype=np.int32) for _ in range(5))
    counts = np.zeros(4, dtype=np.int64)
    rc = lib.lct_json_struct_parse(
        _u8(arena), len(arena), _i64(offsets), _i32(lengths), n,
        _u8(keys_blob), _i32(key_lens), F, _i32(out_offs), _i32(out_lens),
        _u8(status), _u8(side), len(side),
        _i32(extras[0]), _i32(extras[1]), _i32(extras[2]),
        _i32(extras[3]), _i32(extras[4]), extra_cap, _i64(counts))
    if rc != 0:
        return None
    e = int(counts[1])
    return (out_offs, out_lens, status, side[: int(counts[0])],
            tuple(a[:e] for a in extras))


def delim_struct_parse(arena: np.ndarray, offsets: np.ndarray,
                       lengths: np.ndarray, sep: int, quote: int,
                       F: int):
    """Structural-index quote-mode delimiter parse: event-major spans
    (offs [n,F] i32, lens [n,F] i32, nfields [n] i32, side bytes).  Span
    offsets >= len(arena) index into `side`.  Returns None when the
    native library is unavailable."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "lct_delim_struct_parse") or F <= 0:
        return None
    arena = np.ascontiguousarray(arena)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    lengths = np.ascontiguousarray(lengths, dtype=np.int32)
    n = len(offsets)
    total = int(lengths.clip(min=0).sum())
    if len(arena) + total >= 2**31 - 16:
        return None
    out_offs = np.zeros((n, F), dtype=np.int32)
    out_lens = np.full((n, F), -1, dtype=np.int32)
    nfields = np.zeros(n, dtype=np.int32)
    side = np.empty(max(total, 1), dtype=np.uint8)
    counts = np.zeros(2, dtype=np.int64)
    rc = lib.lct_delim_struct_parse(
        _u8(arena), len(arena), _i64(offsets), _i32(lengths), n,
        sep, quote, F, _i32(out_offs), _i32(out_lens), _i32(nfields),
        _u8(side), len(side), _i64(counts))
    if rc != 0:
        return None
    return out_offs, out_lens, nfields, side[: int(counts[0])]


def group_reduce(arena: np.ndarray, slots: np.ndarray,
                 key_offs: np.ndarray, key_lens: np.ndarray,
                 val_offs: np.ndarray, val_lens: np.ndarray,
                 hist_base: float = 1.0, n_hist: int = 41):
    """loongagg fold (native substrate): hashed segment identity over
    (window slot, K key spans) + row-order f64 reduction.

    slots i64 [n]; key_offs i64 / key_lens i32 [n, K] (len -1 = absent);
    val_offs i64 / val_lens i32 [n].  Returns (group_id i32 [n] with -1
    marking invalid-value rows, rep_row i32 [G], sum f64 [G], count i64
    [G], min f64 [G], max f64 [G], last f64 [G], hist i64 [G, n_hist]) —
    group ids in first-seen row order, the same partition and the same
    accumulation order as the numpy twin (bit-identical by the
    scripts/agg_equivalence.py gate).  None when the native library is
    unavailable."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "lct_group_reduce"):
        return None
    arena = np.ascontiguousarray(arena)
    slots = np.ascontiguousarray(slots, dtype=np.int64)
    key_offs = np.ascontiguousarray(key_offs, dtype=np.int64)
    key_lens = np.ascontiguousarray(key_lens, dtype=np.int32)
    val_offs = np.ascontiguousarray(val_offs, dtype=np.int64)
    val_lens = np.ascontiguousarray(val_lens, dtype=np.int32)
    n = len(slots)
    K = key_offs.shape[1] if key_offs.ndim == 2 else 1
    group_id = np.empty(max(n, 1), dtype=np.int32)
    # start with a small group capacity (the common case: cardinality per
    # batch << rows per batch) and retry once at the n ceiling on -1
    cap = min(n, 4096) or 1
    while True:
        rep_row = np.empty(cap, dtype=np.int32)
        sums = np.empty(cap, dtype=np.float64)
        cnt = np.empty(cap, dtype=np.int64)
        mn = np.empty(cap, dtype=np.float64)
        mx = np.empty(cap, dtype=np.float64)
        last = np.empty(cap, dtype=np.float64)
        hist = np.empty((cap, n_hist), dtype=np.int64)
        rc = lib.lct_group_reduce(
            _u8(arena), len(arena), _i64(slots), _i64(key_offs),
            _i32(key_lens), _i64(val_offs), _i32(val_lens), n, K,
            ctypes.c_double(hist_base), n_hist,
            _i32(group_id), _i32(rep_row), _u8(sums), _i64(cnt),
            _u8(mn), _u8(mx), _u8(last), _i64(hist), cap)
        if rc == -1 and cap < n:
            cap = n
            continue
        if rc < 0:
            return None
        G = int(rc)
        return (group_id[:n], rep_row[:G], sums[:G], cnt[:G], mn[:G],
                mx[:G], last[:G], hist[:G])


_key_cache: dict = {}
_key_cache_lock = threading.Lock()


def _key_struct(keys: tuple) -> Tuple[np.ndarray, np.ndarray]:
    """(keys_blob, key_lens) for a key tuple — serializers call with the
    same schema for every group, so build the arrays once (the per-call
    join+copy was measurable at pipeline-e2e rates)."""
    with _key_cache_lock:
        st = _key_cache.get(keys)
    if st is None:
        # build OUTSIDE the lock (the join is O(schema) work); the
        # setdefault makes a racing double-build harmless
        blob = np.frombuffer(b"".join(keys) or b"\0",
                             dtype=np.uint8).copy()
        lens = np.array([len(k) for k in keys], dtype=np.int32)
        with _key_cache_lock:
            if len(_key_cache) >= 256:    # unbounded schemas must not leak
                _key_cache.clear()
            st = _key_cache.setdefault(keys, (blob, lens))
    return st


def sls_serialize(arena: np.ndarray, timestamps: np.ndarray,
                  keys: list, field_offs: np.ndarray, field_lens: np.ndarray,
                  event_major: bool = False) -> Optional[bytes]:
    """keys: list[bytes] (≤64); field_offs/field_lens: int32 — [F, n]
    field-major by default, [n, F] when event_major=True (the parse-kernel
    output layout, serialized without a transpose)."""
    lib = get_lib()
    if lib is None or len(keys) > 64:
        return None
    if event_major and not hasattr(lib, "lct_sls_serialize_strided"):
        return None
    arena = np.ascontiguousarray(arena)
    timestamps = np.ascontiguousarray(timestamps, dtype=np.int64)
    field_offs = np.ascontiguousarray(field_offs, dtype=np.int32)
    field_lens = np.ascontiguousarray(field_lens, dtype=np.int32)
    keys_blob, key_lens = _key_struct(tuple(keys))
    F = len(keys)
    n = len(timestamps)
    sf, si = (1, F) if event_major else (n, 1)
    # cheap capacity bound: field values live in the arena, so arena_len
    # covers Σvlen unless spans overlap (keep-source cases) — then the call
    # returns -needed and the exact-size retry below handles it
    cap = int(len(arena) + n * (int(key_lens.sum()) + 12 * F + 16) + 64)

    def call(buf, buf_cap):
        if event_major:
            return lib.lct_sls_serialize_strided(
                _u8(arena), len(arena), _i64(timestamps), n, F,
                _u8(keys_blob), _i32(key_lens), _i32(field_offs),
                _i32(field_lens), sf, si, _u8(buf), buf_cap)
        return lib.lct_sls_serialize(
            _u8(arena), len(arena), _i64(timestamps), n, F, _u8(keys_blob),
            _i32(key_lens), _i32(field_offs), _i32(field_lens), _u8(buf),
            buf_cap)

    out = np.empty(cap, dtype=np.uint8)
    written = call(out, cap)
    if written < 0:
        # exact-size retry; the +16 is part of the declared capacity so the
        # 16-byte fast copies stay legal right up to the payload end
        out = np.empty(-written + 16, dtype=np.uint8)
        written = call(out, -written + 16)
        if written < 0:
            return None
    # a view, not bytes: the serializer joins parts once — an extra
    # tobytes here would copy the (larger-than-input) payload again
    return memoryview(out)[:written]


NDJSON_TS_NONE = 0
NDJSON_TS_EPOCH = 1
NDJSON_TS_ISO8601 = 2


def ndjson_serialize(arena: np.ndarray, timestamps: np.ndarray,
                     key_frags: tuple, field_offs: np.ndarray,
                     field_lens: np.ndarray, prefix: bytes,
                     prefix_members: bool, ts_frag: bytes, ts_mode: int,
                     ts_first: bool, suffix: bytes = b"\n",
                     event_major: bool = False) -> Optional[memoryview]:
    """NDJSON rows from columnar spans (loongshard zero-copy fast path).

    key_frags: per-field ``b'"key": "'`` fragments (keys pre-escaped by the
    caller); prefix: row head (``{`` + encoded group tags, no trailing
    separator); ts_frag: ``b'"<key>": '``.  Caller guarantees every emitted
    span is valid UTF-8 (json.dumps replacement semantics live on the
    Python fallback).  Returns a memoryview over the output buffer, or
    None when the library is unavailable / the row shape is unsupported."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "lct_ndjson_serialize") \
            or len(key_frags) > 64:
        return None
    arena = np.ascontiguousarray(arena)
    timestamps = np.ascontiguousarray(timestamps, dtype=np.int64)
    field_offs = np.ascontiguousarray(field_offs, dtype=np.int32)
    field_lens = np.ascontiguousarray(field_lens, dtype=np.int32)
    frags_blob, frag_lens = _key_struct(key_frags)
    F = len(key_frags)
    n = len(timestamps)
    sf, si = (1, F) if event_major else (n, 1)
    prefix_b = np.frombuffer(prefix or b"\0", dtype=np.uint8)
    ts_b = np.frombuffer(ts_frag or b"\0", dtype=np.uint8)
    suffix_b = np.frombuffer(suffix or b"\0", dtype=np.uint8)
    # worst case: every value byte expands 6x (\u00XX), plus per-row
    # framing — mirrors the C row bound so -1 can only mean "unsupported"
    cap = int(n * (len(prefix) + len(ts_frag) + 48 + int(frag_lens.sum())
                   + 4 * F + len(suffix) + 2) + 6 * len(arena) + 64)
    out = np.empty(cap, dtype=np.uint8)
    written = lib.lct_ndjson_serialize(
        _u8(arena), len(arena), _i64(timestamps), n, F,
        _u8(frags_blob), _i32(frag_lens), _i32(field_offs),
        _i32(field_lens), sf, si,
        _u8(prefix_b), len(prefix), 1 if prefix_members else 0,
        _u8(ts_b), len(ts_frag), ts_mode, 1 if ts_first else 0,
        _u8(suffix_b), len(suffix), _u8(out), cap)
    if written < 0:
        return None
    return memoryview(out)[:written]


def _codec(fn_c, fn_bound, data: bytes) -> Optional[bytes]:
    lib = get_lib()
    if lib is None or not hasattr(lib, fn_c):
        return None
    src = np.frombuffer(data, dtype=np.uint8)
    cap = int(getattr(lib, fn_bound)(len(src)))
    out = np.empty(max(cap, 16), dtype=np.uint8)
    n = getattr(lib, fn_c)(_u8(src), len(src), _u8(out), len(out))
    if n < 0:
        return None
    return out[:n].tobytes()


def lz4_compress(data: bytes) -> Optional[bytes]:
    """LZ4 block format (raw, no frame) — SLS's default wire codec."""
    return _codec("lct_lz4_compress", "lct_lz4_bound", data)


def lz4_decompress(data: bytes, raw_size: int) -> Optional[bytes]:
    lib = get_lib()
    if lib is None or not hasattr(lib, "lct_lz4_decompress"):
        return None
    src = np.frombuffer(data, dtype=np.uint8)
    out = np.empty(max(raw_size, 1), dtype=np.uint8)
    n = lib.lct_lz4_decompress(_u8(src), len(src), _u8(out), raw_size)
    if n < 0:
        return None
    return out[:n].tobytes()


def snappy_compress(data: bytes) -> Optional[bytes]:
    """Snappy block format — required by Prometheus remote-write."""
    return _codec("lct_snappy_compress", "lct_snappy_bound", data)


def snappy_decompress(data: bytes) -> Optional[bytes]:
    lib = get_lib()
    if lib is None or not hasattr(lib, "lct_snappy_decompress"):
        return None
    src = np.frombuffer(data, dtype=np.uint8)
    raw = lib.lct_snappy_uncompressed_len(_u8(src), len(src))
    if raw < 0:
        return None
    out = np.empty(max(int(raw), 1), dtype=np.uint8)
    n = lib.lct_snappy_decompress(_u8(src), len(src), _u8(out), int(raw))
    if n != raw:
        return None
    return out[:n].tobytes()
