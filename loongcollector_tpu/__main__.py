from .application import main
import sys

sys.exit(main())
