"""Async overlapped host↔device data plane.

The reference overlaps every pipeline stage with dedicated threads and queue
hops (core/runner/ProcessorRunner.cpp:90-189, core/runner/FlusherRunner.cpp:168);
its BoundedProcessQueue watermarks gate the producers
(core/collection_pipeline/queue/BoundedProcessQueue.cpp:89-93).  The TPU
analogue (SURVEY.md §7 step 4, §5.8) is this plane: device kernel dispatches
are ASYNC (jax returns device buffers immediately; computation proceeds in the
background), so the host packs and dispatches chunk N+1 while the device
executes chunk N, and materialises results strictly as needed.

Back-pressure contract: every dispatch acquires from a process-wide in-flight
byte budget and releases it on materialisation.  When the device stalls (or a
tunnel wedges), the budget fills, `submit` blocks, the runner thread stops
popping, the bounded process queues hit their high watermark, and the file
inputs get feedback-blocked — the exact chain the reference builds between
FlusherRunner, the sender queues and the process queues, extended one hop
further onto the device.

Nothing here imports jax: the plane is agnostic to WHAT is dispatched — it
only requires that calling the kernel is cheap (async dispatch) and that
`numpy.asarray` on the returned buffers blocks until the device is done.
That contract holds for jax on every backend and for the latency-injection
test kernel below.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from .. import chaos, prof, trace
from . import xprof
from ..utils.logger import get_logger

log = get_logger("device_plane")

_DEFAULT_BUDGET = 64 * 1024 * 1024  # bytes of packed rows in flight

FP_SUBMIT = chaos.register_point("device_plane.submit")

_tls = threading.local()

# ---------------------------------------------------------------------------
# loongtenant: per-tenant (per-pipeline) shares of the in-flight byte budget.
#
# The chip-lane share mechanics (ops/chip_lanes.ChipLane.over_share),
# re-keyed per pipeline: with N registered tenants each gets budget/N, and
# a tenant dispatching past its share must drain ITS OWN oldest in-flight
# chunk first (the caller's on_wait hook — the same never-sleep-owning-
# budget discipline, per tenant).  Other tenants are untouched: they only
# ever wait on the GLOBAL budget, so one hot pipeline's backlog drains
# through its own lane instead of starving the other 255.
#
# The registry is module-level (not per-plane) so reset_for_testing()
# cannot orphan accounting, and the worker binds its current tenant via
# TLS (set_thread_tenant) exactly like chip_lanes.set_thread_lane.

_tenant_lock = threading.Lock()
_tenant_registered: set = set()            # tenant names holding a share
_tenant_inflight: Dict[str, int] = {}      # name -> dispatched bytes in flight


def set_thread_tenant(name: Optional[str]) -> None:
    """Bind THIS thread's dispatches to a tenant (the processor runner
    sets the owning pipeline's name around process/complete; None
    unbinds)."""
    _tls.tenant = name


def current_tenant() -> Optional[str]:
    return getattr(_tls, "tenant", None)


def register_tenant(name: str) -> None:
    """Grant `name` a share of the plane budget (pipeline manager, at
    config apply).  Re-registering an existing tenant (a reload's next
    generation) is a no-op — the share follows the NAME, not the
    generation."""
    if not name:
        return
    with _tenant_lock:
        _tenant_registered.add(name)


def unregister_tenant(name: str) -> None:
    """Drop `name`'s share (pipeline removed).  In-flight accounting for
    still-unresolved futures survives until they settle."""
    with _tenant_lock:
        _tenant_registered.discard(name)
        if not _tenant_inflight.get(name):
            _tenant_inflight.pop(name, None)


def tenant_count() -> int:
    with _tenant_lock:
        return len(_tenant_registered)


def _tenant_note(name: str, delta: int) -> None:
    with _tenant_lock:
        cur = max(0, _tenant_inflight.get(name, 0) + delta)
        if cur == 0 and name not in _tenant_registered:
            _tenant_inflight.pop(name, None)
        else:
            _tenant_inflight[name] = cur


def tenant_inflight_bytes(name: str) -> int:
    with _tenant_lock:
        return _tenant_inflight.get(name, 0)


def tenant_share_bytes(budget_bytes: int) -> int:
    """One tenant's slice of the plane budget (0 = sharing inactive:
    fewer than two tenants, or an unbounded plane)."""
    with _tenant_lock:
        n = len(_tenant_registered)
    if n <= 1 or not budget_bytes:
        return 0
    return budget_bytes // n


def tenant_over_share(name: str, nbytes: int, budget_bytes: int) -> bool:
    """True when dispatching `nbytes` more would push `name` past its
    per-tenant share.  Never true with <2 tenants (the single-tenant
    agent keeps the whole budget — exactly the pre-tenant behaviour)."""
    share = tenant_share_bytes(budget_bytes)
    if not share:
        return False
    with _tenant_lock:
        held = _tenant_inflight.get(name, 0)
    return held > 0 and held + nbytes > share


def tenant_snapshot(budget_bytes: Optional[int] = None) -> Dict[str, dict]:
    """Per-tenant budget view for /debug/status (observe-only)."""
    if budget_bytes is None:
        plane = DevicePlane._instance
        budget_bytes = plane.budget_bytes if plane is not None else 0
    share = tenant_share_bytes(budget_bytes)
    with _tenant_lock:
        names = set(_tenant_registered) | set(_tenant_inflight)
        rows = {n: _tenant_inflight.get(n, 0) for n in names}
    return {n: {"inflight_bytes": held,
                "share_bytes": share,
                "over_share": bool(share and held > share)}
            for n, held in sorted(rows.items())}


def reset_tenants_for_testing() -> None:
    with _tenant_lock:
        _tenant_registered.clear()
        _tenant_inflight.clear()

# ---------------------------------------------------------------------------
# loongxprof: device-memory accounting — a ledger-style live/peak byte
# ledger per allocation family.  Always on (unlike the timeline): the
# hooks fire at lease/dispatch rate, not per-event rate, and every prior
# device PR has needed exactly this number after the fact.  Families:
#
#   ring_slots       — leased batch-ring staging slots (device_stream)
#   resident_columns — HBM-resident inter-stage columns held by in-flight
#                      fused dispatches (fused_pipeline)
#   dfa_tables       — memoized FusedDFA constant tables (regex/fuse)
#   sharded_staging  — per-shard device_put staging (parallel/mesh)
#   side_arenas      — kernel-side staging pools (segment_reduce etc.)
#
# Conservation contract: at quiesce, ``ring_slots`` live bytes must equal
# the ring's leased bytes (both zero once every slot returned) — the
# auditor folds the residual into its quiesced snapshot check.

MEM_FAMILIES = ("ring_slots", "resident_columns", "dfa_tables",
                "sharded_staging", "side_arenas")

_mem_lock = threading.Lock()
_mem: Dict[str, List[int]] = {}   # family -> [live, peak, allocs, frees]


def mem_note_alloc(family: str, nbytes: int) -> None:
    """Charge `nbytes` of device-resident memory to `family`."""
    if nbytes <= 0:
        return
    with _mem_lock:
        row = _mem.get(family)
        if row is None:
            row = _mem[family] = [0, 0, 0, 0]
        row[0] += nbytes
        if row[0] > row[1]:
            row[1] = row[0]
        row[2] += 1


def mem_note_free(family: str, nbytes: int) -> None:
    """Credit `nbytes` back to `family`.  Live bytes clamp at zero: a
    double-free is an accounting bug upstream, never a negative gauge."""
    if nbytes <= 0:
        return
    with _mem_lock:
        row = _mem.get(family)
        if row is None:
            row = _mem[family] = [0, 0, 0, 0]
        row[0] = max(0, row[0] - nbytes)
        row[3] += 1


def mem_live_bytes(family: str) -> int:
    with _mem_lock:
        row = _mem.get(family)
        return row[0] if row is not None else 0


def device_memory_status() -> dict:
    """Per-family live/peak ledger — the /debug/status ``device_memory``
    section and the auditor's conservation input."""
    with _mem_lock:
        fams = {f: {"live_bytes": row[0], "peak_bytes": row[1],
                    "allocs": row[2], "frees": row[3]}
                for f, row in sorted(_mem.items())}
        total_live = sum(row[0] for row in _mem.values())
    return {"families": fams, "total_live_bytes": total_live}


def mem_reset_for_testing() -> None:
    with _mem_lock:
        _mem.clear()

# submit→resolve stopwatch sink: one shared histogram (lazy so importing
# the plane never touches the metrics registry)
_rtt_hist = None


def roundtrip_histogram():
    """The device round-trip latency histogram (dispatch → materialise),
    observed by every DeviceFuture that resolves successfully."""
    global _rtt_hist
    if _rtt_hist is None:
        from ..monitor.metrics import shared_histogram
        _rtt_hist = shared_histogram("device_roundtrip_seconds",
                                     labels={"component": "device_plane"})
    return _rtt_hist


_dispatch_counter = None
_dispatch_counter_lock = threading.Lock()


def dispatch_counter():
    """``device_dispatch_total``: every kernel dispatch admitted through
    the plane budget, fused or per-stage — the loongresident
    dispatch-count ledger (rate() against batch counts recovers
    dispatches-per-batch, the number stage fusion collapses toward 1).
    Double-checked lock: concurrent first dispatches must not
    double-register the record (the aggregator-base race shape)."""
    global _dispatch_counter
    if _dispatch_counter is None:
        with _dispatch_counter_lock:
            if _dispatch_counter is None:
                from ..monitor.metrics import MetricsRecord
                rec = MetricsRecord(category="component",
                                    labels={"component": "device_plane"})
                _dispatch_counter = rec.counter("device_dispatch_total")
    return _dispatch_counter


_held_hist = None


def held_fraction_histogram():
    """Distribution of the budget fraction held at each dispatch — the
    loongprof utilization view: a histogram living near 1.0 means the
    budget (not the device) gates dispatch."""
    global _held_hist
    if _held_hist is None:
        from ..monitor.metrics import shared_histogram
        _held_hist = shared_histogram("device_budget_held_fraction",
                                      labels={"component": "device_plane"})
    return _held_hist


def note_host_backlog() -> None:
    """loongprof utilization probe, called by runner loops that just
    popped work while more work remains queued: if the device plane sits
    idle even though the host has backlog, the idle gap is charged to
    ``device_idle_while_backlogged_ms`` — the single number separating
    "shard more workers" (host-bound: counter grows) from "the device is
    the bottleneck" (counter flat while occupancy is high).  One global
    read when no plane was ever constructed."""
    plane = DevicePlane._instance
    if plane is not None:
        plane.note_backlogged()


def set_budget_relief(fn: Optional[Callable[[], bool]]) -> None:
    """Register this thread's last-resort budget releaser.  While a thread
    waits for budget in `submit`, the plane first lets the in-dispatch
    PendingParse drain its own chunks (`on_wait`); if that owns nothing, the
    relief hook runs — the ProcessorRunner registers one that completes the
    overlapped group it still holds.  Together they enforce the no-deadlock
    invariant: a thread waiting for budget never holds unmaterialised
    futures it cannot release itself."""
    _tls.relief = fn


def _budget_from_env() -> int:
    try:
        return int(os.environ.get("LOONG_DEVICE_INFLIGHT_BYTES",
                                  _DEFAULT_BUDGET))
    except ValueError:
        return _DEFAULT_BUDGET


class DeviceFuture:
    """A dispatched kernel call whose results are not yet materialised.

    `result()` converts the device buffers to numpy (blocking until the
    device finishes) and releases the plane budget exactly once.  If the
    kernel raised at dispatch or materialisation, the error is surfaced from
    `result()` so callers keep the reference's fail-at-consume semantics
    (engine.py routes Mosaic failures to the XLA path there).
    """

    __slots__ = ("_plane", "_nbytes", "_outputs", "_error", "_done",
                 "_materialised", "_t0", "_span", "_tenant", "_xid",
                 "__weakref__")

    def __init__(self, plane: "DevicePlane", nbytes: int,
                 outputs: Optional[Sequence] = None,
                 error: Optional[BaseException] = None,
                 span=None, tenant: Optional[str] = None, xid: int = 0):
        self._plane = plane
        self._nbytes = nbytes
        self._outputs = outputs
        self._error = error
        self._done = False
        self._materialised: Optional[List[np.ndarray]] = None
        # the submit→resolve stopwatch starts the moment the dispatched
        # future exists; result()/release() stops it exactly once
        self._t0 = time.perf_counter()
        self._span = span
        # loongtenant: which tenant's share these bytes count against —
        # credited back exactly once when the future settles
        self._tenant = tenant
        # loongxprof: the dispatch id correlating this future's device
        # legs with the host span that caused them (0 = plane off)
        self._xid = xid

    @property
    def dispatch_id(self) -> int:
        """loongxprof correlation id (0 when the timeline is off) — the
        dispatch loops read this to attribute program/geometry/pack legs
        via ``xprof.note_dispatch``."""
        return self._xid

    def _release_budget(self) -> None:
        self._plane._release(self._nbytes)
        if self._tenant is not None:
            _tenant_note(self._tenant, -self._nbytes)
            self._tenant = None
        # settle point: fold this dispatch's legs into the decomposition
        # histograms exactly once (no-op for xid 0 / plane off)
        xprof.close_dispatch(self._xid)

    def result(self) -> List[np.ndarray]:
        if self._done:
            if self._error is not None:
                raise self._error
            return self._materialised  # type: ignore[return-value]
        try:
            if self._error is not None:
                raise self._error
            # loongprof: materialisation is where the host actually waits
            # on the device — attribute that wall time to the device scope
            prof.push_marker("device", "materialise")
            try:
                xid = self._xid
                if xid:
                    # exec leg: dispatch return → first output ready (the
                    # device-execution window the host can observe); d2h
                    # leg: the numpy materialisation itself.  Without a
                    # block_until_ready the split collapses into d2h.
                    t_exec = time.perf_counter()
                    first = self._outputs[0] if self._outputs else None
                    if hasattr(first, "block_until_ready"):
                        first.block_until_ready()
                    t_d2h = time.perf_counter()
                    xprof.leg(xid, "exec", t_exec, t_d2h - t_exec)
                    self._materialised = [np.asarray(o)
                                          for o in self._outputs]
                    xprof.leg(xid, "d2h", t_d2h,
                              time.perf_counter() - t_d2h)
                else:
                    self._materialised = [np.asarray(o)
                                          for o in self._outputs]
            finally:
                prof.pop_marker()
            roundtrip_histogram().observe(time.perf_counter() - self._t0)
            if self._span is not None:
                self._span.end("ok")
            return self._materialised
        except BaseException as e:  # noqa: BLE001 — record, release, re-raise
            self._error = e
            if self._span is not None:
                self._span.end("error")
            raise
        finally:
            self._done = True
            self._outputs = None
            self._span = None
            self._release_budget()

    def release(self) -> None:
        """Force-release without materialising: error-path cleanup for a
        dispatch loop that cannot (or must not) consume this future.  The
        device buffers are dropped; the budget returns immediately."""
        if self._done:
            return
        self._done = True
        self._outputs = None
        if self._error is None:
            self._error = RuntimeError(
                "DeviceFuture released without materialisation")
        if self._span is not None:
            self._span.end("released")
            self._span = None
        self._release_budget()

    def __del__(self):
        # Last-resort budget backstop: an abandoned in-flight future must
        # never strand plane budget (the round-5 PendingParse.dispatch
        # leak).  Reaching this path is a bug upstream — warn loudly.
        try:
            if not self._done:
                self._done = True
                self._outputs = None
                if self._span is not None:
                    self._span.end("abandoned")
                    self._span = None
                self._release_budget()
                log.warning(
                    "DeviceFuture dropped without result()/release(); "
                    "budget (%d bytes) reclaimed by finaliser — fix the "
                    "owning dispatch path", self._nbytes)
        except Exception:  # noqa: BLE001 — never raise from a finaliser
            pass


class DevicePlane:
    """Process-wide async dispatch gate with an in-flight byte budget."""

    _instance: Optional["DevicePlane"] = None
    _instance_lock = threading.Lock()

    def __init__(self, budget_bytes: Optional[int] = None):
        self.budget_bytes = budget_bytes or _budget_from_env()
        self._inflight = 0
        self._dispatched = 0
        self._lock = threading.Lock()
        self._freed = threading.Condition(self._lock)
        self._closed = False
        # -- loongprof utilization accounting (all under self._lock) --------
        now = time.perf_counter()
        self._util_t0 = now                 # accounting epoch
        self._util_last = now               # last occupancy transition
        self._occupancy_integral = 0.0      # ∫ (inflight/budget) dt
        self._busy_s = 0.0                  # time with inflight > 0
        self._idle_since: Optional[float] = now
        self._idle_backlogged_ms = 0.0
        self._backlog_probe_at: Optional[float] = None
        self._waiters = 0                   # threads blocked in _acquire

    @classmethod
    def instance(cls) -> "DevicePlane":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    @classmethod
    def reset_for_testing(cls, budget_bytes: Optional[int] = None) -> "DevicePlane":
        with cls._instance_lock:
            cls._instance = cls(budget_bytes)
            return cls._instance

    # -- budget -------------------------------------------------------------

    def inflight_bytes(self) -> int:
        with self._lock:
            return self._inflight

    def dispatched_total(self) -> int:
        with self._lock:
            return self._dispatched

    def over_budget(self) -> bool:
        with self._lock:
            return self._inflight >= self.budget_bytes

    def would_block(self, nbytes: int) -> bool:
        """True when submit(nbytes) would have to wait for budget.  Dispatch
        loops that hold unmaterialised futures MUST consult this and drain
        their own oldest future first — never sleep in submit while owning
        the budget you are waiting for."""
        with self._lock:
            return (self._inflight + nbytes > self.budget_bytes
                    and self._inflight > 0)

    # -- utilization accounting (loongprof) ---------------------------------

    def _util_tick(self, now: float) -> None:
        """Lock held.  Fold the elapsed interval into the occupancy
        integrals BEFORE an inflight transition."""
        dt = now - self._util_last
        if dt > 0:
            self._occupancy_integral += (self._inflight / self.budget_bytes
                                         if self.budget_bytes else 0.0) * dt
            if self._inflight > 0:
                self._busy_s += dt
        self._util_last = now

    def note_backlogged(self) -> None:
        """The host has queued work RIGHT NOW (caller just popped an item
        with more behind it).  Charge the device-idle gap SINCE THE LAST
        backlogged probe to ``device_idle_while_backlogged_ms`` — the
        first probe of an idle span only arms the window, so the hour the
        agent sat idle with no traffic is never charged when a burst
        finally arrives (backlog must exist at BOTH ends of a charged
        gap).  Planes that never dispatched stay at zero — a pure-host
        pipeline's idle device is not a finding."""
        now = time.perf_counter()
        with self._lock:
            if self._dispatched == 0 or self._inflight > 0 \
                    or self._idle_since is None:
                self._backlog_probe_at = None
                return
            if self._backlog_probe_at is None:
                self._backlog_probe_at = now
                return
            start = max(self._idle_since, self._backlog_probe_at)
            if now > start:
                self._idle_backlogged_ms += (now - start) * 1000.0
            self._backlog_probe_at = now

    def utilization(self) -> dict:
        """Snapshot of the device-plane utilization accounting — the
        "shard more vs device-bound" dashboard (docs/observability.md)."""
        now = time.perf_counter()
        with self._lock:
            self._util_tick(now)
            elapsed = max(now - self._util_t0, 1e-9)
            return {
                "budget_bytes": self.budget_bytes,
                "inflight_bytes": self._inflight,
                "held_fraction": (self._inflight / self.budget_bytes
                                  if self.budget_bytes else 0.0),
                "occupancy_avg": self._occupancy_integral / elapsed,
                "busy_fraction": self._busy_s / elapsed,
                # raw monotone integrals: lifetime averages go inert on a
                # long-lived agent, but rate() over these recovers the
                # RECENT occupancy/busy fraction from any scrape pair
                "occupancy_integral_s": self._occupancy_integral,
                "busy_s": self._busy_s,
                "idle_while_backlogged_ms": self._idle_backlogged_ms,
                "submit_queue_depth": self._waiters,
                "dispatched_total": self._dispatched,
                "elapsed_s": elapsed,
            }

    def _acquire(self, nbytes: int,
                 should_abort: Optional[Callable[[], bool]] = None,
                 on_wait: Optional[Callable[[], bool]] = None) -> int:
        """Block until `nbytes` fits in the budget.  A single dispatch larger
        than the whole budget is admitted when nothing is in flight (it could
        otherwise never run).  This blocking IS the device back-pressure: the
        caller is a runner thread, and while it waits the bounded process
        queues upstream fill to their high watermark.

        `on_wait` is called OUTSIDE the lock on every wait iteration; a
        caller that owns unmaterialised futures must drain one there and
        return True (False = nothing owned).  That rule makes the budget
        deadlock-free: every waiting thread can always release the budget it
        itself holds, so some thread always makes progress."""
        waiting = False
        try:
            while True:
                with self._freed:
                    if self._closed or \
                            self._inflight + nbytes <= self.budget_bytes or \
                            self._inflight == 0:
                        self._util_tick(time.perf_counter())
                        self._inflight += nbytes
                        self._dispatched += 1
                        self._idle_since = None
                        # post-admission inflight, returned so the caller
                        # can observe THIS dispatch's held fraction without
                        # re-taking the lock (a later read would race
                        # concurrent releases)
                        return self._inflight
                    if should_abort is not None and should_abort():
                        raise DispatchAborted()
                    if not waiting:
                        # submit-queue depth: threads blocked on budget —
                        # sustained depth > 0 with high occupancy means the
                        # budget (or the device behind it) gates the host
                        waiting = True
                        self._waiters += 1
                progressed = on_wait() if on_wait is not None else False
                if not progressed:
                    relief = getattr(_tls, "relief", None)
                    progressed = bool(relief()) if relief is not None \
                        else False
                if not progressed:
                    with self._freed:
                        self._freed.wait(timeout=0.05)
        finally:
            if waiting:
                with self._lock:
                    self._waiters -= 1

    def _release(self, nbytes: int) -> None:
        with self._freed:
            self._util_tick(time.perf_counter())
            self._inflight = max(0, self._inflight - nbytes)
            if self._inflight == 0:
                self._idle_since = self._util_last
                self._backlog_probe_at = None
            self._freed.notify_all()

    def close(self) -> None:
        with self._freed:
            self._closed = True
            self._freed.notify_all()

    # -- dispatch -----------------------------------------------------------

    def open_stream(self, depth: Optional[int] = None):
        """A pipelined dispatch window over this plane (loongstream): up to
        ``depth`` batches in flight, strict submit-order results, ring
        advance on overflow — the streaming replacement for the
        submit→materialise round trip.  See ops/device_stream.DeviceStream."""
        from .device_stream import DeviceStream
        return DeviceStream(self, depth)

    def submit(self, kernel: Callable, args: Sequence, nbytes: int,
               should_abort: Optional[Callable[[], bool]] = None,
               on_wait: Optional[Callable[[], bool]] = None
               ) -> DeviceFuture:
        """Dispatch `kernel(*args)` asynchronously under the byte budget.

        Returns a DeviceFuture immediately (the device computes in the
        background).  A kernel that raises AT DISPATCH produces an errored
        future rather than raising here, so a multi-chunk dispatch loop keeps
        its bookkeeping simple and errors surface at the (ordered)
        materialisation point."""
        tenant = getattr(_tls, "tenant", None)
        if tenant is not None and on_wait is not None:
            # per-tenant budget share (loongtenant): a tenant already past
            # budget/n_tenants drains ITS OWN oldest in-flight chunk before
            # dispatching more.  Other tenants never enter this loop — one
            # hot pipeline's backlog costs only that pipeline latency
            while tenant_over_share(tenant, nbytes, self.budget_bytes):
                if not on_wait():
                    break
        inflight_now = self._acquire(nbytes, should_abort, on_wait)
        if tenant is not None:
            _tenant_note(tenant, nbytes)
        dispatch_counter().add(1)
        if self.budget_bytes:
            held_fraction_histogram().observe(
                inflight_now / self.budget_bytes)
        tracer = trace.active_tracer()
        span = (tracer.child_or_sampled("device", "device.roundtrip",
                                        {"nbytes": nbytes})
                if tracer is not None else None)
        # loongxprof: mint the dispatch id AFTER budget admission, so the
        # submit leg measures the dispatch call, not the back-pressure
        # wait (which the tracer's host span already covers).  0 when off.
        xid = xprof.begin_dispatch(nbytes)
        if xid and span is not None:
            # the host/device correlation key the timeline export lines
            # spans up by (volatile attr: excluded from structure)
            span.set_attr("dispatch_id", xid)
        try:
            # after _acquire, inside the try: an injected fault behaves
            # exactly like a kernel raising at dispatch — errored future,
            # budget released at the consume point (result/release)
            chaos.faultpoint(FP_SUBMIT)
            prof.push_marker("device", "dispatch")
            if xid:
                # current-dispatch TLS: code running INSIDE the kernel
                # call (ShardedKernel._dispatch) attaches its H2D legs to
                # this dispatch
                xprof.set_current_dispatch(xid)
                t_submit = time.perf_counter()
            try:
                outputs = kernel(*args)
            finally:
                if xid:
                    xprof.leg(xid, "submit", t_submit,
                              time.perf_counter() - t_submit)
                    xprof.set_current_dispatch(0)
                prof.pop_marker()
            if not isinstance(outputs, (tuple, list)):
                outputs = (outputs,)
            return DeviceFuture(self, nbytes, outputs=outputs, span=span,
                                tenant=tenant, xid=xid)
        except DispatchAborted:
            if span is not None:
                span.end("aborted")
            self._release(nbytes)
            if tenant is not None:
                _tenant_note(tenant, -nbytes)
            xprof.close_dispatch(xid)
            raise
        except BaseException as e:  # noqa: BLE001 — deliver via result()
            return DeviceFuture(self, nbytes, error=e, span=span,
                                tenant=tenant, xid=xid)


class DispatchAborted(RuntimeError):
    """Raised by submit() when the caller's should_abort() fired while
    waiting for budget (pipeline stopping)."""


# ---------------------------------------------------------------------------
# Latency-injection kernel: the CPU-testable stand-in for a remote device.


class LatencyInjectedArray:
    """Numpy-convertible handle that blocks until a deadline — models an
    async device buffer whose computation completes `rtt` after dispatch."""

    __slots__ = ("_value", "_deadline")

    def __init__(self, value: np.ndarray, deadline: float):
        self._value = value
        self._deadline = deadline

    def block_until_ready(self) -> "LatencyInjectedArray":
        now = time.perf_counter()
        if now < self._deadline:
            time.sleep(self._deadline - now)
        return self

    def __array__(self, dtype=None, copy=None):
        self.block_until_ready()
        if dtype is not None:
            return self._value.astype(dtype)
        return self._value


class LatencyInjectedKernel:
    """Wraps a synchronous kernel so that dispatch returns instantly and
    materialisation blocks for `rtt_s` — an honest model of a (possibly
    tunneled) accelerator.  `serialize=True` (concurrency 1) models a
    device that executes one dispatch at a time: each call's execution
    starts after the previous call's, exactly like a device execution
    stream.

    ``wire_s`` splits a tunneled round trip into its pipelinable part:
    each dispatch pays one-way wire latency BEFORE execution can start
    (H2D) and the host pays it again before results are visible (D2H), so
    a synchronous round trip costs ``2*wire_s + rtt_s`` while a pipelined
    dispatcher overlaps the wire legs of neighbouring batches and is
    bounded only by the serialized execution stream (``rtt_s`` per batch).
    This is what the loongstream depth sweep measures.  wire_s=0 keeps the
    original single-latency behaviour."""

    def __init__(self, inner: Callable, rtt_s: float, serialize: bool = True,
                 wire_s: float = 0.0):
        self.inner = inner
        self.rtt_s = rtt_s
        self.serialize = serialize
        self.wire_s = wire_s
        self._stream_free_at = 0.0
        self._lock = threading.Lock()
        self.calls = 0

    def __call__(self, *args):
        outs = self.inner(*args)
        if not isinstance(outs, (tuple, list)):
            outs = (outs,)
        now = time.perf_counter()
        with self._lock:
            self.calls += 1
            if self.serialize:
                # execution may start once the batch has crossed the wire
                # AND the single execution stream is free
                start = max(now + self.wire_s, self._stream_free_at)
                exec_done = start + self.rtt_s
                self._stream_free_at = exec_done
            else:
                exec_done = now + self.wire_s + self.rtt_s
            deadline = exec_done + self.wire_s   # results cross back
        return tuple(LatencyInjectedArray(np.asarray(o), deadline)
                     for o in outs)


class StallableKernel(LatencyInjectedKernel):
    """Latency kernel whose completions can be held indefinitely — for
    watermark-under-stalled-device tests."""

    def __init__(self, inner: Callable, rtt_s: float = 0.0):
        super().__init__(inner, rtt_s)
        self._stalled = threading.Event()
        self._stalled.set()  # set = running

    def stall(self) -> None:
        self._stalled.clear()

    def unstall(self) -> None:
        self._stalled.set()

    def __call__(self, *args):
        outs = super().__call__(*args)
        ev = self._stalled

        class _Gate(LatencyInjectedArray):
            __slots__ = ()

            def block_until_ready(self):
                ev.wait()
                return super().block_until_ready()

        return tuple(_Gate(o._value, o._deadline) for o in outs)
