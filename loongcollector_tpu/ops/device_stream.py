"""loongstream: the streaming device pipeline (batch rings + auto-tuner).

`BENCH_TPU_LAST_GOOD.json` shows the kernel parsing at 128 GB/s while the
pipeline moves 2 MB/s end-to-end: the device sits idle on batch assembly,
H2D/D2H transfer and synchronous round-trips (exactly what loongprof's
``device_idle_while_backlogged_ms`` measures).  This module closes that gap
on the host side of the dispatch:

* **BatchRing / BatchSlot** — a persistent ring of pre-allocated
  fixed-geometry batch buffers per ``(B, L)`` geometry.  Packing reuses the
  slot's arrays instead of allocating per dispatch (no allocator churn, no
  fresh page faults on the H2D path), and every pack records padding waste
  (padded-vs-real rows and bytes) per geometry, observable in
  /debug/status, the Prometheus exposition and ``bench.py``
  ``extra.utilization``.  Slots are leased and MUST be released exactly
  once — the loonglint acquire-release checker enforces the pairing the
  same way it does for device-budget futures.

* **DeviceStream** — the pipelined dispatch window (ParPaRaw's feeding
  discipline): up to ``depth`` batches stay in flight; submitting into a
  full window first materialises the OLDEST batch (the ring advance), so
  the host packs/H2Ds batch N+1 while the device computes N and batch
  N-depth+1 returns spans.  Results complete strictly in submit order; a
  fault mid-ring errors only that batch's entry, releases its slot and
  budget, and never stalls or reorders the ring.

* **WidthAutoTuner** — replaces the static ``MIN_BATCH``/``pad_batch``
  policy with runtime-chosen B floors per length bucket (driven by the
  measured padding fraction) and a flush deadline for the worker lane
  rings (driven by the device-utilization accounting: when
  ``device_idle_while_backlogged_ms`` grows, batches ride the ring longer
  to buy overlap; when the device keeps up, the deadline shrinks back for
  latency).

Chaos fault points ``device_plane.h2d`` (pack/transfer stage — wrap the
kernel with :func:`h2d_gated`) and ``device_plane.ring_advance``
(materialise stage) make the async stages stormable.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import chaos
from . import xprof
from .device_batch import MIN_BATCH, pack_rows

FP_RING_ADVANCE = chaos.register_point("device_plane.ring_advance")
FP_H2D = chaos.register_point("device_plane.h2d")

ENV_DEPTH = "LOONG_STREAM_DEPTH"
ENV_TUNER = "LOONG_STREAM_TUNER"

DEFAULT_DEPTH = 3
MAX_DEPTH = 8

#: the tuner never shrinks a geometry floor below this (a 32-row dispatch
#: still amortises its fixed cost ~32x over a single-row call)
MIN_TUNED_FLOOR = 32


def stream_depth(env=os.environ) -> int:
    """Pipeline depth: how many batches one dispatch loop keeps in flight
    (pack N+1 / compute N / span-return N-1 needs 3).  ``LOONG_STREAM_DEPTH``
    overrides; clamped to [1, 8] — 1 degenerates to the synchronous
    submit→materialise round-trip (the bench sweep's baseline)."""
    raw = env.get(ENV_DEPTH)
    if raw:
        try:
            return max(1, min(int(raw), MAX_DEPTH))
        except ValueError:
            pass
    return DEFAULT_DEPTH


def tuner_enabled(env=os.environ) -> bool:
    return env.get(ENV_TUNER) != "0"


def h2d_gated(kernel):
    """Wrap a kernel so the dispatch-side pack/H2D stage is a chaos fault
    point: an injected ERROR raises inside the DevicePlane.submit try —
    exactly a kernel failing at dispatch — so only THAT batch's future
    errors (budget released at its consume point) and the ring keeps
    moving.  A DELAY models a slow transfer.  Disabled plane: one global
    read per dispatch."""
    def _gated(*args):
        chaos.faultpoint(FP_H2D)
        return kernel(*args)
    return _gated


# ---------------------------------------------------------------------------
# padding-waste accounting


_pad_hist = None


def padding_fraction_histogram():
    """Per-pack fraction of the device tensor that is padding (rows beyond
    n_real plus the zero tail of every real row): a distribution living
    near 1.0 means the geometry floor, not the data, sizes the dispatch —
    the signal the width auto-tuner acts on."""
    global _pad_hist
    if _pad_hist is None:
        from ..monitor.metrics import shared_histogram
        _pad_hist = shared_histogram("device_batch_padding_fraction",
                                     labels={"component": "device_stream"})
    return _pad_hist


_geom_records: Dict[Tuple[int, int], object] = {}
_geom_records_lock = threading.Lock()


def _geometry_record(B: int, L: int):
    rec = _geom_records.get((B, L))
    if rec is None:
        with _geom_records_lock:
            rec = _geom_records.get((B, L))
            if rec is None:
                from ..monitor.metrics import MetricsRecord
                rec = MetricsRecord(
                    category="device_plane",
                    labels={"component": "batch_ring",
                            "geometry": f"{B}x{L}"})
                _geom_records[(B, L)] = rec
    return rec


class _GeometryStats:
    __slots__ = ("packs", "real_rows", "padded_rows", "real_bytes",
                 "padded_bytes", "slot_allocs", "slot_reuses")

    def __init__(self) -> None:
        self.packs = 0
        self.real_rows = 0
        self.padded_rows = 0
        self.real_bytes = 0
        self.padded_bytes = 0
        self.slot_allocs = 0
        self.slot_reuses = 0

    def as_dict(self) -> dict:
        total = self.real_bytes + self.padded_bytes
        return {
            "packs": self.packs,
            "real_rows": self.real_rows,
            "padded_rows": self.padded_rows,
            "real_bytes": self.real_bytes,
            "padded_bytes": self.padded_bytes,
            "padding_fraction": (self.padded_bytes / total) if total else 0.0,
            "slot_allocs": self.slot_allocs,
            "slot_reuses": self.slot_reuses,
        }


# ---------------------------------------------------------------------------
# batch ring


class BatchSlot:
    """One pre-allocated fixed-geometry batch buffer, leased from the ring.

    ``pack()`` fills the slot's arrays from the arena (zero-copy reuse of
    the same host pages every generation) and returns the DeviceBatch view;
    ``release()`` returns the slot to its pool — exactly once, after the
    dispatch that used it has materialised (the kernel may alias the
    buffers until then)."""

    __slots__ = ("_ring", "B", "L", "rows", "lengths", "origins", "_leased",
                 "pack_t0", "pack_dur")

    def __init__(self, ring: "BatchRing", B: int, L: int):
        self._ring = ring
        self.B = B
        self.L = L
        self.rows = np.zeros((B, L), dtype=np.uint8)
        self.lengths = np.zeros(B, dtype=np.int32)
        self.origins = np.zeros(B, dtype=np.int32)
        self._leased = False
        # loongxprof: last pack()'s stopwatch (perf_counter start, dur s)
        # — the dispatch loop attaches it as the h2d leg.  None while the
        # timeline is off (the pack pays no perf_counter calls then)
        self.pack_t0: Optional[float] = None
        self.pack_dur: Optional[float] = None

    def pack(self, arena: np.ndarray, offsets: np.ndarray,
             lengths: np.ndarray, lane: Optional[int] = None):
        """Pack rows into this slot's buffers; records padding waste and
        feeds the auto-tuner (per chip lane when the dispatching worker is
        lane-bound — loongmesh keys the tuner's floors per chip so one
        sparse chip cannot shrink every lane's geometry)."""
        if xprof.is_active():
            self.pack_t0 = time.perf_counter()
            batch = pack_rows(arena, offsets, lengths, self.L, self.B,
                              out=(self.rows, self.lengths, self.origins))
            self.pack_dur = time.perf_counter() - self.pack_t0
        else:
            self.pack_t0 = self.pack_dur = None
            batch = pack_rows(arena, offsets, lengths, self.L, self.B,
                              out=(self.rows, self.lengths, self.origins))
        self._ring.record_pack(self.B, self.L, batch.n_real,
                               int(np.asarray(lengths, np.int64).sum()),
                               lane=lane)
        return batch

    def nbytes(self) -> int:
        """Host bytes this slot stages for H2D (rows + lengths + origins)
        — the unit the ``ring_slots`` device-memory family accounts in."""
        return self.rows.nbytes + self.lengths.nbytes + self.origins.nbytes

    def release(self) -> None:
        if not self._leased:
            return
        self._leased = False
        self._ring._return(self)

    def __del__(self):
        # ledger backstop: a leased slot dropped without release() belongs
        # to an abandoned dispatch (the DeviceFuture finaliser already
        # warns about that path) — keep the lease count truthful so the
        # storm conservation assertions measure real leaks, not GC noise
        try:
            if self._leased:
                self._leased = False
                self._ring._forget(self)
        except Exception:  # noqa: BLE001 — never raise from a finaliser
            pass


class BatchRing:
    """Geometry-keyed pools of reusable BatchSlots plus the padding-waste
    ledger.  ``lease()`` never blocks: past the per-geometry pool cap it
    hands out a transient slot (dropped on release) — back-pressure is the
    DevicePlane byte budget's job, the ring only recycles memory."""

    def __init__(self, slots_per_geometry: Optional[int] = None):
        self._lock = threading.Lock()
        self._pools: Dict[Tuple[int, int], List[BatchSlot]] = {}
        self._stats: Dict[Tuple[int, int], _GeometryStats] = {}
        self._leased = 0
        self._slots_per_geometry = slots_per_geometry

    def _cap(self) -> int:
        if self._slots_per_geometry is not None:
            return self._slots_per_geometry
        return stream_depth() + 2

    def lease(self, B: int, L: int) -> BatchSlot:
        with self._lock:
            pool = self._pools.get((B, L))
            slot = pool.pop() if pool else None
            self._leased += 1
            st = self._stats.setdefault((B, L), _GeometryStats())
            if slot is None:
                st.slot_allocs += 1
            else:
                st.slot_reuses += 1
        if slot is None:
            slot = BatchSlot(self, B, L)
        slot._leased = True
        # loongxprof device-memory ledger: a leased slot's bytes are live
        # staging until the dispatch that used it materialises — the
        # conservation residual at quiesce checks live==0 once every
        # lease returned (pooled slots are idle host buffers, not leases)
        from .device_plane import mem_note_alloc
        mem_note_alloc("ring_slots", slot.nbytes())
        return slot

    def _return(self, slot: BatchSlot) -> None:
        with self._lock:
            self._leased = max(0, self._leased - 1)
            pool = self._pools.setdefault((slot.B, slot.L), [])
            if len(pool) < self._cap():
                pool.append(slot)
        from .device_plane import mem_note_free
        mem_note_free("ring_slots", slot.nbytes())

    def _forget(self, slot: BatchSlot) -> None:
        """A leased slot died un-released (finaliser backstop)."""
        with self._lock:
            self._leased = max(0, self._leased - 1)
        from .device_plane import mem_note_free
        mem_note_free("ring_slots", slot.nbytes())

    def record_pack(self, B: int, L: int, n_real: int,
                    real_bytes: int, lane: Optional[int] = None) -> None:
        total_bytes = B * L
        padded_bytes = max(0, total_bytes - real_bytes)
        with self._lock:
            st = self._stats.setdefault((B, L), _GeometryStats())
            st.packs += 1
            st.real_rows += n_real
            st.padded_rows += B - n_real
            st.real_bytes += real_bytes
            st.padded_bytes += padded_bytes
        frac = padded_bytes / total_bytes if total_bytes else 0.0
        padding_fraction_histogram().observe(frac)
        rec = _geometry_record(B, L)
        rec.counter("batch_rows_real_total").add(n_real)
        rec.counter("batch_rows_padded_total").add(B - n_real)
        rec.counter("batch_bytes_real_total").add(real_bytes)
        rec.counter("batch_bytes_padded_total").add(padded_bytes)
        auto_tuner().observe_pack(L, B, n_real, lane=lane)

    # -- observability ------------------------------------------------------

    def leased_total(self) -> int:
        with self._lock:
            return self._leased

    def pooled_total(self) -> int:
        with self._lock:
            return sum(len(p) for p in self._pools.values())

    def stats(self) -> Dict[str, dict]:
        """Per-geometry padding/reuse ledger, keyed "BxL"."""
        with self._lock:
            return {f"{B}x{L}": st.as_dict()
                    for (B, L), st in sorted(self._stats.items())}

    def totals(self) -> dict:
        with self._lock:
            real_b = sum(s.real_bytes for s in self._stats.values())
            pad_b = sum(s.padded_bytes for s in self._stats.values())
            return {
                "leased": self._leased,
                "pooled": sum(len(p) for p in self._pools.values()),
                "packs": sum(s.packs for s in self._stats.values()),
                "real_rows": sum(s.real_rows for s in self._stats.values()),
                "padded_rows": sum(s.padded_rows
                                   for s in self._stats.values()),
                "real_bytes": real_b,
                "padded_bytes": pad_b,
                "padding_fraction": (pad_b / (real_b + pad_b)
                                     if real_b + pad_b else 0.0),
            }


_ring: Optional[BatchRing] = None
_ring_lock = threading.Lock()


def batch_ring() -> BatchRing:
    global _ring
    if _ring is None:
        with _ring_lock:
            if _ring is None:
                _ring = BatchRing()
    return _ring


# ---------------------------------------------------------------------------
# width auto-tuner


class _BucketState:
    __slots__ = ("floor", "ewma_pad", "packs_since", "packs_total")

    def __init__(self) -> None:
        self.floor = MIN_BATCH
        self.ewma_pad = 0.0
        self.packs_since = 0
        self.packs_total = 0


class WidthAutoTuner:
    """Runtime batch-geometry and flush-deadline policy.

    * **B floors**: per length bucket L, the padded batch size floor starts
      at the static ``MIN_BATCH`` and walks down by powers of two (never
      below ``MIN_TUNED_FLOOR``) while the observed ROW padding fraction
      ``(B - n_real) / B`` stays high — sparse traffic stops paying for
      256-row tensors that carry 8 real rows.  It walks back up when
      batches run row-dense.  Row occupancy, not byte occupancy, drives
      the decision: the zero tail inside a real row is the L bucket's
      geometry cost (a dense batch of 50-byte lines in the 128 bucket
      must NOT shrink B); the byte view stays observable through the
      ``device_batch_padding_fraction`` histogram.  Movement is
      hysteretic (one step per ``ADJUST_EVERY`` packs) so the jit geometry
      cache sees at most a handful of shapes per bucket.
    * **flush deadline**: how long a worker lane lets a pending batch ride
      the ring before force-completing it.  When the device-utilization
      accounting reports ``device_idle_while_backlogged_ms`` growing (the
      host cannot feed the device), the deadline stretches — deeper
      effective overlap; when the device keeps up it decays back toward
      the default so latency stays interactive.
    """

    ADJUST_EVERY = 32        # packs per floor step (hysteresis)
    HIGH_PAD = 0.5           # shrink the floor above this EWMA
    LOW_PAD = 0.05           # re-grow the floor below this EWMA
    EWMA_ALPHA = 0.125

    DEADLINE_DEFAULT_S = 0.020
    DEADLINE_MAX_S = 0.100

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # keyed (lane, L): lane None is the process-global stream; chip
        # lanes (loongmesh) get their own floors so one sparse chip's
        # traffic cannot shrink the geometry every other chip dispatches;
        # fused pipeline programs (loongresident) key their floors per
        # program as "fused:<sig>" pseudo-lanes — a sparse fused pipeline
        # must not shrink the staged plane's geometry (or vice versa)
        self._buckets: Dict[Tuple[Optional[int], int], _BucketState] = {}
        self._flush_deadline_s = self.DEADLINE_DEFAULT_S
        self._last_adjust = 0.0
        # None = unarmed: the first look at the plane only records the
        # baseline — a tuner created next to a long-lived plane must not
        # charge the plane's lifetime idle history to its first period
        # (the same retroactive-charging shape note_backlogged guards)
        self._last_idle_ms: Optional[float] = None
        self._deadline_adjusts = 0

    # -- B floor ------------------------------------------------------------

    def min_batch_for(self, L: int, lane: Optional[int] = None) -> int:
        if not tuner_enabled():
            return MIN_BATCH
        with self._lock:
            st = self._buckets.get((lane, L))
            return st.floor if st is not None else MIN_BATCH

    def observe_pack(self, L: int, B: int, n_real: int,
                     lane: Optional[int] = None) -> None:
        # row occupancy, deliberately NOT bytes: see the class docstring
        frac = (B - n_real) / B if B else 0.0
        with self._lock:
            st = self._buckets.setdefault((lane, L), _BucketState())
            st.packs_total += 1
            st.packs_since += 1
            st.ewma_pad += self.EWMA_ALPHA * (frac - st.ewma_pad)
            if not tuner_enabled() or st.packs_since < self.ADJUST_EVERY:
                return
            st.packs_since = 0
            if st.ewma_pad > self.HIGH_PAD and st.floor > MIN_TUNED_FLOOR:
                st.floor //= 2
            elif st.ewma_pad < self.LOW_PAD and st.floor < MIN_BATCH:
                st.floor *= 2

    # -- flush deadline -------------------------------------------------------

    def flush_deadline_s(self) -> float:
        return self._flush_deadline_s

    def maybe_adjust(self) -> None:
        """Periodic (≥1 s apart) deadline adjustment off the device plane's
        utilization accounting.  Observe-only: never constructs a plane."""
        if not tuner_enabled():
            return
        now = time.monotonic()
        with self._lock:
            if now - self._last_adjust < 1.0:
                return
            self._last_adjust = now
        from .device_plane import DevicePlane
        plane = DevicePlane._instance
        if plane is None:
            return
        idle_ms = plane.utilization()["idle_while_backlogged_ms"]
        with self._lock:
            if self._last_idle_ms is None:
                self._last_idle_ms = idle_ms    # arm the window only
                return
            delta = idle_ms - self._last_idle_ms
            self._last_idle_ms = idle_ms
            if delta > 25.0:
                # the device starved while the host had backlog: let
                # batches ride the ring longer (more overlap in flight)
                self._flush_deadline_s = min(
                    self._flush_deadline_s * 2.0, self.DEADLINE_MAX_S)
                self._deadline_adjusts += 1
            elif self._flush_deadline_s > self.DEADLINE_DEFAULT_S:
                # device kept up this period: decay back toward the
                # latency-friendly default
                self._flush_deadline_s = max(
                    self._flush_deadline_s / 2.0, self.DEADLINE_DEFAULT_S)
                self._deadline_adjusts += 1

    # -- observability ------------------------------------------------------

    def chosen(self) -> dict:
        """The tuner's current decisions — /debug/status and bench.py
        record these so every geometry the auto-tuner picked is auditable."""
        def _bucket(st: _BucketState) -> dict:
            return {"floor": st.floor,
                    "ewma_row_padding_fraction": round(st.ewma_pad, 4),
                    "packs": st.packs_total}

        with self._lock:
            lanes: Dict[str, dict] = {}
            glob: Dict[str, dict] = {}
            # lane keys mix int chip indices with "fused:<sig>" program
            # pseudo-lanes (loongresident): chip lanes sort numerically
            # first, pseudo-lanes after them lexicographically
            def _lane_sort(kv):
                lane_k, L_k = kv[0]
                return (lane_k is not None, isinstance(lane_k, str),
                        lane_k if isinstance(lane_k, int) else -1,
                        str(lane_k), L_k)

            for (lane, L), st in sorted(self._buckets.items(),
                                        key=_lane_sort):
                if lane is None:
                    glob[str(L)] = _bucket(st)
                else:
                    lanes.setdefault(str(lane), {})[str(L)] = _bucket(st)
            out = {
                "enabled": tuner_enabled(),
                "flush_deadline_ms": round(self._flush_deadline_s * 1e3, 3),
                "deadline_adjusts": self._deadline_adjusts,
                "buckets": glob,
            }
            if lanes:
                out["lane_buckets"] = lanes
            return out


_tuner: Optional[WidthAutoTuner] = None
_tuner_lock = threading.Lock()


def auto_tuner() -> WidthAutoTuner:
    global _tuner
    if _tuner is None:
        with _tuner_lock:
            if _tuner is None:
                _tuner = WidthAutoTuner()
    return _tuner


def reset_for_testing() -> None:
    """Fresh ring + tuner (tests must not inherit another test's floors,
    deadlines or padding ledger)."""
    global _ring, _tuner
    with _ring_lock:
        _ring = BatchRing()
    with _tuner_lock:
        _tuner = WidthAutoTuner()


# ---------------------------------------------------------------------------
# the pipelined dispatch window


class DeviceStream:
    """Ordered pipelined dispatch over a DevicePlane.

    ``submit`` never lets more than ``depth`` batches stay in flight: a
    full window first advances the ring (materialises the OLDEST batch),
    so with depth 3 the host is packing batch N+1 while the device
    computes N and N-1's spans return.  ``drain()`` materialises the rest.
    Results arrive strictly in submit order as ``(tag, outputs)`` — an
    errored batch (kernel failure or injected ``device_plane.h2d`` /
    ``device_plane.ring_advance`` fault) delivers ``(tag, exception)`` in
    its slot's position: the fault costs one batch, never the ring.

    NOTE: the regex engine's PendingParse implements the same window
    discipline inline (ops/regex/engine.py) because its per-chunk error
    handling is engine-specific (Pallas→XLA pinning, CPU re-run of a
    faulted chunk).  A change to the ring invariants here — advance
    order, slot/budget release, fault isolation — almost certainly needs
    a mirror there.
    """

    def __init__(self, plane=None, depth: Optional[int] = None):
        if plane is None:
            from .device_plane import DevicePlane
            plane = DevicePlane.instance()
        self.plane = plane
        self.depth = max(1, depth if depth is not None else stream_depth())
        self._window: deque = deque()
        self._results: List[Tuple[object, object]] = []
        self.advances = 0

    def inflight(self) -> int:
        return len(self._window)

    def submit(self, kernel, args, nbytes: int, tag=None,
               slot: Optional[BatchSlot] = None) -> None:
        """Dispatch under the plane budget, advancing first if the window
        is full.  When ``slot`` is given the stream owns its release (at
        materialisation, success or error — including a failure in the
        pre-submit advance, which would otherwise strand the new slot)."""
        try:
            while len(self._window) >= self.depth:
                self.advance()
            fut = self.plane.submit(h2d_gated(kernel), args, nbytes,
                                    on_wait=self._advance_if_any)
        except BaseException:
            if slot is not None:
                slot.release()
            raise
        self._window.append((tag, slot, fut))
        if slot is not None:
            xprof.note_dispatch(fut, "stream", f"{slot.B}x{slot.L}",
                                slot.pack_t0, slot.pack_dur)
        else:
            xprof.note_dispatch(fut, "stream", "-")

    def _advance_if_any(self) -> bool:
        if not self._window:
            return False
        self.advance()
        return True

    def advance(self):
        """Materialise the oldest in-flight batch (the ring advance) and
        append its result.  Errors are captured per batch — the window
        keeps its order and the slot/budget always return."""
        if not self._window:
            return None
        tag, slot, fut = self._window.popleft()
        self.advances += 1
        try:
            try:
                chaos.faultpoint(FP_RING_ADVANCE)
                out = fut.result()
            except Exception as e:  # noqa: BLE001 — delivered in-order
                fut.release()
                out = e
            except BaseException:
                # KeyboardInterrupt/SystemExit must reach the caller, not
                # become a ring entry — release and propagate
                fut.release()
                raise
        finally:
            if slot is not None:
                slot.release()
        self._results.append((tag, out))
        return out

    def drain(self) -> List[Tuple[object, object]]:
        """Advance until the window empties; returns (and clears) all
        results in submit order."""
        while self._window:
            self.advance()
        out, self._results = self._results, []
        return out
