"""Grok pattern expansion.

Reference behaviour: the Go grok processor compiles a pattern library and
expands %{NAME:field} references into one regex (SURVEY.md §2.5; reference
semantics at plugins/processor/grok/processor_grok.go — library + expansion,
then regex match).  Expansion output feeds the tiered RegexEngine, so common
grok expressions run on the Tier-1 device kernel.

The default library below is the standard public grok vocabulary
(logstash-style names), written kernel-friendly: field-shaped patterns use
negated-class forms (`[^ ]`-style) rather than lazy dots wherever the
standard semantics allow, because those compile to backtracking-free segment
programs (ops/regex/program.py).
"""

from __future__ import annotations

import re
from typing import Dict, Optional

def _variants(*parts) -> list:
    """Cartesian concatenation of alternative lists — enumerates the exact
    language of a case-class/optional-suffix pattern as plain literals."""
    out = [""]
    for alts in parts:
        out = [a + b for a in out for b in alts]
    return out


def _loglevel_literals() -> str:
    """LOGLEVEL as an all-literal longest-first alternation.

    Same language as the classic `[Ww]arn?(?:ing)?`-style pattern (quirky
    forms like 'waring' included), but literal branches compile to the
    Tier-1 kernel: prefix pairs (WARN/WARNING) are sound under commit when
    ordered longest-first with a follow-set guard (program.py), which the
    class/optional formulation can never prove.
    """
    words = (
        _variants(["A", "a"], ["lert"]) + ["ALERT"]
        + _variants(["T", "t"], ["race"]) + ["TRACE"]
        + _variants(["D", "d"], ["ebug"]) + ["DEBUG"]
        + _variants(["N", "n"], ["otice"]) + ["NOTICE"]
        + _variants(["I", "i"], ["nf"], ["", "o"], ["", "rmation"])
        + _variants(["INF"], ["", "O"], ["", "RMATION"])
        + _variants(["W", "w"], ["ar"], ["", "n"], ["", "ing"])
        + _variants(["WAR"], ["", "N"], ["", "ING"])
        + _variants(["E", "e"], ["r"], ["", "r"], ["", "or"])
        + _variants(["ER"], ["", "R"], ["", "OR"])
        + _variants(["C", "c"], ["ri"], ["", "t"], ["", "ical"])
        + _variants(["CRI"], ["", "T"], ["", "ICAL"])
        + _variants(["F", "f"], ["atal"]) + ["FATAL"]
        + _variants(["S", "s"], ["evere"]) + ["SEVERE"]
        + _variants(["EMERG"], ["", "ENCY"])
        + _variants(["E", "e"], ["merg"], ["", "ency"])
    )
    uniq = sorted(set(words), key=lambda w: (-len(w), w))
    return "(?:" + "|".join(uniq) + ")"


# Standard grok vocabulary (public, logstash-compatible names).
DEFAULT_PATTERNS: Dict[str, str] = {
    "USERNAME": r"[a-zA-Z0-9._-]+",
    "USER": r"%{USERNAME}",
    "INT": r"[+-]?\d+",
    "BASE10NUM": r"[+-]?(?:\d+(?:\.\d+)?|\.\d+)",
    "NUMBER": r"%{BASE10NUM}",
    "BASE16NUM": r"(?:0[xX])?[0-9a-fA-F]+",
    "POSINT": r"\d+",
    "NONNEGINT": r"\d+",
    "WORD": r"\w+",
    "NOTSPACE": r"\S+",
    "SPACE": r"\s*",
    "DATA": r".*?",
    "GREEDYDATA": r".*",
    "QUOTEDSTRING": r"\"[^\"]*\"",
    "UUID": r"[0-9a-fA-F]{8}-[0-9a-fA-F]{4}-[0-9a-fA-F]{4}-[0-9a-fA-F]{4}-[0-9a-fA-F]{12}",
    "IPV4": r"(?:\d{1,3}\.){3}\d{1,3}",
    "IPV6": r"[0-9a-fA-F:.]+",
    "IP": r"%{IPV4}",
    "HOSTNAME": r"[a-zA-Z0-9._-]+",
    "IPORHOST": r"%{HOSTNAME}",
    "HOSTPORT": r"%{IPORHOST}:%{POSINT}",
    "PATH": r"(?:/[^ ]*)+",
    "UNIXPATH": r"(?:/[^ ]*)+",
    "URIPROTO": r"[A-Za-z]+(?:\+[A-Za-z+]+)?",
    "URIHOST": r"%{IPORHOST}(?::%{POSINT})?",
    "URIPATH": r"(?:/[^? ]*)+",
    "URIPARAM": r"\?[^ ]*",
    "URIPATHPARAM": r"%{URIPATH}(?:%{URIPARAM})?",
    "URI": r"%{URIPROTO}://(?:%{USER}(?::[^@]*)?@)?(?:%{URIHOST})?(?:%{URIPATHPARAM})?",
    "MONTH3": r"(?:Jan|Feb|Mar|Apr|May|Jun|Jul|Aug|Sep|Oct|Nov|Dec)",
    "MONTH": r"(?:Jan(?:uary)?|Feb(?:ruary)?|Mar(?:ch)?|Apr(?:il)?|May|Jun(?:e)?|Jul(?:y)?|Aug(?:ust)?|Sep(?:tember)?|Oct(?:ober)?|Nov(?:ember)?|Dec(?:ember)?)",
    "MONTHNUM": r"(?:1[0-2]|0[1-9]|[1-9])",
    "MONTHNUM2": r"(?:1[0-2]|0[1-9])",
    "MONTHDAY": r"(?:(?:0[1-9])|(?:[12][0-9])|(?:3[01])|[1-9])",
    "MONTHDAY2": r"(?:3[01]|[12][0-9]|0[1-9])",
    "DAY": r"(?:Mon(?:day)?|Tue(?:sday)?|Wed(?:nesday)?|Thu(?:rsday)?|Fri(?:day)?|Sat(?:urday)?|Sun(?:day)?)",
    "YEAR": r"(?:\d\d){1,2}",
    "HOUR": r"(?:2[0-3]|[01][0-9]|[0-9])",
    "HOUR2": r"(?:2[0-3]|[01][0-9])",
    "MINUTE": r"(?:[0-5][0-9])",
    "SECOND": r"(?:[0-5][0-9]|60)(?:[:.,][0-9]+)?",
    "TIME": r"%{HOUR2}:%{MINUTE}(?::%{SECOND})?",
    "DATE_US": r"%{MONTHNUM}[/-]%{MONTHDAY}[/-]%{YEAR}",
    "DATE_EU": r"%{MONTHDAY}[./-]%{MONTHNUM}[./-]%{YEAR}",
    "ISO8601_TIMEZONE": r"(?:Z|[+-]%{HOUR2}(?::?%{MINUTE}))",
    "ISO8601_SECOND": r"%{SECOND}",
    "TIMESTAMP_ISO8601": r"%{YEAR}-%{MONTHNUM2}-%{MONTHDAY2}[T ]%{HOUR2}:?%{MINUTE}(?::?%{SECOND})?%{ISO8601_TIMEZONE}?",
    "DATE": r"%{DATE_US}|%{DATE_EU}",
    "DATESTAMP": r"%{DATE}[- ]%{TIME}",
    "TZ": r"[A-Z]{3,4}",
    "HTTPDATE": r"%{MONTHDAY2}/%{MONTH3}/%{YEAR}:%{TIME} %{INT}",
    "SYSLOGTIMESTAMP": r"%{MONTH} +%{MONTHDAY} %{TIME}",
    "LOGLEVEL": _loglevel_literals(),
    # composite access-log patterns, kernel-friendly field classes: the
    # request field uses [^ "] (not \S) so the optional HTTP-version group
    # and closing quote never need backtracking — same semantics for
    # well-formed access logs, Tier-1 on device
    "NOTSPACEQ": r'[^ "]+',
    "COMMONAPACHELOG": (
        r'%{NOTSPACE:clientip} %{NOTSPACE:ident} %{NOTSPACE:auth} '
        r'\[%{HTTPDATE:timestamp}\] "%{WORD:verb} %{NOTSPACEQ:request}'
        r'(?: HTTP/%{NUMBER:httpversion})?" %{INT:response} '
        r'(?:%{POSINT:bytes}|-)'),
    # referrer/agent as [^"]* (not DATA=.*?): identical for well-formed
    # logs, backtracking-free on device
    "COMBINEDAPACHELOG": (
        r'%{COMMONAPACHELOG} "(?P<referrer>[^"]*)" "(?P<agent>[^"]*)"'),
    "NGINXACCESS": (
        r'%{NOTSPACE:remote_addr} - %{NOTSPACE:remote_user} '
        r'\[%{HTTPDATE:time_local}\] "%{WORD:method} %{NOTSPACE:request} '
        r'HTTP/%{NUMBER:http_version}" %{INT:status} %{INT:body_bytes_sent} '
        r'"([^"]*)" "([^"]*)"'),
}

_REF = re.compile(r"%\{(\w+)(?::([\w.\[\]@-]+))?\}")
MAX_DEPTH = 16


class GrokError(Exception):
    pass


def expand(pattern: str,
           custom: Optional[Dict[str, str]] = None,
           _depth: int = 0) -> str:
    """Expand %{NAME} / %{NAME:field} references into a plain regex with
    named capture groups."""
    if _depth > MAX_DEPTH:
        raise GrokError("grok expansion too deep (recursive pattern?)")
    library = DEFAULT_PATTERNS if not custom else {**DEFAULT_PATTERNS, **custom}
    out = []
    pos = 0
    for m in _REF.finditer(pattern):
        out.append(pattern[pos : m.start()])
        name, field = m.group(1), m.group(2)
        body = library.get(name)
        if body is None:
            raise GrokError(f"unknown grok pattern %{{{name}}}")
        body = expand(body, custom, _depth + 1)
        if field:
            safe = re.sub(r"\W", "_", field)
            out.append(f"(?P<{safe}>{body})")
        else:
            out.append(f"(?:{body})")
        pos = m.end()
    out.append(pattern[pos:])
    return "".join(out)
