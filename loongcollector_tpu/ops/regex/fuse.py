"""loongfuse: ahead-of-time multi-pattern DFA fusion (ROADMAP item 3).

Plain regex parses at ~1 GB/s host-native, but grok sits near 250 MB/s and
multiline collapsed on TPU — the per-pattern, per-stage execution model is
the bottleneck, not match speed.  This module compiles a pipeline's WHOLE
grok/regex/multiline pattern set ahead of time into one minimized
multi-accept DFA so a single scan classifies every pattern at once
(PAPERS.md: "Deterministic vs. Non Deterministic Finite Automata in
Automata Processing" for the dense-DFA layout; PaREM for the
parallel-split scan — here the split is the 4-wide interleaved row walk in
``lct_dfa_scan``).

Three layers:

1. **Compiler** (`compile_fused` / `load_or_compile`): per-pattern Thompson
   NFAs share one state space, a common ε-start forms the product, subset
   construction carries per-pattern accept TAGS, and Hopcroft minimization
   runs with the initial partition split by tag set.  Tiered caps: the
   fused automaton may use ``FUSED_MAX_STATES``/``FUSED_MAX_CLASSES``
   (host scan tables are byte-indexed, so only table bytes matter), while
   ``device_ok`` records whether it also fits the MXU kernel's dense
   [K·S, S] budget.  A pattern that blows the budget is DEMOTED — dropped
   from the automaton with a recorded reason and a one-shot alarm — and
   keeps running on its per-pattern path; fusion degrades, never breaks.
   Compiled automata are cached by pattern-set content hash under
   ``<data_dir>/dfa_cache/`` so restarts and hot-reloads skip compilation.

2. **Scanner** (`ByteTableScanner`): the runtime form is a byte-indexed
   transition table ``t256[s, b]`` (class compression applied at build
   time), walked by the native ``lct_dfa_scan`` 4 rows at a time, with a
   lockstep numpy fallback.  One pass returns a uint32 accept-tag bitmask
   per event.

3. **Execution** (`FusedSingleExec` / `FusedSetExec`): the accept tags GATE
   which Tier-1 extract program runs per event.  For a single trial-heavy
   pattern (grok composites), the pattern's residual choice points
   (optionals / alternations left after capture-interior relaxation) are
   enumerated into ≤``MAX_VARIANTS`` LINEAR variants in backtracking
   preference order; capture interiors whose language cannot contain the
   following delimiter byte are relaxed to plain class spans, so each
   variant compiles to the walker's fastest (mask-accelerated) form.  The
   optimistic path runs variant 0 first and validates only the relaxed
   interiors with small regional DFAs; rows that fail fall back to the
   authoritative fused scan, whose lowest set tag bit IS the backtracking
   preference.  For a pattern SET (grok Match lists, multiline
   start/continue/end), one scan replaces N per-pattern match passes.

Correctness contract: fused output is byte-identical to the per-pattern
path — enforced by the differential tests in tests/test_fuse.py, the grok
library goldens, and the scripts/fuse_equivalence.py lint gate.
"""

from __future__ import annotations

import ctypes
import hashlib
import itertools
import json
import os
import re
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

try:  # Python 3.11+
    from re import _constants as sre_c
    from re import _parser as sre_parse
except ImportError:  # pragma: no cover
    import sre_constants as sre_c
    import sre_parse

from ... import native as native_mod
from .charclass import CharClass
from .dfa import (DFAUnsupported, _NFA, build_pattern_nfa, compile_dfa,
                  strip_anchors)
from .native_exec import NativeT1Executor, try_build
from .program import compile_tier1

# ---------------------------------------------------------------------------
# Tiered caps.  Single-pattern Tier-2 stays at dfa.py's 64/32 (the legacy
# DFAMatchKernel budget).  The fused tiers:
#   * host scan tables are byte-indexed (classes folded at build time), so
#     the host cap is about table footprint: 2048 states × 256 × u16 = 1 MB.
#   * the device kernel keeps the dense [K·S, S] MXU mapping, so the fused
#     automaton is device-eligible only under the tighter caps below.
FUSED_MAX_STATES = 2048
FUSED_MAX_CLASSES = 96
DEVICE_MAX_STATES = 128
DEVICE_MAX_CLASSES = 48
MAX_PATTERNS = 32            # accept tags ride a uint32 bitmask
MAX_VARIANTS = 16
REGION_MAX_STATES = 512

CACHE_VERSION = 2            # bump when FusedDFA's serialized layout changes


class FuseUnsupported(Exception):
    pass


# ---------------------------------------------------------------------------
# Fused compile: product NFA -> multi-accept subset construction -> Hopcroft
# ---------------------------------------------------------------------------


@dataclass
class FusedDFA:
    patterns: List[str]           # fused members, priority order (bit i)
    names: List[str]
    num_states: int
    num_classes: int
    byte_class: np.ndarray        # [256] uint8
    transitions: np.ndarray       # [S, K] int32
    start: int
    accept_tags: np.ndarray       # [S] uint32 bitmask of accepting patterns
    demoted: List[Tuple[str, str, str]] = field(default_factory=list)
    stats: Dict[str, object] = field(default_factory=dict)

    @property
    def device_ok(self) -> bool:
        return (self.num_states <= DEVICE_MAX_STATES
                and self.num_classes <= DEVICE_MAX_CLASSES)

    def byte_class_intervals(self) -> List[List[Tuple[int, int]]]:
        out = []
        for k in range(self.num_classes):
            out.append(CharClass(self.byte_class == k).intervals())
        return out

    def match_cpu(self, data: bytes) -> int:
        """Reference interpreter (tests): accept-tag bitmask for `data`."""
        s = self.start
        for b in data:
            s = int(self.transitions[s, self.byte_class[b]])
        return int(self.accept_tags[s])


def _determinize(nfa: _NFA, starts: List[int], accepts: List[int],
                 max_states: int, max_classes: int
                 ) -> Tuple[np.ndarray, np.ndarray, int, np.ndarray]:
    """Multi-accept subset construction over a shared NFA.

    `starts[i]`/`accepts[i]` are pattern i's NFA entry/accept states; the
    DFA state containing accepts[i] carries tag bit i.  Returns
    (byte_class, transitions, start, accept_tags)."""
    n = len(nfa.eps)
    closure: List[frozenset] = []
    for i in range(n):
        seen = {i}
        stack = [i]
        while stack:
            s = stack.pop()
            for t in nfa.eps[s]:
                if t not in seen:
                    seen.add(t)
                    stack.append(t)
        closure.append(frozenset(seen))

    masks: List[np.ndarray] = []
    for s in range(n):
        for mask, _ in nfa.trans[s]:
            masks.append(mask)
    if masks:
        sig = np.stack(masks).astype(np.uint8)
        _, byte_class = np.unique(sig.T, axis=0, return_inverse=True)
        byte_class = byte_class.astype(np.uint8)
    else:
        byte_class = np.zeros(256, dtype=np.uint8)
    num_classes = int(byte_class.max()) + 1
    if num_classes > max_classes:
        raise DFAUnsupported(f"{num_classes} byte classes > {max_classes}")
    class_rep = np.zeros(num_classes, dtype=np.int32)
    for k in range(num_classes):
        class_rep[k] = int(np.argmax(byte_class == k))

    def step(states: frozenset, byte: int) -> frozenset:
        out: set = set()
        for s in states:
            for mask, t in nfa.trans[s]:
                if mask[byte]:
                    out.update(closure[t])
        return frozenset(out)

    start_set = frozenset().union(*(closure[s] for s in starts)) \
        if starts else frozenset()
    dfa_states: Dict[frozenset, int] = {}
    order: List[frozenset] = []

    def intern(fs: frozenset) -> int:
        if fs not in dfa_states:
            if len(order) >= max_states:
                raise DFAUnsupported(f"fused DFA exceeds {max_states} states")
            dfa_states[fs] = len(order)
            order.append(fs)
        return dfa_states[fs]

    dead_id = intern(frozenset())
    start_id = intern(start_set)
    trans_rows: List[List[int]] = [[dead_id] * num_classes]
    i = 1
    while i < len(order):
        fs = order[i]
        trans_rows.append(
            [intern(step(fs, int(class_rep[k]))) for k in range(num_classes)])
        i += 1

    transitions = np.array(trans_rows, dtype=np.int32)
    accept_tags = np.zeros(len(order), dtype=np.uint32)
    for bit, acc in enumerate(accepts):
        for sid, fs in enumerate(order):
            if acc in fs:
                accept_tags[sid] |= np.uint32(1 << bit)
    return byte_class, transitions, start_id, accept_tags


def _hopcroft(transitions: np.ndarray, accept_tags: np.ndarray,
              start: int) -> Tuple[np.ndarray, np.ndarray, int]:
    """Partition-refinement minimization preserving accept TAG SETS (two
    states are distinguishable when their tag bitmasks differ — required
    for multi-accept: merging tag-1 and tag-2 acceptors would conflate
    patterns)."""
    S, K = transitions.shape
    # initial partition: states grouped by tag value
    block_of = np.zeros(S, dtype=np.int64)
    blocks: Dict[int, int] = {}
    for s in range(S):
        t = int(accept_tags[s])
        if t not in blocks:
            blocks[t] = len(blocks)
        block_of[s] = blocks[t]
    n_blocks = len(blocks)

    # inverse transition lists: inv[k][s'] = states s with δ(s,k)=s'
    inv: List[List[List[int]]] = [[[] for _ in range(S)] for _ in range(K)]
    for s in range(S):
        for k in range(K):
            inv[k][int(transitions[s, k])].append(s)

    members: List[set] = [set() for _ in range(n_blocks)]
    for s in range(S):
        members[block_of[s]].add(s)
    worklist = set(range(n_blocks))
    while worklist:
        a = worklist.pop()
        splitter = list(members[a])
        for k in range(K):
            x = set()
            for sprime in splitter:
                x.update(inv[k][sprime])
            if not x:
                continue
            # split every block that x cuts
            touched: Dict[int, set] = {}
            for s in x:
                touched.setdefault(block_of[s], set()).add(s)
            for b, inter in touched.items():
                if len(inter) == len(members[b]):
                    continue
                new_b = len(members)
                members.append(inter)
                members[b] -= inter
                for s in inter:
                    block_of[s] = new_b
                if b in worklist:
                    worklist.add(new_b)
                else:
                    worklist.add(
                        new_b if len(inter) <= len(members[b]) else b)

    # renumber blocks reachability-first so ids are dense and stable
    n_final = len(members)
    new_trans = np.zeros((n_final, K), dtype=np.int32)
    new_tags = np.zeros(n_final, dtype=np.uint32)
    rep = [min(m) if m else 0 for m in members]
    for b in range(n_final):
        r = rep[b]
        new_tags[b] = accept_tags[r]
        for k in range(K):
            new_trans[b, k] = block_of[int(transitions[r, k])]
    return new_trans, new_tags, int(block_of[start])


def compile_fused(patterns: Sequence[str],
                  names: Optional[Sequence[str]] = None,
                  max_states: int = FUSED_MAX_STATES,
                  max_classes: int = FUSED_MAX_CLASSES,
                  alarm_demotions: bool = True,
                  note_demotions: bool = True) -> FusedDFA:
    """AOT-fuse `patterns` (priority order) into one multi-accept DFA.

    Patterns that cannot join (unsupported constructs, or the set blows the
    tiered state/class budget) are demoted with a recorded reason; the
    remaining set still fuses.  Raises FuseUnsupported only when NO pattern
    survives."""
    t0 = time.perf_counter()
    names = list(names) if names is not None else \
        [f"p{i}" for i in range(len(patterns))]
    patterns = [p.decode("latin-1") if isinstance(p, bytes) else p
                for p in patterns]
    demoted: List[Tuple[str, str, str]] = []

    # individually validate + size each pattern (the demotion heuristic
    # needs per-pattern state counts to pick the budget-blowing culprit)
    sizes: Dict[int, int] = {}
    kept: List[int] = []
    for i, p in enumerate(patterns):
        try:
            nfa_i = _NFA()
            _, s_i, a_i = build_pattern_nfa(p, nfa_i)
            bc_i, tr_i, _, _ = _determinize(
                nfa_i, [s_i], [a_i], max_states, max_classes)
            sizes[i] = tr_i.shape[0]
            kept.append(i)
        except DFAUnsupported as e:
            demoted.append((names[i], p, f"unsupported: {e}"))
    while len(kept) > MAX_PATTERNS:
        i = kept.pop()
        demoted.append((names[i], patterns[i],
                        f"pattern set exceeds {MAX_PATTERNS} accept tags"))

    byte_class = transitions = accept_tags = None
    start = 0
    while kept:
        nfa = _NFA()
        starts, accepts = [], []
        try:
            for i in kept:
                _, s_i, a_i = build_pattern_nfa(patterns[i], nfa)
                starts.append(s_i)
                accepts.append(a_i)
            byte_class, transitions, start, accept_tags = _determinize(
                nfa, starts, accepts, max_states, max_classes)
            transitions, accept_tags, start = _hopcroft(
                transitions, accept_tags, start)
            break
        except DFAUnsupported as e:
            # demote the largest individual contributor and retry
            worst = max(kept, key=lambda i: sizes[i])
            kept.remove(worst)
            demoted.append((names[worst], patterns[worst],
                            f"fused budget: {e}"))
    if not kept:
        if note_demotions:
            for nm, p, reason in demoted:
                note_demotion(p, reason, alarm=alarm_demotions)
        raise FuseUnsupported("no pattern in the set is fusable")

    compile_ms = (time.perf_counter() - t0) * 1e3
    fdfa = FusedDFA(
        patterns=[patterns[i] for i in kept],
        names=[names[i] for i in kept],
        num_states=transitions.shape[0],
        num_classes=transitions.shape[1],
        byte_class=byte_class,
        transitions=transitions,
        start=start,
        accept_tags=accept_tags,
        demoted=demoted,
        stats={"compile_ms": round(compile_ms, 2),
               "states": int(transitions.shape[0]),
               "classes": int(transitions.shape[1]),
               "n_patterns": len(kept),
               "n_demoted": len(demoted),
               "cache": "miss"},
    )
    if note_demotions:
        for nm, p, reason in demoted:
            note_demotion(p, reason, alarm=alarm_demotions)
    _note_compile(fdfa)
    return fdfa

# ---------------------------------------------------------------------------
# Runtime scanner: byte-indexed tables + native 4-wide interleaved walk
# ---------------------------------------------------------------------------


def _bind_scan(lib) -> bool:
    if getattr(lib, "_dfa_scan_bound", False):
        return True
    if not hasattr(lib, "lct_dfa_scan"):
        return False
    p = ctypes.c_void_p
    lib.lct_dfa_scan.restype = ctypes.c_int64
    lib.lct_dfa_scan.argtypes = [
        p, ctypes.c_int64, p, p, ctypes.c_int64,
        p, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, p, p]
    lib._dfa_scan_bound = True
    return True


class ByteTableScanner:
    """One fused automaton in runtime form: ``t256[s, b]`` with the class
    compression folded in at build time, so the scan's serial dependency is
    a single L1-resident load per byte.  u8 state ids when S ≤ 256 (the
    whole table stays L1-resident for typical fused sets), u16 above."""

    def __init__(self, byte_class: np.ndarray, transitions: np.ndarray,
                 start: int, accept_tags: np.ndarray):
        S = transitions.shape[0]
        t256 = transitions[:, byte_class]            # [S, 256]
        self.wide = S > 256
        dtype = np.uint16 if self.wide else np.uint8
        self.t256 = np.ascontiguousarray(t256.astype(dtype))
        self.start = int(start)
        self.accept_tags = np.ascontiguousarray(
            accept_tags.astype(np.uint32))
        self.num_states = S

    @classmethod
    def from_fused(cls, fdfa: FusedDFA) -> "ByteTableScanner":
        return cls(fdfa.byte_class, fdfa.transitions, fdfa.start,
                   fdfa.accept_tags)

    @classmethod
    def from_dfa(cls, dfa) -> "ByteTableScanner":
        """Single-pattern Tier-2 DFA (dfa.py) as a host scanner: bit 0 set
        ⇔ match.  Replaces the per-row Python `re` loop that made the
        DFA tier's host path two orders of magnitude slower than this."""
        tags = np.where(dfa.accepting, 1, 0).astype(np.uint32)
        return cls(dfa.byte_class, dfa.transitions, dfa.start, tags)

    def scan(self, arena: np.ndarray, offsets: np.ndarray,
             lengths: np.ndarray) -> np.ndarray:
        """uint32 accept-tag bitmask per row.  Negative lengths (absent
        spans) scan as empty strings."""
        offsets = np.ascontiguousarray(offsets, dtype=np.int64)
        lengths = np.ascontiguousarray(lengths, dtype=np.int32)
        n = len(offsets)
        out = np.zeros(n, dtype=np.uint32)
        if n == 0:
            return out
        arena = np.ascontiguousarray(arena, dtype=np.uint8)
        lib = native_mod.get_lib()
        if lib is not None and _bind_scan(lib):
            rc = lib.lct_dfa_scan(
                arena.ctypes.data, len(arena),
                offsets.ctypes.data, lengths.ctypes.data, n,
                self.t256.ctypes.data, self.num_states,
                1 if self.wide else 0, self.start,
                self.accept_tags.ctypes.data, out.ctypes.data)
            if rc == 0:
                return out
        return self._scan_numpy(arena, offsets, lengths, out)

    def _scan_numpy(self, arena, offsets, lengths, out) -> np.ndarray:
        """Lockstep fallback when the native library is absent: all rows
        advance one byte column per step (the same schedule as the device
        kernel, gather-based)."""
        lens = np.maximum(lengths, 0)
        # native contract: a span outside the arena scans to tag 0 — never
        # a partial-prefix state (the two fallbacks must agree)
        oob = (offsets < 0) | (offsets + lens > len(arena))
        lens = np.where(oob, 0, lens)
        states = np.full(len(offsets), self.start, dtype=np.int64)
        max_len = int(lens.max()) if len(lens) else 0
        alive = np.nonzero(lens > 0)[0]
        for p in range(max_len):
            alive = alive[lens[alive] > p]
            if not len(alive):
                break
            b = arena[offsets[alive] + p]
            states[alive] = self.t256[states[alive], b]
        out[:] = self.accept_tags[states]
        out[oob] = 0
        return out


# ---------------------------------------------------------------------------
# Compile cache: pattern-set content hash -> persisted automaton
# ---------------------------------------------------------------------------

_cache_dir: Optional[str] = None
# LRU-bounded like engine._engine_cache: pattern-set churn across pipeline
# hot-reloads must not pin every compiled automaton (~up to 1 MB of tables
# each) for the process lifetime
_mem_cache: "OrderedDict[str, FusedDFA]" = OrderedDict()
_mem_cache_lock = threading.Lock()
_MEM_CACHE_MAX = 128


def set_cache_dir(path: Optional[str]) -> None:
    """Application startup hook (mirrors flight.set_dump_dir): fused
    automata persist under ``<data_dir>/dfa_cache/``."""
    global _cache_dir
    _cache_dir = path


def _resolved_cache_dir() -> Optional[str]:
    env = os.environ.get("LOONG_DFA_CACHE")
    if env:
        return env
    return _cache_dir


def _set_key(patterns: Sequence[str], max_states: int,
             max_classes: int) -> str:
    blob = json.dumps([CACHE_VERSION, max_states, max_classes,
                       list(patterns)], ensure_ascii=False)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:20]


def _cache_path(dirname: str, key: str) -> str:
    return os.path.join(dirname, "dfa_cache", f"v{CACHE_VERSION}_{key}.npz")


def _save_cache(path: str, fdfa: FusedDFA) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    meta = json.dumps({
        "version": CACHE_VERSION,
        "patterns": fdfa.patterns,
        "names": fdfa.names,
        "demoted": fdfa.demoted,
        "stats": {k: v for k, v in fdfa.stats.items() if k != "cache"},
    })
    tmp = path + f".tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            np.savez(f,
                     byte_class=fdfa.byte_class,
                     transitions=fdfa.transitions,
                     start=np.int64(fdfa.start),
                     accept_tags=fdfa.accept_tags,
                     meta=np.frombuffer(meta.encode("utf-8"), np.uint8))
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def _load_cache(path: str, patterns: Sequence[str]) -> Optional[FusedDFA]:
    try:
        with np.load(path, allow_pickle=False) as z:
            meta = json.loads(bytes(z["meta"].tobytes()).decode("utf-8"))
            if meta.get("version") != CACHE_VERSION:
                return None
            byte_class = z["byte_class"]
            transitions = z["transitions"]
            start = int(z["start"])
            accept_tags = z["accept_tags"]
    except (OSError, KeyError, ValueError, json.JSONDecodeError):
        return None
    # hash collision / stale-content guard: the SET as given must resolve
    # to exactly the stored fused-member + demotion split
    stored_all = list(meta["patterns"]) + [p for _, p, _ in meta["demoted"]]
    if sorted(stored_all) != sorted(patterns):
        return None
    stats = dict(meta.get("stats", {}))
    stats["cache"] = "hit"
    return FusedDFA(
        patterns=list(meta["patterns"]),
        names=list(meta["names"]),
        num_states=transitions.shape[0],
        num_classes=transitions.shape[1],
        byte_class=byte_class,
        transitions=transitions,
        start=start,
        accept_tags=accept_tags,
        demoted=[tuple(d) for d in meta["demoted"]],
        stats=stats,
    )


def load_or_compile(patterns: Sequence[str],
                    names: Optional[Sequence[str]] = None,
                    max_states: int = FUSED_MAX_STATES,
                    max_classes: int = FUSED_MAX_CLASSES,
                    note_demotions: bool = True) -> FusedDFA:
    """`compile_fused` behind the two-level cache: in-process (pipeline
    reloads reuse the object) and on-disk (restarts skip compilation)."""
    patterns = [p.decode("latin-1") if isinstance(p, bytes) else p
                for p in patterns]
    key = _set_key(patterns, max_states, max_classes)
    with _mem_cache_lock:
        got = _mem_cache.get(key)
        if got is not None:
            _mem_cache.move_to_end(key)          # LRU touch
    if got is not None:
        _count("fuse_cache_hit_total")
        return got
    dirname = _resolved_cache_dir()
    if dirname:
        fdfa = _load_cache(_cache_path(dirname, key), patterns)
        if fdfa is not None:
            _count("fuse_cache_hit_total")
            # replay demotions: the cache carries the demoted split, but the
            # counter/alarm are process-level — without this a restart makes
            # the off-device fallback silent again
            if note_demotions:
                for _nm, p, reason in fdfa.demoted:
                    note_demotion(p, reason)
            _note_compile(fdfa, cached=True)
            _memoize(key, fdfa)
            return fdfa
    _count("fuse_cache_miss_total")
    fdfa = compile_fused(patterns, names=names, max_states=max_states,
                         max_classes=max_classes,
                         note_demotions=note_demotions)
    if dirname:
        _save_cache(_cache_path(dirname, key), fdfa)
    _memoize(key, fdfa)
    return fdfa


def _fdfa_nbytes(fdfa: FusedDFA) -> int:
    """Device-constant footprint of one memoized automaton (the tables a
    dispatch keeps resident): transition matrix + byte classes + accept
    tags — the ``dfa_tables`` device-memory family's unit."""
    total = 0
    for name in ("transitions", "byte_class", "accept_tags"):
        arr = getattr(fdfa, name, None)
        total += getattr(arr, "nbytes", 0) or 0
    return total


def _memoize(key: str, fdfa: FusedDFA) -> None:
    from ..device_plane import mem_note_alloc, mem_note_free
    evicted: List[FusedDFA] = []
    with _mem_cache_lock:
        fresh = key not in _mem_cache
        _mem_cache[key] = fdfa
        _mem_cache.move_to_end(key)
        while len(_mem_cache) > _MEM_CACHE_MAX:
            evicted.append(
                _mem_cache.popitem(last=False)[1])   # evict LRU
    # dfa_tables ledger (loongxprof): tables live while memoized, credit
    # back on eviction — outside the cache lock
    if fresh:
        mem_note_alloc("dfa_tables", _fdfa_nbytes(fdfa))
    for old in evicted:
        mem_note_free("dfa_tables", _fdfa_nbytes(old))


# ---------------------------------------------------------------------------
# Observability: compile stats, demotion counter + one-shot alarm
# ---------------------------------------------------------------------------

_stats_lock = threading.Lock()
_metrics_rec = None
_alarmed: set = set()
_fusion_state: Dict[str, object] = {
    "compiles": 0, "cache_hits": 0, "cache_misses": 0, "demotions": 0,
    "sets": [],                 # last 8 compiled/loaded sets
}


def _metrics():
    global _metrics_rec
    if _metrics_rec is None:
        with _stats_lock:
            if _metrics_rec is None:
                from ...monitor.metrics import MetricsRecord
                _metrics_rec = MetricsRecord(
                    category="component", labels={"component": "loongfuse"})
    return _metrics_rec


def _count(name: str, delta: int = 1) -> None:
    try:
        _metrics().counter(name).add(delta)
    except Exception:  # noqa: BLE001 — stats must never break parsing
        pass
    with _stats_lock:
        if name == "fuse_cache_hit_total":
            _fusion_state["cache_hits"] += delta
        elif name == "fuse_cache_miss_total":
            _fusion_state["cache_misses"] += delta
        elif name == "regex_tier_demotions":
            _fusion_state["demotions"] += delta


def _note_compile(fdfa: FusedDFA, cached: bool = False) -> None:
    try:
        rec = _metrics()
        if not cached:
            rec.counter("fuse_compile_total").add(1)
            rec.counter("fuse_compile_ms_total").add(
                int(fdfa.stats.get("compile_ms", 0)))
        rec.gauge("fused_dfa_states").set(fdfa.num_states)
        rec.gauge("fused_dfa_classes").set(fdfa.num_classes)
    except Exception:  # noqa: BLE001
        pass
    entry = {"names": list(fdfa.names), "states": fdfa.num_states,
             "classes": fdfa.num_classes,
             "device_ok": fdfa.device_ok,
             "demoted": [(nm, reason) for nm, _, reason in fdfa.demoted],
             **{k: v for k, v in fdfa.stats.items()}}
    with _stats_lock:
        if not cached:
            _fusion_state["compiles"] += 1
        sets = _fusion_state["sets"]
        sets.append(entry)
        del sets[:-8]


def note_demotion(pattern: str, reason: str, pipeline: str = "",
                  alarm: bool = True) -> None:
    """A pattern fell off the device tier (fused budget, DFA caps,
    capture-needing Tier-2).  Counted always; alarmed ONCE per pattern —
    the silent-fallback failure mode this exists to kill is a TPU
    throughput collapse (multiline-java's 1.6 MB/s) that nothing reported."""
    _count("regex_tier_demotions")
    if not alarm:
        return
    with _stats_lock:
        if pattern in _alarmed:
            return
        _alarmed.add(pattern)
    try:
        from ...monitor.alarms import AlarmManager, AlarmType
        AlarmManager.instance().send_alarm(
            AlarmType.REGEX_TIER_DEMOTED,
            f"regex demoted off device tier ({reason}): {pattern[:160]}",
            pipeline=pipeline)
    except Exception:  # noqa: BLE001
        pass


def fusion_status() -> Dict[str, object]:
    """The /debug/status `fusion` section and bench.py `extra.fusion`."""
    with _stats_lock:
        return {
            "compiles": _fusion_state["compiles"],
            "cache_hits": _fusion_state["cache_hits"],
            "cache_misses": _fusion_state["cache_misses"],
            "demotions": _fusion_state["demotions"],
            "sets": [dict(s) for s in _fusion_state["sets"]],
        }


def reset_for_testing() -> None:
    """Clear process-level fusion state (mem cache, one-shot alarms,
    status counters).  Metrics records persist — they are process-lifetime
    instruments like shared_histogram's."""
    global _cache_dir
    from ..device_plane import mem_note_free
    with _mem_cache_lock:
        dropped = list(_mem_cache.values())
        _mem_cache.clear()
    for fdfa in dropped:
        mem_note_free("dfa_tables", _fdfa_nbytes(fdfa))
    with _stats_lock:
        _alarmed.clear()
        _fusion_state.update(compiles=0, cache_hits=0, cache_misses=0,
                             demotions=0, sets=[])
    _cache_dir = None

# ---------------------------------------------------------------------------
# Single-pattern variant linearization
#
# A grok composite compiles to a Tier-1 program full of Optional_/Alt trial
# ops — the walker re-tries them per row, which is the measured 4× gap vs a
# linear program.  The fused DFA carries FULL original semantics, so
# extraction can be gated: enumerate the pattern's residual choice points
# into linear variants (preference order = re's backtracking order), relax
# capture interiors that end at a delimiter byte their language excludes,
# and let the accept tag pick the variant per event.
# ---------------------------------------------------------------------------

_END = -1          # follow sentinel: end of pattern (a forced boundary)

MAXREPEAT = sre_c.MAXREPEAT


@dataclass(eq=False)
class _FLit:
    data: bytes


@dataclass(eq=False)
class _FCls:
    mask: np.ndarray              # bool [256]
    lo: int
    hi: Optional[int]             # None = unbounded
    lazy: bool = False


@dataclass(eq=False)
class _FSeq:
    items: list


@dataclass(eq=False)
class _FAlt:
    branches: List["_FSeq"]


@dataclass(eq=False)
class _FOpt:
    body: "_FSeq"
    lazy: bool = False


@dataclass(eq=False)
class _FGrp:
    cap: Optional[int]            # 1-based group number, None = (?:)
    body: "_FSeq"


@dataclass(eq=False)
class _FRng:
    """Composite-body repeat (?:X){lo,hi}.  hi=None is unbounded.  Bounded
    small ranges are EXPANDED into nested optionals before choice
    enumeration (X{1,2} → X(?:X)? — greedy prefers the longer count, same
    as re); anything left un-expanded can only survive inside a relaxed
    region, where the fused DFA owns its exact semantics."""
    body: "_FSeq"
    lo: int
    hi: Optional[int]
    lazy: bool = False


@dataclass(eq=False)
class _FRlx:
    cap: int                      # 1-based group number
    mask: np.ndarray              # interior alphabet (span class)
    region: "_FSeq"               # ORIGINAL body (exact grammar)


def _tok_to_ast(tokens) -> _FSeq:
    items: list = []
    for op, av in tokens:
        if op is sre_c.LITERAL:
            items.append(_FLit(bytes([av])))
        elif op is sre_c.NOT_LITERAL:
            items.append(_FCls(CharClass.single(av).negated().mask, 1, 1))
        elif op is sre_c.IN:
            items.append(_FCls(CharClass.from_sre_in(av).mask, 1, 1))
        elif op is sre_c.ANY:
            items.append(_FCls(CharClass.dot().mask, 1, 1))
        elif op is sre_c.CATEGORY:
            items.append(_FCls(CharClass.from_category(av).mask, 1, 1))
        elif op is sre_c.SUBPATTERN:
            g, add_flags, del_flags, sub = av
            if add_flags or del_flags:
                raise FuseUnsupported("inline flags")
            items.append(_FGrp(g, _tok_to_ast(list(sub))))
        elif op is sre_c.BRANCH:
            _, alts = av
            items.append(_FAlt([_tok_to_ast(list(a)) for a in alts]))
        elif op in (sre_c.MAX_REPEAT, sre_c.MIN_REPEAT):
            lo, hi, sub = av
            lazy = op is sre_c.MIN_REPEAT
            body = _tok_to_ast(list(sub))
            if len(body.items) == 1 and isinstance(body.items[0], _FCls) \
                    and body.items[0].lo == 1 and body.items[0].hi == 1:
                items.append(_FCls(body.items[0].mask, lo,
                                   None if hi is MAXREPEAT else int(hi),
                                   lazy))
            elif (lo, hi) == (0, 1):
                items.append(_FOpt(body, lazy))
            else:
                items.append(_FRng(body, lo,
                                   None if hi is MAXREPEAT else int(hi),
                                   lazy))
        else:
            raise FuseUnsupported(f"op {op}")
    return _FSeq(items)


def _alphabet(node) -> np.ndarray:
    m = np.zeros(256, dtype=bool)
    if isinstance(node, _FLit):
        for b in node.data:
            m[b] = True
    elif isinstance(node, _FCls):
        m |= node.mask
    elif isinstance(node, _FSeq):
        for it in node.items:
            m |= _alphabet(it)
    elif isinstance(node, _FAlt):
        for br in node.branches:
            m |= _alphabet(br)
    elif isinstance(node, (_FOpt, _FGrp, _FRng)):
        m |= _alphabet(node.body)
    elif isinstance(node, _FRlx):
        m |= node.mask
    return m


def _has_group(node) -> bool:
    if isinstance(node, _FGrp):
        return True
    if isinstance(node, _FSeq):
        return any(_has_group(i) for i in node.items)
    if isinstance(node, _FAlt):
        return any(_has_group(b) for b in node.branches)
    if isinstance(node, (_FOpt, _FRng)):
        return _has_group(node.body)
    return False


def _has_trials(node) -> bool:
    """Does the subtree contain COMPOSITE trial ops (optionals /
    alternations / composite repeats)?  Only such capture interiors are
    worth relaxing: a pure class-quantifier run (`[+-]?\\d+`) already
    compiles to trial-free Span ops, so relaxing it would spend a regional
    validation for nothing."""
    if isinstance(node, (_FAlt, _FOpt, _FRng)):
        return True
    if isinstance(node, _FSeq):
        return any(_has_trials(i) for i in node.items)
    if isinstance(node, _FGrp):
        return _has_trials(node.body)
    return False


def _min_len(node) -> int:
    """Minimum match length of a subtree (saturating small int)."""
    if isinstance(node, _FLit):
        return len(node.data)
    if isinstance(node, _FCls):
        return node.lo
    if isinstance(node, _FSeq):
        return sum(_min_len(i) for i in node.items)
    if isinstance(node, _FAlt):
        return min((_min_len(b) for b in node.branches), default=0)
    if isinstance(node, _FOpt):
        return 0
    if isinstance(node, _FGrp):
        return _min_len(node.body)
    if isinstance(node, _FRng):
        return node.lo * _min_len(node.body)
    if isinstance(node, _FRlx):
        return 0
    return 0


# Regions shorter than this stay EXACT in the walker: validating a 3-byte
# span with a separate DFA pass costs more than the walker's own trial,
# and pinned variants absorb the residual choice points anyway.
_MIN_RELAX_LEN = 4


def _clone(node):
    """Fresh node objects for repeat expansion — choice points are keyed
    by identity, so each expanded copy must decide independently."""
    if isinstance(node, _FSeq):
        return _FSeq([_clone(i) for i in node.items])
    if isinstance(node, _FLit):
        return _FLit(node.data)
    if isinstance(node, _FCls):
        return _FCls(node.mask, node.lo, node.hi, node.lazy)
    if isinstance(node, _FAlt):
        return _FAlt([_clone(b) for b in node.branches])
    if isinstance(node, _FOpt):
        return _FOpt(_clone(node.body), node.lazy)
    if isinstance(node, _FGrp):
        return _FGrp(node.cap, _clone(node.body))
    if isinstance(node, _FRng):
        return _FRng(_clone(node.body), node.lo, node.hi, node.lazy)
    if isinstance(node, _FRlx):
        return _FRlx(node.cap, node.mask, node.region)
    raise FuseUnsupported(f"clone {type(node).__name__}")


_MAX_RNG_EXPAND = 4


def _expand_rngs(node):
    """Rewrite small bounded composite repeats into mandatory copies plus
    a nested optional chain, in re's preference order: greedy X{1,2} →
    X(?:X)? (longer count first), lazy X{1,2}? → X(?:X)?? (shorter
    first).  Relaxed regions keep their original form — the fused DFA owns
    them."""
    if isinstance(node, _FSeq):
        return _FSeq([_expand_rngs(i) for i in node.items])
    if isinstance(node, _FAlt):
        return _FAlt([_expand_rngs(b) for b in node.branches])
    if isinstance(node, _FOpt):
        return _FOpt(_expand_rngs(node.body), node.lazy)
    if isinstance(node, _FGrp):
        return _FGrp(node.cap, _expand_rngs(node.body))
    if isinstance(node, _FRng):
        body = _expand_rngs(node.body)
        if node.hi is None or node.hi - node.lo > _MAX_RNG_EXPAND \
                or _has_group(body):
            return _FRng(body, node.lo, node.hi, node.lazy)
        items = [_clone(body) for _ in range(node.lo)]
        tail = None
        for _ in range(node.hi - node.lo):
            inner = _FSeq([_clone(body)] + ([tail] if tail else []))
            tail = _FOpt(inner, node.lazy)
        if tail is not None:
            items.append(tail)
        return _FSeq(items)
    return node


def _relax_seq(seq: _FSeq, follow) -> _FSeq:
    """Rewrite capture groups to relaxed class spans where sound.

    A group G directly followed by a literal whose first byte d is OUTSIDE
    G's interior alphabet A (or sitting at the very end of the pattern) has
    a FORCED boundary: in any accepted string G's span is exactly the
    maximal A-run, so `[A]*` reproduces re's spans on validated rows.  The
    exact interior grammar moves to the regional validator / fused DFA."""
    out: list = []
    n = len(seq.items)
    for i, it in enumerate(seq.items):
        if i + 1 < n:
            nxt = seq.items[i + 1]
            item_follow = nxt.data[0] if isinstance(nxt, _FLit) else None
        else:
            item_follow = follow
        if isinstance(it, _FGrp) and it.cap is not None:
            alpha = _alphabet(it.body)
            boundary_ok = (item_follow is _END
                           or (item_follow is not None
                               and not alpha[item_follow]))
            if boundary_ok and _has_trials(it.body) \
                    and not _has_group(it.body) \
                    and _min_len(it.body) >= _MIN_RELAX_LEN:
                out.append(_FRlx(it.cap, alpha, it.body))
                continue
            out.append(_FGrp(it.cap, _relax_seq(it.body, item_follow)))
        elif isinstance(it, _FGrp):
            out.append(_FGrp(None, _relax_seq(it.body, item_follow)))
        elif isinstance(it, _FOpt):
            # when the optional is taken, its tail sees the optional's own
            # follow (the delimiter appears either way)
            out.append(_FOpt(_relax_seq(it.body, item_follow), it.lazy))
        elif isinstance(it, _FAlt):
            out.append(_FAlt([_relax_seq(b, item_follow)
                              for b in it.branches]))
        else:
            out.append(it)
    return _FSeq(out)


def _collect_choices(node, out: list, in_rep: list) -> None:
    """DFS choice points in syntactic order — which for a concatenative
    pattern is exactly re's backtracking decision order, so enumerating
    assignments lexicographically yields variants in preference order."""
    if isinstance(node, _FSeq):
        for it in node.items:
            _collect_choices(it, out, in_rep)
    elif isinstance(node, _FOpt):
        out.append((node, 2))
        _collect_choices(node.body, out, in_rep)
    elif isinstance(node, _FAlt):
        out.append((node, len(node.branches)))
        for b in node.branches:
            _collect_choices(b, out, in_rep)
    elif isinstance(node, _FGrp):
        _collect_choices(node.body, out, in_rep)
    elif isinstance(node, _FRng):
        if node.hi is not None and node.hi != node.lo:
            in_rep.append(node)      # un-expanded bounded range: bail
        probe: list = []
        _collect_choices(node.body, probe, in_rep)
        if probe:
            # per-iteration choices cannot be pinned set-wide
            in_rep.append(node)


def _pin(node, decisions: Dict[int, int]):
    """Resolve choice points per `decisions` (keyed by node id).  Un-taken
    subtrees vanish — their capture groups stay unmatched (span -1), the
    same as re."""
    if isinstance(node, _FSeq):
        out = []
        for it in node.items:
            p = _pin(it, decisions)
            if p is not None:
                out.append(p)
        return _FSeq(out)
    if isinstance(node, _FOpt):
        choice = decisions[id(node)]
        present = (choice == 0) if not node.lazy else (choice == 1)
        return _pin(node.body, decisions) if present else None
    if isinstance(node, _FAlt):
        return _pin(node.branches[decisions[id(node)]], decisions)
    if isinstance(node, _FGrp):
        return _FGrp(node.cap, _pin(node.body, decisions))
    if isinstance(node, _FRng):
        return _FRng(_pin(node.body, decisions), node.lo, node.hi,
                     node.lazy)
    return node


_CLS_ESCAPE = {ord("\\"), ord("]"), ord("^"), ord("-")}


def _class_str(mask: np.ndarray) -> str:
    if mask.all():
        return r"[\x00-\xff]"
    parts = []
    for lo, hi in CharClass(mask).intervals():
        def esc(b):
            if b in _CLS_ESCAPE or b < 0x21 or b > 0x7e:
                return f"\\x{b:02x}"
            return chr(b)
        parts.append(esc(lo) if lo == hi else f"{esc(lo)}-{esc(hi)}")
    return "[" + "".join(parts) + "]"


def _quant(lo: int, hi: Optional[int], lazy: bool) -> str:
    if (lo, hi) == (1, 1):
        return ""
    if hi is None:
        q = "*" if lo == 0 else ("+" if lo == 1 else f"{{{lo},}}")
    elif lo == hi:
        q = f"{{{lo}}}"
    else:
        q = f"{{{lo},{hi}}}"
    return q + ("?" if lazy and q else "")


def _emit(node, caps_out: Optional[list], relaxed_as_class: bool) -> str:
    """Pinned AST -> regex string.  caps_out collects surviving capture
    group numbers in emission order (the walker's cap index mapping);
    None emits everything non-capturing (the fused DFA's exact variants)."""
    if isinstance(node, _FSeq):
        return "".join(_emit(i, caps_out, relaxed_as_class)
                       for i in node.items)
    if isinstance(node, _FLit):
        return re.escape(node.data.decode("latin-1"))
    if isinstance(node, _FCls):
        return _class_str(node.mask) + _quant(node.lo, node.hi, node.lazy)
    if isinstance(node, _FGrp):
        body = _emit(node.body, caps_out, relaxed_as_class)
        if node.cap is not None and caps_out is not None:
            caps_out.append(node.cap)
            return f"({body})"
        return f"(?:{body})"
    if isinstance(node, _FRlx):
        if relaxed_as_class:
            body = _class_str(node.mask) + "*"
        else:
            body = _emit(node.region, None, False)
        if caps_out is not None:
            caps_out.append(node.cap)
            return f"({body})"
        return f"(?:{body})"
    if isinstance(node, _FRng):
        return ("(?:" + _emit(node.body, caps_out, relaxed_as_class)
                + ")" + _quant(node.lo, node.hi, node.lazy))
    if isinstance(node, _FOpt):
        q = "??" if node.lazy else "?"
        return ("(?:" + _emit(node.body, caps_out, relaxed_as_class)
                + ")" + q)
    if isinstance(node, _FAlt):
        return ("(?:" + "|".join(_emit(b, caps_out, relaxed_as_class)
                                 for b in node.branches) + ")")
    raise FuseUnsupported(f"emit {type(node).__name__}")


def _walk_rlx(node, out: list) -> None:
    # every container _relax_seq recurses into must be walked here, or a
    # relaxed interior ships without its regional validator (an un-taken
    # optional/branch region simply has span -1 at parse time)
    if isinstance(node, _FSeq):
        for it in node.items:
            _walk_rlx(it, out)
    elif isinstance(node, _FRlx):
        out.append(node)
    elif isinstance(node, (_FGrp, _FRng, _FOpt)):
        _walk_rlx(node.body, out)
    elif isinstance(node, _FAlt):
        for b in node.branches:
            _walk_rlx(b, out)

# ---------------------------------------------------------------------------
# Execution: fused single-pattern extract + fused pattern-set classify
# ---------------------------------------------------------------------------


@dataclass(eq=False)
class _Variant:
    pattern: str                  # relaxed+pinned walker form
    exact: str                    # pinned exact form (fused DFA member)
    exec: NativeT1Executor
    cap_map: List[int]            # walker cap g -> ORIGINAL cap index (0-based)


class FusedSingleExec:
    """Host-tier fused execution of ONE trial-heavy pattern.

    Optimistic pipeline: variant 0 (re's most-preferred choice assignment)
    runs as a LINEAR native walk over all rows; relaxed capture interiors
    are then validated by small regional DFAs over exactly the captured
    spans (a few % of the bytes).  Rows that fail either step take the
    authoritative fused scan, whose lowest set accept bit is the
    backtracking-preferred variant, and re-extract on that variant's
    linear program.  Output is byte-identical to `re` / the trial walker.
    """

    def __init__(self, pattern: str, variants: List[_Variant],
                 scanner: Optional[ByteTableScanner],
                 regions0: List[Tuple[int, ByteTableScanner]],
                 num_caps: int):
        self.pattern = pattern
        self.variants = variants
        # scanner=None is UNPINNED mode: variant 0 keeps its trial ops and
        # is therefore authoritative for match/no-match on its own (its
        # language is a superset of the original, so walker-fail ⇒
        # original-fail); only region-validation failures need the exact
        # `re` net.  Pinned mode gates failed rows through the fused scan.
        self.scanner = scanner
        self.regions0 = regions0
        self.num_caps = num_caps
        self._re = re.compile(pattern.encode("latin-1"))

    def parse(self, arena: np.ndarray, offsets: np.ndarray,
              lengths: np.ndarray
              ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        offsets = np.asarray(offsets, dtype=np.int64)
        lengths = np.asarray(lengths, dtype=np.int32)
        n = len(offsets)
        C = max(self.num_caps, 1)
        if n == 0:
            return (np.zeros(0, dtype=bool),
                    np.zeros((0, C), dtype=np.int32),
                    np.full((0, C), -1, dtype=np.int32))

        v0 = self.variants[0]
        k_ok, k_off, k_len = v0.exec(arena, offsets, lengths)
        ok = k_ok if k_ok.dtype == np.bool_ else k_ok.astype(bool)
        if v0.cap_map == list(range(C)) and k_off.shape[1] == C:
            # variant 0 carries every original capture in order (the common
            # case): adopt the walker's freshly-allocated output arrays
            # instead of re-scattering ~2·n·C elements per parse
            cap_off, cap_len = k_off, k_len
        else:
            cap_off = np.zeros((n, C), dtype=np.int32)
            cap_len = np.full((n, C), -1, dtype=np.int32)
            for g, oc in enumerate(v0.cap_map):
                cap_off[:, oc] = k_off[:, g]
                cap_len[:, oc] = k_len[:, g]

        # regional validation of relaxed interiors (variant-0 rows only);
        # an absent optional region (span -1) has nothing to validate
        pend = ~ok
        region_fail = np.zeros(0, dtype=np.int64)
        rows = np.nonzero(ok)[0]
        for oc, rscan in self.regions0:
            if not len(rows):
                break
            present = cap_len[rows, oc] >= 0
            check = rows[present]
            tags = rscan.scan(arena, cap_off[check, oc].astype(np.int64),
                              cap_len[check, oc])
            bad_rows = check[(tags & 1) == 0]
            if len(bad_rows):
                pend[bad_rows] = True
                ok[bad_rows] = False
                region_fail = np.concatenate([region_fail, bad_rows])
                keep = np.ones(len(rows), dtype=bool)
                keep[np.searchsorted(rows, bad_rows)] = False
                rows = rows[keep]

        if self.scanner is None:
            # unpinned mode: the walker already decided match/no-match for
            # every row except the region-validation failures
            if len(region_fail):
                cap_off[region_fail] = 0
                cap_len[region_fail] = -1
                self._re_rows(arena, offsets, lengths, region_fail,
                              ok, cap_off, cap_len)
            return ok, cap_off, cap_len

        if pend.any():
            prows = np.nonzero(pend)[0]
            cap_off[prows] = 0
            cap_len[prows] = -1
            ok[prows] = False
            tags = self.scanner.scan(arena, offsets[prows], lengths[prows])
            defensive = prows[(tags & 1) == 1]
            for v in range(1, len(self.variants)):
                bit = np.uint32(1 << v)
                below = np.uint32((1 << v) - 1)
                sel = prows[((tags & bit) != 0) & ((tags & below) == 0)]
                if not len(sel):
                    continue
                var = self.variants[v]
                s_ok, s_off, s_len = var.exec(arena, offsets[sel],
                                              lengths[sel])
                s_ok = np.array(s_ok, dtype=bool)
                hit = sel[s_ok]
                for g, oc in enumerate(var.cap_map):
                    cap_off[hit, oc] = s_off[s_ok, g]
                    cap_len[hit, oc] = s_len[s_ok, g]
                ok[hit] = True
                # a tagged row whose walker disagreed is a bug net, not a
                # hot path: resolve it with re exactly
                defensive = np.concatenate([defensive, sel[~s_ok]])
            if len(defensive):
                self._re_rows(arena, offsets, lengths, defensive,
                              ok, cap_off, cap_len)
        return ok, cap_off, cap_len

    def _re_rows(self, arena, offsets, lengths, rows, ok, cap_off,
                 cap_len) -> None:
        for i in rows:
            o, ln = int(offsets[i]), int(lengths[i])
            m = self._re.fullmatch(bytes(arena[o:o + ln].tobytes()))
            if m is None:
                ok[i] = False
                cap_off[i] = 0
                cap_len[i] = -1
                continue
            ok[i] = True
            for g in range(self.num_caps):
                s, e = m.span(g + 1)
                if s >= 0:
                    cap_off[i, g] = o + s
                    cap_len[i, g] = e - s
                else:
                    cap_off[i, g] = 0
                    cap_len[i, g] = -1


def try_build_single(pattern: str) -> Optional[FusedSingleExec]:
    """Build the fused execution for one pattern, or None when the pattern
    does not profit (already linear) or cannot be handled exactly (the
    engine keeps its existing tiers — degradation, never breakage)."""
    if isinstance(pattern, bytes):
        pattern = pattern.decode("latin-1")
    try:
        re_c = re.compile(pattern.encode("latin-1"))
        tokens = strip_anchors(list(sre_parse.parse(pattern)))
        ast_root = _tok_to_ast(tokens)
    except Exception:  # noqa: BLE001 — unparseable/unsupported shapes
        # keep their existing tiers
        return None
    num_caps = re_c.groups
    relaxed = _expand_rngs(_relax_seq(ast_root, _END))
    choices: list = []
    rep_choices: list = []
    _collect_choices(relaxed, choices, rep_choices)
    n_variants = 1
    for _, k in choices:
        n_variants *= k
    rlx_nodes: list = []
    _walk_rlx(relaxed, rlx_nodes)
    if not rlx_nodes and n_variants == 1:
        return None                      # nothing to gain over the walker

    def _region_scanner(node: _FRlx) -> Tuple[int, ByteTableScanner]:
        rdfa = compile_dfa(_emit(node.region, None, False),
                           max_states=REGION_MAX_STATES,
                           max_classes=FUSED_MAX_CLASSES)
        return node.cap - 1, ByteTableScanner.from_dfa(rdfa)

    try:
        if rep_choices or n_variants > MAX_VARIANTS:
            # UNPINNED fallback: keep the trial ops in one relaxed walker.
            # Its language is a superset of the original, so walker-fail is
            # authoritative no-match; relaxed interiors are forced-boundary
            # spans, so walker-pass + region-pass is an exact match.  Only
            # region failures need the `re` net — no fused scan at all.
            if not rlx_nodes:
                return None
            caps: List[int] = []
            walker_str = _emit(relaxed, caps, True)
            wexec = try_build(compile_tier1(walker_str))
            if wexec is None:
                return None
            variants = [_Variant(walker_str, pattern, wexec,
                                 [c - 1 for c in caps])]
            regions0 = [_region_scanner(nd) for nd in rlx_nodes]
            return FusedSingleExec(pattern, variants, None, regions0,
                                   num_caps)

        variants: List[_Variant] = []
        regions0: List[Tuple[int, ByteTableScanner]] = []
        for assignment in itertools.product(
                *[range(k) for _, k in choices]) if choices else [()]:
            decisions = {id(node): c
                         for (node, _), c in zip(choices, assignment)}
            pinned = _pin(relaxed, decisions)
            caps = []
            walker_str = _emit(pinned, caps, True)
            exact_str = _emit(pinned, None, False)
            prog = compile_tier1(walker_str)
            wexec = try_build(prog)
            if wexec is None:
                return None              # host fused path needs the lib
            cap_map = [c - 1 for c in caps]
            variants.append(_Variant(walker_str, exact_str, wexec, cap_map))
            if len(variants) == 1:       # variant 0: regional validators
                v0_rlx: list = []
                _walk_rlx(pinned, v0_rlx)
                regions0 = [_region_scanner(nd) for nd in v0_rlx]
        # synthetic variant regexes: a budget demotion here just means "no
        # fused single-exec" (the pattern keeps its tier) — it must NOT
        # fire the user-facing demotion counter/alarm naming a regex the
        # user never wrote, neither now nor on a cache-hit replay
        fdfa = load_or_compile([v.exact for v in variants],
                               names=[f"v{i}" for i in
                                      range(len(variants))],
                               note_demotions=False)
        if fdfa.demoted:
            return None                  # variants must ALL be exact
    except Exception:  # noqa: BLE001 — Tier1Unsupported / DFAUnsupported /
        # FuseUnsupported / emit bugs all mean the same thing here: this
        # pattern keeps its existing tiers
        return None
    return FusedSingleExec(pattern, variants,
                           ByteTableScanner.from_fused(fdfa),
                           regions0, num_caps)


class FusedSetExec:
    """One fused automaton over a whole pattern SET (grok Match list,
    multiline start/continue/end): a single scan classifies every pattern
    at once.  Demoted members keep their per-pattern path; `bit_of` maps
    original set positions to accept-tag bits."""

    def __init__(self, patterns: Sequence[str],
                 names: Optional[Sequence[str]] = None):
        patterns = [p.decode("latin-1") if isinstance(p, bytes) else p
                    for p in patterns]
        self.patterns = patterns
        self.fdfa = load_or_compile(patterns, names=names)
        self.scanner = ByteTableScanner.from_fused(self.fdfa)
        self.bit_of: Dict[int, int] = {}
        nb = 0
        for i, p in enumerate(patterns):
            if nb < len(self.fdfa.patterns) and p == self.fdfa.patterns[nb]:
                self.bit_of[i] = nb
                nb += 1
        self._kernel = None
        self._kernel_lock = threading.Lock()

    @property
    def n_fused(self) -> int:
        return len(self.fdfa.patterns)

    def _device_kernel(self):
        with self._kernel_lock:
            if self._kernel is None:
                from ..kernels.dfa_scan import FusedScanKernel
                self._kernel = FusedScanKernel(self.fdfa)
            return self._kernel

    def classify(self, arena: np.ndarray, offsets: np.ndarray,
                 lengths: np.ndarray,
                 force: Optional[str] = None) -> np.ndarray:
        """uint32 accept-tag bitmask per row; bit b = fused member b
        full-matches.  `force` pins the route ("host"/"device") for tests
        and the bench sweep."""
        offsets = np.asarray(offsets, dtype=np.int64)
        lengths = np.asarray(lengths, dtype=np.int32)
        n = len(offsets)
        if n == 0:
            return np.zeros(0, dtype=np.uint32)
        use_device = force == "device"
        if force is None and self.fdfa.device_ok:
            from .engine import (_device_min_bytes, _native_host_mode,
                                 _pallas_enabled)
            if not _native_host_mode() and _pallas_enabled() is None \
                    and os.environ.get("LOONG_NATIVE_T1") != "0" \
                    and int(lengths.sum()) >= _device_min_bytes():
                use_device = True
        if not use_device:
            return self.scanner.scan(arena, offsets, lengths)
        from ..device_batch import (LENGTH_BUCKETS, MAX_BATCH, pack_rows,
                                    pick_length_bucket)
        kern = self._device_kernel()
        tags = np.zeros(n, dtype=np.uint32)
        max_bucket = LENGTH_BUCKETS[-1]
        over = lengths > max_bucket
        device_idx = np.nonzero(~over)[0]
        for i in range(0, len(device_idx), MAX_BATCH):
            chunk = device_idx[i:i + MAX_BATCH]
            d_len = lengths[chunk]
            L = pick_length_bucket(int(d_len.max()) if len(d_len) else 1) \
                or max_bucket
            batch = pack_rows(arena, offsets[chunk], d_len, L)
            # synchronous chunked classify tier — callers that want the
            # resident form use the fused pipeline scan stage instead
            # loonglint: disable=host-bounce
            k_tags = np.asarray(kern(batch.rows, batch.lengths))
            tags[chunk] = k_tags[: len(chunk)].astype(np.uint32)
        over_idx = np.nonzero(over)[0]
        if len(over_idx):
            tags[over_idx] = self.scanner.scan(arena, offsets[over_idx],
                                               lengths[over_idx])
        return tags

    def member_masks(self, tags: np.ndarray
                     ) -> List[Optional[np.ndarray]]:
        """Per ORIGINAL set position: bool match array, or None when the
        member was demoted (caller keeps its per-pattern path)."""
        out: List[Optional[np.ndarray]] = []
        for i in range(len(self.patterns)):
            bit = self.bit_of.get(i)
            if bit is None:
                out.append(None)
            else:
                out.append((tags & np.uint32(1 << bit)) != 0)
        return out


def try_build_set(patterns: Sequence[str],
                  names: Optional[Sequence[str]] = None
                  ) -> Optional[FusedSetExec]:
    """FusedSetExec, or None when nothing in the set can fuse."""
    try:
        return FusedSetExec(patterns, names=names)
    except Exception:  # noqa: BLE001 — any compile failure means "no fusion"
        return None
