from .charclass import CharClass
from .program import (SegmentProgram, Tier1Unsupported, compile_tier1,
                      classify_pattern, PatternTier)
