"""Host-tier Tier-1 execution: serialize SegmentPrograms for the C++ walker.

When no accelerator is reachable (degraded mode) the XLA:CPU emulation of
the masked-reduction kernel is an order of magnitude slower than a direct
scalar walk, so the engine routes parse_batch to `lct_t1_exec`
(native/loongcollector_native.cpp) — the same compiled IR, executed
per-row, mirroring ops/kernels/field_extract.py op-for-op.  The reference's
equivalent hot loop is likewise native C++
(core/plugin/processor/ProcessorParseRegexNative.cpp:186-253).

Differential bit-identity with the device kernel is enforced by
tests/test_native_t1.py over the generative fuzz corpus.
"""

from __future__ import annotations

import ctypes
from typing import Dict, List, Optional, Tuple

import numpy as np

from ... import native as native_mod
from .program import (INF, Alt, CapEnd, CapStart, FixedSpan, Lit, Optional_,
                      SegmentProgram, Span)

MAX_CAPS = 32     # kT1MaxCaps in the C++ executor
MAX_CLASSES = 64  # kT1MaxClasses in the C++ executor


class NativeUnsupported(Exception):
    """Program cannot run on the native tier (too many caps, lib absent)."""


class _LitTable:
    def __init__(self) -> None:
        self._idx: Dict[bytes, int] = {}
        self.blob = bytearray()
        self.offs: List[int] = []
        self.lens: List[int] = []

    def add(self, data: bytes) -> int:
        got = self._idx.get(data)
        if got is not None:
            return got
        idx = len(self.offs)
        self._idx[data] = idx
        self.offs.append(len(self.blob))
        self.lens.append(len(data))
        self.blob.extend(data)
        return idx


def _ser_ops(ops, words: List[int], lits: _LitTable, reverse: bool) -> None:
    for op in ops:
        if isinstance(op, Lit):
            # suffix ops store literal bytes pre-reversed; the executor
            # memcmps the FORWARD spelling at (cur - k), so un-reverse here
            data = op.data[::-1] if reverse else op.data
            words.extend([0, lits.add(data)])
        elif isinstance(op, Span):
            words.extend([1, op.class_id, op.min_len,
                          -1 if op.max_len == INF else op.max_len,
                          1 if op.lazy else 0])
        elif isinstance(op, FixedSpan):
            words.extend([2, op.class_id, op.n])
        elif isinstance(op, CapStart):
            words.extend([3, op.cap_id])
        elif isinstance(op, CapEnd):
            words.extend([4, op.cap_id])
        elif isinstance(op, Optional_):
            body: List[int] = []
            _ser_ops(op.body, body, lits, reverse)
            words.extend([5, len(body)])
            words.extend(body)
        elif isinstance(op, Alt):
            words.extend([6, len(op.branches)])
            for branch in op.branches:
                body = []
                _ser_ops(branch, body, lits, reverse)
                words.append(len(body))
                words.extend(body)
        else:  # pragma: no cover
            raise NativeUnsupported(f"op {op!r}")


def serialize_program(program: SegmentProgram
                      ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                 np.ndarray, np.ndarray, int]:
    """Returns (words i32, class_bitmaps u8 [K,256], lit_blob u8,
    lit_offs i32, lit_lens i32, num_caps)."""
    ncaps = max(program.num_caps, 1)
    if ncaps > MAX_CAPS:
        raise NativeUnsupported(f"{ncaps} captures > {MAX_CAPS}")
    if len(program.classes) > MAX_CLASSES:
        # kT1MaxClasses: the executor rejects such programs at call time
        # (rc=-1); refusing to build keeps the engine on its fallback tier
        raise NativeUnsupported(
            f"{len(program.classes)} classes > {MAX_CLASSES}")
    lits = _LitTable()
    words: List[int] = [1, ncaps]

    prefix: List[int] = []
    _ser_ops(program.ops, prefix, lits, reverse=False)
    words.append(len(prefix))
    words.extend(prefix)

    if program.pivot is not None:
        p = program.pivot
        words.extend([1, p.class_id, p.min_len,
                      -1 if p.max_len == INF else p.max_len,
                      1 if p.lazy else 0])
    else:
        words.append(0)

    suffix: List[int] = []
    if program.suffix_ops:
        _ser_ops(program.suffix_ops, suffix, lits, reverse=True)
    words.append(len(suffix))
    words.extend(suffix)

    if program.pivot2 is not None:
        p2 = program.pivot2
        words.extend([1, p2.class_id, p2.min_len,
                      -1 if p2.max_len == INF else p2.max_len,
                      1 if p2.lazy else 0])
    else:
        words.append(0)

    mid: List[int] = []
    if program.mid_ops:
        _ser_ops(program.mid_ops, mid, lits, reverse=False)
    words.append(len(mid))
    words.extend(mid)

    words.append(len(program.split_caps))
    words.extend(program.split_caps)
    words.append(len(program.mid_end_caps))
    words.extend(program.mid_end_caps)

    bitmaps = np.stack([c.mask for c in program.classes]).astype(np.uint8) \
        if program.classes else np.zeros((0, 256), np.uint8)
    return (np.array(words, dtype=np.int32),
            np.ascontiguousarray(bitmaps),
            np.frombuffer(bytes(lits.blob) or b"\0", dtype=np.uint8).copy(),
            np.array(lits.offs or [0], dtype=np.int32),
            np.array(lits.lens or [0], dtype=np.int32),
            ncaps)


def _bind(lib) -> None:
    if getattr(lib, "_t1_bound", False):
        return
    u8p = ctypes.c_void_p      # raw addresses (see native.py binding note)
    i32p = ctypes.c_void_p
    i64p = ctypes.c_void_p
    lib.lct_t1_exec.restype = ctypes.c_int64
    lib.lct_t1_exec.argtypes = [
        u8p, ctypes.c_int64, i64p, i32p, ctypes.c_int64,
        i32p, ctypes.c_int64, u8p, ctypes.c_int64,
        u8p, i32p, i32p, ctypes.c_int64,
        u8p, i32p, i32p]
    lib._t1_bound = True


class NativeT1Executor:
    """One serialized program + the ctypes call, shaped like the device
    path's output: (ok bool [N], cap_off i32 [N,C] arena-ABSOLUTE,
    cap_len i32 [N,C], len -1 = absent)."""

    def __init__(self, program: SegmentProgram):
        lib = native_mod.get_lib()
        if lib is None or not hasattr(lib, "lct_t1_exec"):
            raise NativeUnsupported("native library unavailable")
        _bind(lib)
        self._lib = lib
        (self._words, self._bitmaps, self._blob, self._loffs, self._llens,
         self.num_caps) = serialize_program(program)

    def __call__(self, arena: np.ndarray, offsets: np.ndarray,
                 lengths: np.ndarray
                 ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        arena = np.ascontiguousarray(arena, dtype=np.uint8)
        offsets = np.ascontiguousarray(offsets, dtype=np.int64)
        lengths = np.ascontiguousarray(lengths, dtype=np.int32)
        n = len(offsets)
        C = self.num_caps
        # one arena carve instead of three mmap-class allocations: the
        # outputs live as long as the group's columns, so they cannot be
        # pooled, but they CAN share one block (pipeline-e2e hot path)
        span = n * C * 4
        blk = np.empty(span * 2 + n, dtype=np.uint8)
        cap_off = blk[:span].view(np.int32).reshape(n, C)
        cap_len = blk[span:span * 2].view(np.int32).reshape(n, C)
        ok = blk[span * 2:]
        u8 = native_mod._u8
        i32 = native_mod._i32
        i64 = native_mod._i64
        rc = self._lib.lct_t1_exec(
            u8(arena), len(arena), i64(offsets), i32(lengths), n,
            i32(self._words), len(self._words),
            u8(self._bitmaps), len(self._bitmaps),
            u8(self._blob), i32(self._loffs), i32(self._llens),
            len(self._loffs),
            u8(ok), i32(cap_off), i32(cap_len))
        if rc != 0:
            raise NativeUnsupported(f"lct_t1_exec rc={rc}")
        # zero-copy reinterpret: the executor writes strictly 0/1
        return ok.view(np.bool_), cap_off, cap_len


def try_build(program: SegmentProgram) -> Optional[NativeT1Executor]:
    try:
        return NativeT1Executor(program)
    except NativeUnsupported:
        return None
