"""Unified regex engine: tier dispatch + batch orchestration.

The single entry point processors use.  Given a pattern, picks the execution
tier (segment kernel / DFA kernel / CPU `re`), owns geometry bucketing and
row packing, and returns arena-absolute capture spans so downstream stays
zero-copy (SURVEY.md §7 step 4: spans must index the ORIGINAL arena).

Oversize events (> largest length bucket) and CPU-tier patterns run through
the Python `re` fallback with identical semantics — the reference's
"route unsupported patterns to CPU" contract.
"""

from __future__ import annotations

import os
import re
import time
from typing import Optional, Tuple

import numpy as np

import threading

from ... import chaos
from .. import chip_lanes, xprof
from ..chip_lanes import ChipLaneFault, lane_gated
from ..device_batch import (LENGTH_BUCKETS, MAX_BATCH, pack_rows, pad_batch,
                            pick_length_bucket)
from ..device_stream import (FP_RING_ADVANCE, auto_tuner, batch_ring,
                             h2d_gated, stream_depth)
from ..kernels.dfa_scan import DFAMatchKernel
from ..kernels.field_extract import ExtractKernel
from .dfa import DFAUnsupported, compile_dfa
from .program import (Alt, Optional_, PatternTier, Tier1Unsupported,
                      compile_tier1)


def _pallas_enabled() -> Optional[bool]:
    """LOONG_PALLAS=1 forces the fused Pallas path, =0 forces the XLA
    path; unset → auto (Pallas on real TPU, XLA elsewhere — the Pallas
    interpreter is a debugging tool, not a fast CPU path)."""
    env = os.environ.get("LOONG_PALLAS")
    if env is not None:
        return env == "1"
    return None


_host_backend_cached: Optional[bool] = None

# ---------------------------------------------------------------------------
# Latency-aware device routing.
#
# A device dispatch costs a fixed round trip (sub-ms on a local chip; tens of
# ms through a remote/tunneled TPU) before any bytes are parsed, while the
# native C++ walker starts instantly at a few hundred MB/s.  The crossover is
#     min_bytes = dispatch_latency * host_throughput
# — below it the host tier finishes before the device call would even return.
# Latency AND effective host<->device bandwidth are MEASURED once per process
# with a realistic two-size payload probe, so the threshold adapts to the
# actual deployment: ~100 KB on local silicon; through a high-latency tunnel
# whose effective bandwidth is below the walker's throughput, the device can
# never win on host-resident data and routing pins to the host tier.
# LOONG_DEVICE_MIN_BYTES overrides.

_HOST_WALKER_BPS = 300e6          # conservative native-walker throughput
_dispatch_probe_lock = threading.Lock()
_device_min_bytes_cached: Optional[int] = None


def _device_min_bytes() -> int:
    global _device_min_bytes_cached
    if _device_min_bytes_cached is not None:
        return _device_min_bytes_cached
    env = os.environ.get("LOONG_DEVICE_MIN_BYTES")
    if env is not None:
        _device_min_bytes_cached = int(env)
        return _device_min_bytes_cached
    with _dispatch_probe_lock:
        if _device_min_bytes_cached is not None:
            return _device_min_bytes_cached
        _device_min_bytes_cached = _run_dispatch_probe()
    return _device_min_bytes_cached


def _run_dispatch_probe() -> int:
    """Measure the device round trip and derive the routing crossover.

    The probe mimics the REAL parse path: host-resident numpy rows in, a
    row-reduction out, result materialised back to the host.  (A
    `jnp.zeros` input lives on-device already and makes a 70 ms tunnel
    round trip look like 30 µs.)  Two payload sizes fit the affine cost
    t(n) = lat + n/bw, separating fixed dispatch latency from effective
    host<->device bandwidth.  A wedged tunnel hangs transfers instead of
    raising, so the whole probe runs under a deadline (timeout ⇒
    host-only)."""

    def probe() -> int:
        try:
            import jax
            import jax.numpy as jnp_
            import numpy as np_
            # not a kernel family: a once-per-process latency probe whose
            # compile cost IS part of what it measures — compile_watch
            # accounting would pollute the families it exists to audit
            # loonglint: disable=unwatched-jit
            g = jax.jit(lambda r: r.astype(jnp_.int32).sum(axis=1))
            sizes = [(2048, 128), (8192, 512)]      # 256 KB, 4 MB
            times = []
            for B, L in sizes:
                rows = np_.zeros((B, L), np_.uint8)
                np_.asarray(g(rows))                # compile + warm path
                samples = []
                for _ in range(3):
                    t0 = time.perf_counter()
                    np_.asarray(g(rows))
                    samples.append(time.perf_counter() - t0)
                times.append(sorted(samples)[1])
            n0, n1 = (B * L for B, L in sizes)
            t0_, t1_ = times
            bw = (n1 - n0) / max(t1_ - t0_, 1e-9)
            lat = max(t0_ - n0 / bw, 1e-6)
            if bw <= _HOST_WALKER_BPS * 1.1:
                # effective device bandwidth can't beat the host walker at
                # ANY size (high-latency tunnel): never route to the device
                return 1 << 60
            crossover = lat / (1.0 / _HOST_WALKER_BPS - 1.0 / bw)
            # clamp: one noisy latency sample must not pin multi-hundred-MB
            # batches to the host for the whole process
            return max(32 * 1024, min(int(crossover), 128 * 1024 * 1024))
        except Exception:  # noqa: BLE001 — routing must never break parsing
            return 256 * 1024

    return _call_with_deadline(probe, _probe_deadline_s() * 2, 1 << 60)


def _probe_deadline_s() -> float:
    try:
        return float(os.environ.get("LOONG_BACKEND_PROBE_TIMEOUT_S", "30"))
    except ValueError:
        return 30.0


def _call_with_deadline(fn, timeout_s: float, fallback):
    """Run `fn` on a daemon thread; return its result, or `fallback` if it
    raises or misses the deadline.

    Backend init and transfers through a remote/tunneled accelerator
    (axon) BLOCK indefinitely when the tunnel is down — a hang, not an
    exception — and routing must never hang the pipeline."""
    import queue

    q: "queue.Queue" = queue.Queue(maxsize=1)

    def run() -> None:
        try:
            q.put(fn())
        except Exception:  # noqa: BLE001 — fast failure must not stall
            q.put(fallback)

    t = threading.Thread(target=run, daemon=True, name="loong-probe")
    t.start()
    try:
        return q.get(timeout=timeout_s)
    except Exception:  # noqa: BLE001 — timeout ⇒ device unusable
        return fallback


def _backend_is_cpu_with_deadline() -> bool:
    """`jax.default_backend() == "cpu"`, with a hard deadline: if the
    backend cannot even answer, it is pinned unusable ⇒ host mode."""

    def query() -> bool:
        import jax
        return jax.default_backend() == "cpu"

    return _call_with_deadline(query, _probe_deadline_s(), True)


def _native_host_mode() -> bool:
    """True when Tier-1 programs should run on the native C++ walker:
    the backend is CPU (no accelerator — degraded mode or tests), where
    XLA's emulation of the masked-reduction kernel is ~10× slower than a
    scalar walk.  LOONG_NATIVE_T1=1 forces it, =0 disables it."""
    env = os.environ.get("LOONG_NATIVE_T1")
    if env is not None:
        return env == "1"
    if os.environ.get("LOONG_PALLAS") is not None:
        return False  # explicit device-kernel force wins over host auto
    global _host_backend_cached
    if _host_backend_cached is None:
        _host_backend_cached = _backend_is_cpu_with_deadline()
    return _host_backend_cached


def _chunks(idx: np.ndarray, size: int):
    for i in range(0, len(idx), size):
        yield idx[i : i + size]


class BatchParseResult:
    """ok: bool [N]; cap_off/cap_len: int32 [N, C] arena-absolute spans
    (len -1 ⇒ no capture / failed parse)."""

    __slots__ = ("ok", "cap_off", "cap_len")

    def __init__(self, ok, cap_off, cap_len):
        self.ok = ok
        self.cap_off = cap_off
        self.cap_len = cap_len


from collections import OrderedDict

_engine_cache: "OrderedDict" = OrderedDict()
_engine_cache_lock = threading.Lock()
_ENGINE_CACHE_MAX = 512


def clear_engine_cache() -> None:
    """Drop every cached engine.  Mesh width (``LOONG_MESH_CHIPS``), lane
    routing and backend forces are resolved once per engine — tests and
    the bench chips sweep clear the cache after changing them so the next
    ``get_engine`` re-resolves against the new environment."""
    with _engine_cache_lock:
        _engine_cache.clear()


def get_engine(pattern: str,
               force_tier: Optional[PatternTier] = None) -> "RegexEngine":
    """Process-wide engine cache: pipeline reloads and same-pattern plugins
    reuse compiled kernels instead of re-jitting (compilation is the
    dominant cost of a pipeline swap)."""
    if isinstance(pattern, bytes):
        pattern = pattern.decode("latin-1")
    key = (pattern, force_tier)
    with _engine_cache_lock:
        eng = _engine_cache.get(key)
        if eng is not None:
            _engine_cache.move_to_end(key)  # LRU touch
            return eng
    # compile outside the lock (jit can take seconds); races build the same
    # engine twice at worst
    eng = RegexEngine(pattern, force_tier)
    eng.warm_host()
    with _engine_cache_lock:
        _engine_cache[key] = eng
        while len(_engine_cache) > _ENGINE_CACHE_MAX:
            _engine_cache.popitem(last=False)  # evict least-recently used
    return eng


class _LanePlacedKernel:
    """A single-device kernel pinned to one chip lane (loongmesh): inputs
    are device_put onto the lane's chip, so the jitted step executes on
    that chip's stream — distinct workers drive distinct chips with no
    collectives on the batch path.  Exposes the same ``donated_call``
    protocol as the base kernels (the placed copies are transient staging
    buffers, safe to donate)."""

    __slots__ = ("base", "lane")

    def __init__(self, base, lane):
        self.base = base
        self.lane = lane

    def _place(self, rows, lengths):
        import jax
        return (jax.device_put(rows, self.lane.device),
                jax.device_put(lengths, self.lane.device))

    def __call__(self, rows, lengths):
        rows_d, lens_d = self._place(rows, lengths)
        return self.base(rows_d, lens_d)

    def donated_call(self, rows, lengths):
        rows_d, lens_d = self._place(rows, lengths)
        don = getattr(self.base, "donated_call", None)
        return don(rows_d, lens_d) if don is not None \
            else self.base(rows_d, lens_d)


class RegexEngine:
    def __init__(self, pattern: str, force_tier: Optional[PatternTier] = None):
        if isinstance(pattern, bytes):
            pattern = pattern.decode("latin-1")
        self.pattern = pattern
        self._re = re.compile(pattern.encode("latin-1"))
        self.num_caps = self._re.groups
        self.group_names = {v - 1: k for k, v in self._re.groupindex.items()}
        self._segment_kernel: Optional[ExtractKernel] = None
        self._pallas_kernel = None          # built lazily on first use
        self._use_pallas: Optional[bool] = None
        self._sharded = None                # None=unresolved, False=off
        self._lane_kernels = {}             # chip index -> _LanePlacedKernel
        self._native_exec = None            # host C++ walker, built lazily
        self._native_tried = False
        self._dfa_kernel: Optional[DFAMatchKernel] = None
        self._fused_single = None           # loongfuse host exec, lazy
        self._fused_tried = False
        self._dfa_scanner = None            # fused host scanner (DFA tier)
        self.tier = PatternTier.CPU
        if force_tier in (None, PatternTier.SEGMENT):
            try:
                self._segment_kernel = ExtractKernel(compile_tier1(pattern))
                self.tier = PatternTier.SEGMENT
            except Tier1Unsupported:
                pass
        if self.tier is PatternTier.CPU and force_tier in (None, PatternTier.DFA):
            try:
                self._dfa_kernel = DFAMatchKernel(compile_dfa(pattern))
                self.tier = PatternTier.DFA
            except DFAUnsupported:
                pass
        if force_tier is not None and self.tier is not force_tier \
                and force_tier is not PatternTier.CPU:
            raise ValueError(f"pattern {pattern!r} cannot run at {force_tier}")
        # demotion observability (loongfuse satellite): a pattern falling
        # off the device tier used to be SILENT — a TPU collapse like
        # multiline-java's 1.6 MB/s was invisible until a bench run
        if force_tier is None:
            from .fuse import note_demotion
            if self.tier is PatternTier.CPU:
                note_demotion(pattern,
                              "no device tier (Tier-1 and DFA compile "
                              "both refused)")
            elif self.tier is PatternTier.DFA and self.num_caps > 0:
                note_demotion(pattern,
                              "capture-needing Tier-2 (device gates the "
                              "match; captures extract on host)")

    # ------------------------------------------------------------------

    def set_device_kernel_override(self, kern) -> None:
        """Test/diagnostic hook: route this engine's device dispatches
        through `kern` (e.g. a LatencyInjectedKernel modelling a remote
        chip).  None restores normal selection."""
        self._kernel_override = kern

    def _maybe_sharded(self):
        """Multi-chip engine mode (SURVEY §2.7): when enabled and more than
        one device is attached, SEGMENT-tier dispatches run through
        ShardedParsePlane — the batch dimension shards over the ICI mesh,
        per-chip extraction + psum'd telemetry.  The plane rides the same
        async DevicePlane budget as single-chip dispatch, so watermark
        back-pressure is unchanged.  LOONG_SHARDED=1 forces, =0 disables;
        default auto (on when >1 device)."""
        if self._sharded is not None:
            return self._sharded or None
        env = os.environ.get("LOONG_SHARDED", "").strip()
        if env == "0" or self._segment_kernel is None:
            self._sharded = False
            return None
        if env != "1" and _pallas_enabled() is not None:
            # an explicit LOONG_PALLAS force pins the single-device kernel
            # choice; only an explicit LOONG_SHARDED=1 outranks it
            self._sharded = False
            return None
        try:
            import jax
            n = len(jax.devices())
            if n <= 1 and env != "1":
                self._sharded = False
                return None
            from ...parallel.mesh import ShardedKernel
            self._sharded = ShardedKernel(self._segment_kernel.program)
        except Exception:  # noqa: BLE001 — mesh build failure = single-chip
            from ...utils.logger import get_logger
            get_logger("regex").exception(
                "sharded plane unavailable; staying single-device")
            self._sharded = False
            return None
        return self._sharded

    def _device_kernel_failed(self, kern) -> None:
        """Runtime fault in a device kernel: pin this engine off that path
        (throughput cost, never liveness)."""
        if kern is self._pallas_kernel:
            self._use_pallas = False
        if self._sharded not in (None, False) and kern is self._sharded:
            self._sharded = False
        if isinstance(kern, _LanePlacedKernel):
            # a placed kernel's failure is usually the BASE kernel's
            # (Mosaic bug, not chip health): pin the base path too, or
            # every lane rebuilds a wrapper around the same failing
            # kernel and healthy chips trip their breakers on software
            if kern.base is self._pallas_kernel:
                self._use_pallas = False
            self._lane_kernels.pop(kern.lane.index, None)

    def _device_kernel(self, lane=None):
        """Segment-tier kernel selection.  A lane-bound dispatch (sharded
        processor worker on a multi-chip host) gets a single-device kernel
        PLACED on its home chip — independent per-chip execution streams,
        the loongmesh data plane.  Unbound dispatches shard over the full
        mesh when multiple devices are attached, else fused Pallas on TPU
        (one VMEM pass per row block), XLA fusion elsewhere.  Resolved
        once per engine (per lane); the paths are differentially fuzzed
        against each other."""
        if getattr(self, "_kernel_override", None) is not None:
            return self._kernel_override
        if lane is not None:
            k = self._lane_kernels.get(lane.index)
            if k is None:
                k = _LanePlacedKernel(self._single_device_kernel(), lane)
                self._lane_kernels[lane.index] = k
            return k
        sharded = self._maybe_sharded()
        if sharded is not None:
            return sharded
        return self._single_device_kernel()

    def _single_device_kernel(self):
        """Pallas-vs-XLA choice for one device (shared by the default
        path and every lane-placed wrapper)."""
        if self._use_pallas is None:
            forced = _pallas_enabled()
            if forced is not None:
                self._use_pallas = forced
            else:
                import jax
                self._use_pallas = jax.default_backend() == "tpu"
        if self._use_pallas:
            if self._pallas_kernel is None:
                from ..kernels.field_extract_pallas import PallasExtractKernel
                self._pallas_kernel = PallasExtractKernel(
                    self._segment_kernel.program)
            return self._pallas_kernel
        return self._segment_kernel

    def _host_walker(self):
        """The native C++ scalar walker for this program (degraded tier);
        None when the library is absent or the program exceeds its limits."""
        if not self._native_tried:
            self._native_tried = True
            if self._segment_kernel is not None:
                from .native_exec import try_build
                self._native_exec = try_build(self._segment_kernel.program)
        return self._native_exec

    def warm_host(self) -> None:
        """AOT-build the host execution artifacts (loongfuse variant
        linearization, native walker, DFA byte-table scanner) at pipeline
        start — get_engine calls this so the first data batch never stalls
        on variant compilation.  Direct constructions (tests, ad-hoc) stay
        cheap and build lazily."""
        if self.tier is PatternTier.SEGMENT:
            self._fused_exec()
            self._host_walker()
        elif self.tier is PatternTier.DFA:
            self._dfa_host_scanner()

    @staticmethod
    def _ops_have_trials(ops) -> bool:
        return any(isinstance(op, (Alt, Optional_)) for op in ops)

    def _fused_exec(self):
        """loongfuse host execution (AOT variant linearization + fused
        classify), built lazily on first host parse.  Only trial-heavy
        straight programs profit — a linear program IS the fast path
        already, and pivot programs scan bidirectionally."""
        if not self._fused_tried:
            self._fused_tried = True
            prog = self._segment_kernel.program \
                if self._segment_kernel is not None else None
            if prog is not None and prog.pivot is None \
                    and prog.pivot2 is None \
                    and self._ops_have_trials(prog.ops):
                from .fuse import try_build_single
                self._fused_single = try_build_single(self.pattern)
        return self._fused_single

    def _dfa_host_scanner(self):
        """Fused byte-table scanner over the Tier-2 DFA: the host
        match-gate (multiline classification) at table-walk speed instead
        of a per-row Python `re` loop."""
        if self._dfa_scanner is None and self._dfa_kernel is not None:
            from .fuse import ByteTableScanner
            self._dfa_scanner = ByteTableScanner.from_dfa(
                self._dfa_kernel.dfa)
        return self._dfa_scanner

    def parse_batch(self, arena: np.ndarray, offsets: np.ndarray,
                    lengths: np.ndarray) -> BatchParseResult:
        """Full-match + captures for N events over a shared arena."""
        return self.parse_batch_async(arena, offsets, lengths).result()

    def parse_batch_async(self, arena: np.ndarray, offsets: np.ndarray,
                          lengths: np.ndarray,
                          depth: Optional[int] = None) -> "PendingParse":
        """Dispatch the parse; `result()` on the returned handle materialises.

        The async device data plane (SURVEY §7 step 4): each device chunk is
        dispatched through DevicePlane under the in-flight byte budget, and
        the host packs chunk N+1 while the device executes chunk N.  Callers
        that hold the PendingParse (runner overlap mode) get cross-group
        overlap too: the device computes group N while the host runs group
        N-1's downstream processors and group N+1's pack.  Host-walker and
        CPU-tier routing are unchanged — those paths return an
        already-materialised PendingParse.

        loongstream: chunks ride batch-ring slots and at most ``depth``
        (default ``LOONG_STREAM_DEPTH``) stay in flight — the ring advance
        (span return of chunk N-depth+1) overlaps packing/H2D of N+1 and
        device compute of N.  ``depth=1`` forces the synchronous
        submit→materialise round trip (the bench sweep baseline)."""
        offsets = np.asarray(offsets, dtype=np.int64)
        lengths = np.asarray(lengths, dtype=np.int32)
        n = len(offsets)
        C = max(self.num_caps, 1)
        if n and self.tier is PatternTier.SEGMENT:
            use_host = _native_host_mode()
            if not use_host and _pallas_enabled() is None \
                    and os.environ.get("LOONG_NATIVE_T1") != "0":
                # accelerator backend: small batches still lose to the fixed
                # dispatch round trip — route them to the native walker
                # (explicit LOONG_PALLAS / LOONG_NATIVE_T1 forces win)
                nat = self._host_walker()
                use_host = (nat is not None
                            and int(lengths.sum()) < _device_min_bytes())
            if use_host:
                fx = self._fused_exec()
                if fx is not None:
                    k_ok, k_off, k_len = fx.parse(arena, offsets, lengths)
                    return PendingParse.ready(
                        BatchParseResult(k_ok, k_off, k_len))
                nat = self._host_walker()
                if nat is not None:
                    k_ok, k_off, k_len = nat(arena, offsets, lengths)
                    return PendingParse.ready(
                        BatchParseResult(k_ok, k_off, k_len))
        ok = np.zeros(n, dtype=bool)
        cap_off = np.zeros((n, C), dtype=np.int32)
        cap_len = np.full((n, C), -1, dtype=np.int32)
        if n == 0:
            return PendingParse.ready(BatchParseResult(ok, cap_off, cap_len))

        max_bucket = LENGTH_BUCKETS[-1]
        over = lengths > max_bucket
        device_idx = np.nonzero(~over)[0]
        cpu_idx = np.nonzero(over)[0]

        if self.tier is PatternTier.CPU or self._segment_kernel is None:
            cpu_idx = np.arange(n)
            device_idx = np.array([], dtype=np.int64)

        pending = PendingParse(self, arena, offsets, lengths,
                               ok, cap_off, cap_len, cpu_idx, depth=depth)
        if len(device_idx):
            pending.dispatch(device_idx)
        return pending

    def _cpu_fallback_rows(self, arena, offsets, lengths, cpu_idx,
                           ok, cap_off, cap_len) -> None:
        for i in cpu_idx:
            o, ln = int(offsets[i]), int(lengths[i])
            m = self._re.fullmatch(bytes(arena[o : o + ln].tobytes()))
            if m is not None:
                ok[i] = True
                for g in range(self.num_caps):
                    s, e = m.span(g + 1)
                    if s >= 0:
                        cap_off[i, g] = o + s
                        cap_len[i, g] = e - s

    def _host_parse_rows(self, arena, offsets, lengths, idx,
                         ok, cap_off, cap_len) -> None:
        """Host-tier parse of selected rows, spans arena-absolute — the
        chip-lane RESPILL path (loongmesh): a tripped lane's shard parses
        here, synchronously, so a single-chip fault costs throughput on
        that lane only — never events, never the rest of the mesh.  Tier
        order mirrors the degraded-mode routing: fused exec → native
        walker → CPU `re`."""
        if len(idx) == 0:
            return
        fx = self._fused_exec()
        nat = fx if fx is not None else self._host_walker()
        if nat is not None:
            run = nat.parse if fx is not None else nat
            k_ok, k_off, k_len = run(arena, offsets[idx], lengths[idx])
            ok[idx] = k_ok
            cap_off[idx] = k_off
            cap_len[idx] = k_len
            return
        self._cpu_fallback_rows(arena, offsets, lengths, idx,
                                ok, cap_off, cap_len)

    def match_batch(self, arena: np.ndarray, offsets: np.ndarray,
                    lengths: np.ndarray) -> np.ndarray:
        """Full-match boolean only (filtering) — can use the DFA tier."""
        offsets = np.asarray(offsets, dtype=np.int64)
        lengths = np.asarray(lengths, dtype=np.int32)
        n = len(offsets)
        if n == 0:
            return np.zeros(0, dtype=bool)
        if self.tier is PatternTier.SEGMENT:
            return self.parse_batch(arena, offsets, lengths).ok
        if self.tier is PatternTier.DFA:
            # host route (loongfuse): the fused byte-table scanner walks
            # the SAME automaton the device kernel runs, at native table
            # speed — degraded mode, and small batches where the fixed
            # dispatch round trip dwarfs any host scan; explicit
            # device-kernel forces win, as in parse_batch
            if _pallas_enabled() is None \
                    and os.environ.get("LOONG_NATIVE_T1") != "0" \
                    and (_native_host_mode()
                         or int(lengths.sum()) < _device_min_bytes() // 6):
                sc = self._dfa_host_scanner()
                if sc is not None:
                    tags = sc.scan(arena, offsets, lengths)
                    return (tags & 1).astype(bool)
            ok = np.zeros(n, dtype=bool)
            max_bucket = LENGTH_BUCKETS[-1]
            over = lengths > max_bucket
            device_idx = np.nonzero(~over)[0]
            for chunk in _chunks(device_idx, MAX_BATCH):
                d_off = offsets[chunk]
                d_len = lengths[chunk]
                L = pick_length_bucket(int(d_len.max())) or max_bucket
                batch = pack_rows(arena, d_off, d_len, L)
                # synchronous chunked match tier (DFA-tier match_batch):
                # a standalone boolean gate, not a fusable stage run
                # loonglint: disable=host-bounce
                k_ok = np.asarray(self._dfa_kernel(batch.rows, batch.lengths))
                ok[chunk] = k_ok[: batch.n_real]
            for i in np.nonzero(over)[0]:
                o, ln = int(offsets[i]), int(lengths[i])
                ok[i] = self._re.fullmatch(bytes(arena[o : o + ln].tobytes())) is not None
            return ok
        # CPU tier
        ok = np.zeros(n, dtype=bool)
        for i in range(n):
            o, ln = int(offsets[i]), int(lengths[i])
            ok[i] = self._re.fullmatch(bytes(arena[o : o + ln].tobytes())) is not None
        return ok


class PendingParse:
    """A parse whose device chunks are in flight.

    loongstream dispatch discipline: `dispatch()` packs each device chunk
    into a leased batch-ring slot (pre-allocated fixed-geometry buffers —
    no per-dispatch allocation on the H2D path) and submits it through the
    DevicePlane, keeping at most ``depth`` chunks in flight: a full window
    first advances the ring (materialises the OLDEST chunk), so the host
    packs chunk N+1 while the device executes N and N-depth+1 returns
    spans.  When the in-flight byte budget would block a submit, the
    oldest owned future is drained first (never sleep in submit while
    owning the budget you wait for — see DevicePlane.would_block).
    `result()` runs the CPU-tier fallback rows (host work, overlapping the
    device), then materialises remaining chunks in order.

    Error semantics: an injected chaos fault (``device_plane.h2d`` /
    ``device_plane.ring_advance`` / ``device_plane.submit``) costs that one
    chunk a synchronous re-run — never the parse, never the ring order.  A
    Pallas/Mosaic failure at materialisation pins the engine to the XLA
    path and re-runs that chunk synchronously; failures on the XLA kernel
    itself propagate.  Every path releases the chunk's slot and budget.

    loongmesh: a lane-bound worker's chunks dispatch on its home chip
    (``device_plane.chip_lane.<i>`` chaos point, per-chip budget share,
    per-chip tuner floors).  An injected single-chip fault feeds the
    lane's breaker and respills that chunk to host parsing; a tripped
    (OPEN) lane respills its whole shard pre-dispatch until the half-open
    probe re-closes it — the other chips' lanes keep running throughout.
    """

    __slots__ = ("engine", "arena", "offsets", "lengths", "ok", "cap_off",
                 "cap_len", "cpu_idx", "_chunks_pending", "_result", "kern",
                 "depth")

    def __init__(self, engine, arena, offsets, lengths, ok, cap_off, cap_len,
                 cpu_idx, depth=None):
        self.engine = engine
        self.arena = arena
        self.offsets = offsets
        self.lengths = lengths
        self.ok = ok
        self.cap_off = cap_off
        self.cap_len = cap_len
        self.cpu_idx = cpu_idx
        # [(chunk_idx, DeviceBatch, BatchSlot, DeviceFuture, kernel)]
        self._chunks_pending = []
        self._result = None
        self.kern = None
        self.depth = max(1, depth if depth is not None else stream_depth())

    @classmethod
    def ready(cls, result: BatchParseResult) -> "PendingParse":
        p = cls.__new__(cls)
        p._result = result
        p._chunks_pending = []
        p.cpu_idx = ()
        return p

    @property
    def done(self) -> bool:
        return self._result is not None

    def dispatch(self, device_idx: np.ndarray) -> None:
        from ..device_plane import DevicePlane
        plane = DevicePlane.instance()
        ring = batch_ring()
        tuner = auto_tuner()
        # loongmesh: a lane-bound worker thread dispatches on its home
        # chip (source → worker → chip affinity); unbound dispatch shards
        # over the full mesh (or runs single-device)
        lane = chip_lanes.current_lane()
        lane_count = chip_lanes.router().lane_count() if lane is not None \
            else 0
        self.kern = self.engine._device_kernel(lane)
        max_bucket = LENGTH_BUCKETS[-1]
        try:
            for chunk in _chunks(device_idx, MAX_BATCH):
                if lane is not None and not lane.breaker.allow_probe():
                    # lane breaker OPEN (or the half-open probe slot is
                    # already in flight): this chip is sick — respill its
                    # shard to host parsing.  Events still parse, in
                    # order, synchronously (ledger-conserved); the other
                    # chips' lanes keep running untouched.
                    lane.note_respill(len(chunk))
                    self.engine._host_parse_rows(
                        self.arena, self.offsets, self.lengths, chunk,
                        self.ok, self.cap_off, self.cap_len)
                    continue
                # ring advance: a full window materialises its oldest chunk
                # (span return of N-depth+1) before packing N+1
                while len(self._chunks_pending) >= self.depth:
                    self._drain_one()
                # per-chip budget share: a lane holding more than its
                # slice of the plane budget drains its own oldest chunk
                # first — one slow chip backs up its own lane, not the
                # whole plane (same never-sleep-owning-budget rule)
                while lane is not None \
                        and lane.over_share(plane, lane_count) \
                        and self._chunks_pending:
                    self._drain_one()
                # re-read the kernel PER CHUNK: the drain above (or the
                # budget-wait hook inside submit) may have pinned the
                # engine to the XLA path mid-dispatch — each pending tuple
                # must record the kernel its chunk was actually SUBMITTED
                # on, or the materialise-time fallback check misfires.
                # Buffer donation: a kernel offering a donating variant
                # gets it on this path — each dispatch's inputs are
                # transient staging copies, so XLA may reuse their HBM for
                # the outputs instead of allocating per dispatch.
                sub_kern = self.kern
                call = getattr(sub_kern, "donated_call", None) or sub_kern
                if lane is not None:
                    # chip-lane chaos: dispatch passes this lane's fault
                    # point; the bare kernel stays in the pending tuple so
                    # recovery re-runs never re-fire the injection
                    call = lane_gated(lane, call)
                d_off = self.offsets[chunk]
                d_len = self.lengths[chunk]
                L = pick_length_bucket(int(d_len.max()) if len(d_len) else 1) \
                    or max_bucket
                lane_idx = lane.index if lane is not None else None
                B = pad_batch(len(chunk),
                              min_batch=tuner.min_batch_for(L, lane_idx),
                              multiple_of=getattr(sub_kern,
                                                  "batch_multiple", 1))
                slot = ring.lease(B, L)
                try:
                    batch = slot.pack(self.arena, d_off, d_len,
                                      lane=lane_idx)
                    fut = plane.submit(h2d_gated(call),
                                       (batch.rows, batch.lengths),
                                       batch.rows.nbytes,
                                       on_wait=self._drain_if_pending)
                except BaseException:
                    slot.release()
                    raise
                xprof.note_dispatch(fut, "regex", f"{B}x{L}",
                                    slot.pack_t0, slot.pack_dur)
                if lane is not None:
                    lane.note_pack(B, batch.n_real)
                    lane.note_dispatch(batch.rows.nbytes)
                self._chunks_pending.append((chunk, batch, slot, fut,
                                             sub_kern, lane))
        except BaseException:
            # a failed pack/submit must not strand the budget (or the ring
            # slots, or the lanes' in-flight accounting) the
            # already-submitted futures hold (round-5 leak): force-release
            # them — the caller abandons this parse, nobody will result()
            # them
            for _, b, slot, fut, _k, ln in self._chunks_pending:
                fut.release()
                if ln is not None:
                    ln.note_done(b.rows.nbytes)
                    # an abandoned chunk may hold the lane's half-open
                    # probe slot — release it (no health sample) so the
                    # lane is not forced to respill until probe_timeout_s
                    ln.breaker.on_inconclusive()
                slot.release()
            self._chunks_pending.clear()
            raise

    def _drain_if_pending(self) -> bool:
        """Budget-wait hook: materialise our oldest in-flight chunk so the
        bytes we hold are released while we wait (DevicePlane._acquire's
        deadlock-freedom rule)."""
        if not self._chunks_pending:
            return False
        self._drain_one()
        return True

    def _drain_one(self) -> None:
        chunk, batch, slot, fut, sub_kern, lane = self._chunks_pending.pop(0)
        try:
            try:
                chaos.faultpoint(FP_RING_ADVANCE)
                k_ok, k_off, k_len = fut.result()
                if lane is not None:
                    # healthy materialisation on this chip: breaker sample
                    # (re-closes a half-open lane when this was the probe)
                    lane.breaker.on_success()
            except ChipLaneFault:
                # injected SINGLE-CHIP fault (device_plane.chip_lane.<i>):
                # feed the lane breaker — enough of these trip it OPEN and
                # later chunks respill pre-dispatch — and respill THIS
                # chunk's shard to host parsing.  Events conserved, order
                # kept (results land in the same slots), the other chips'
                # lanes never notice.
                fut.release()
                lane.breaker.on_failure()
                lane.note_fault()
                lane.note_respill(int(batch.n_real))
                self.engine._host_parse_rows(
                    self.arena, self.offsets, self.lengths, chunk,
                    self.ok, self.cap_off, self.cap_len)
                return
            except chaos.ChaosFault:
                # injected async-stage fault (h2d / ring_advance / submit):
                # it must error only THIS chunk — the slot still holds the
                # packed rows, so re-run synchronously and keep the ring
                # moving in order.  fut.release() is a no-op if result()
                # already returned the budget.  The chunk may hold the
                # lane's half-open probe slot: its outcome MUST reach the
                # breaker (success on a clean re-run, inconclusive on a
                # re-run failure) or the slot wedges and the whole lane
                # respills for probe_timeout_s.
                fut.release()
                try:
                    outs = sub_kern(batch.rows, batch.lengths)
                except BaseException:
                    if lane is not None:
                        lane.breaker.on_inconclusive()
                    raise
                if lane is not None:
                    lane.breaker.on_success()
                # chaos-fault recovery re-run: the designed exception path
                # loonglint: disable=host-bounce
                k_ok, k_off, k_len = (np.asarray(a) for a in outs)
            except Exception:  # noqa: BLE001
                if sub_kern is self.engine._segment_kernel or \
                        getattr(self.engine, "_kernel_override",
                                None) is not None:
                    raise
                # Mosaic/mesh/chip runtime failure must cost throughput,
                # never liveness: pin this engine off the failed path and
                # re-run the chunk on the proven XLA kernel.  A lane
                # kernel's REAL failure also counts against its chip's
                # breaker — repeated ones trip the lane to host respill.
                from ...utils.logger import get_logger
                get_logger("regex").exception(
                    "device kernel failed for %r; falling back to XLA path",
                    self.engine.pattern)
                if lane is not None:
                    lane.breaker.on_failure()
                    lane.note_fault()
                self.engine._device_kernel_failed(sub_kern)
                # lane dispatches keep their placement (the pop above
                # plus base pinning rebuilds a wrapper around the proven
                # XLA kernel); unplaced dispatches fall to XLA directly
                self.kern = self.engine._segment_kernel if lane is None \
                    else self.engine._device_kernel(lane)
                # kernel-failure fallback re-run on the proven XLA path
                # loonglint: disable=host-bounce
                k_ok, k_off, k_len = (np.asarray(a) for a in
                                      self.kern(batch.rows, batch.lengths))
            k_ok = k_ok[: batch.n_real]
            k_off = k_off[: batch.n_real]
            k_len = k_len[: batch.n_real]
            self.ok[chunk] = k_ok
            # row-relative -> arena-absolute
            self.cap_off[chunk] = k_off + batch.origins[: batch.n_real, None]
            self.cap_len[chunk] = k_len
        finally:
            if lane is not None:
                lane.note_done(batch.rows.nbytes)
            # the slot may be repacked the moment it returns to the ring:
            # release only after the spans were copied out above
            slot.release()

    def result(self) -> BatchParseResult:
        if self._result is not None:
            return self._result
        # CPU-tier rows first: host work that overlaps in-flight device chunks
        if len(self.cpu_idx):
            self.engine._cpu_fallback_rows(
                self.arena, self.offsets, self.lengths, self.cpu_idx,
                self.ok, self.cap_off, self.cap_len)
        try:
            while self._chunks_pending:
                self._drain_one()
        except BaseException:
            # a failed chunk must not leak the others' in-flight budget —
            # or their ring slots, or their lanes' in-flight accounting
            for _, b, slot, fut, _k, ln in self._chunks_pending:
                try:
                    fut.result()
                except Exception:  # noqa: BLE001 — releasing, not consuming
                    pass
                if ln is not None:
                    ln.note_done(b.rows.nbytes)
                    ln.breaker.on_inconclusive()   # see dispatch cleanup
                slot.release()
            self._chunks_pending.clear()
            raise
        self._result = BatchParseResult(self.ok, self.cap_off, self.cap_len)
        # drop references so the arena/batches free promptly
        self.arena = self.offsets = self.lengths = None
        return self._result
