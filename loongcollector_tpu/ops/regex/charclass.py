"""Byte character classes as 256-entry boolean masks.

The TPU kernels never gather from a 256-entry LUT (per-element gathers are
slow on TPU); instead each class is lowered to a union of byte intervals and
membership is computed with vectorised range comparisons on the VPU.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

try:  # Python 3.11+
    from re import _constants as sre_c
    from re import _parser as sre_parse
except ImportError:  # pragma: no cover
    import sre_constants as sre_c
    import sre_parse

_WHITESPACE = b" \t\n\r\x0b\x0c"
_DIGITS = b"0123456789"
_WORD = b"abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_"


def _category_mask(cat) -> np.ndarray:
    mask = np.zeros(256, dtype=bool)
    name = str(cat)
    if "DIGIT" in name:
        mask[list(_DIGITS)] = True
    elif "SPACE" in name:
        mask[list(_WHITESPACE)] = True
    elif "WORD" in name:
        mask[list(_WORD)] = True
    else:
        raise ValueError(f"unsupported category {cat}")
    if "NOT" in name:
        mask = ~mask
    return mask


class CharClass:
    """A set of byte values."""

    __slots__ = ("mask",)

    def __init__(self, mask: np.ndarray):
        self.mask = np.asarray(mask, dtype=bool)

    # -- constructors -------------------------------------------------------

    @classmethod
    def from_bytes(cls, data: bytes) -> "CharClass":
        mask = np.zeros(256, dtype=bool)
        mask[list(data)] = True
        return cls(mask)

    @classmethod
    def single(cls, byte: int) -> "CharClass":
        mask = np.zeros(256, dtype=bool)
        mask[byte] = True
        return cls(mask)

    @classmethod
    def dot(cls, dotall: bool = False) -> "CharClass":
        mask = np.ones(256, dtype=bool)
        if not dotall:
            mask[ord("\n")] = False
        return cls(mask)

    @classmethod
    def from_sre_in(cls, items) -> "CharClass":
        """Build from an sre `IN` item list: LITERAL/RANGE/CATEGORY/NEGATE."""
        mask = np.zeros(256, dtype=bool)
        negate = False
        for op, av in items:
            if op is sre_c.NEGATE:
                negate = True
            elif op is sre_c.LITERAL:
                if av > 255:
                    raise ValueError("non-byte literal in class")
                mask[av] = True
            elif op is sre_c.RANGE:
                lo, hi = av
                if hi > 255:
                    raise ValueError("non-byte range in class")
                mask[lo : hi + 1] = True
            elif op is sre_c.CATEGORY:
                mask |= _category_mask(av)
            else:
                raise ValueError(f"unsupported class item {op}")
        if negate:
            mask = ~mask
        return cls(mask)

    @classmethod
    def from_category(cls, cat) -> "CharClass":
        return cls(_category_mask(cat))

    # -- ops ----------------------------------------------------------------

    def negated(self) -> "CharClass":
        return CharClass(~self.mask)

    def union(self, other: "CharClass") -> "CharClass":
        return CharClass(self.mask | other.mask)

    def intersects(self, other: "CharClass") -> bool:
        return bool((self.mask & other.mask).any())

    def issubset(self, other: "CharClass") -> bool:
        return bool((self.mask & ~other.mask).sum() == 0)

    def contains(self, byte: int) -> bool:
        return bool(self.mask[byte])

    def __eq__(self, other) -> bool:
        return isinstance(other, CharClass) and bool((self.mask == other.mask).all())

    def __hash__(self) -> int:
        return hash(self.mask.tobytes())

    def popcount(self) -> int:
        return int(self.mask.sum())

    def intervals(self) -> List[Tuple[int, int]]:
        """Minimal list of inclusive (lo, hi) byte intervals covering the set.

        Membership test in the kernel: OR over intervals of (b>=lo)&(b<=hi).
        If the complement has fewer intervals, the kernel may instead test the
        complement and negate (see kernel emission).
        """
        out: List[Tuple[int, int]] = []
        m = self.mask
        i = 0
        while i < 256:
            if m[i]:
                j = i
                while j + 1 < 256 and m[j + 1]:
                    j += 1
                out.append((i, j))
                i = j + 1
            i += 1
        return out

    def to_regex_fragment(self) -> str:
        """Debug/CPU-fallback representation like [\\x00-\\x1f...]."""
        parts = []
        for lo, hi in self.intervals():
            if lo == hi:
                parts.append(f"\\x{lo:02x}")
            else:
                parts.append(f"\\x{lo:02x}-\\x{hi:02x}")
        return "[" + "".join(parts) + "]"

    def __repr__(self) -> str:
        return f"CharClass({self.to_regex_fragment()})"
