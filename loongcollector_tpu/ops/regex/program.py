"""Regex → Tier-1 "segment program" compiler.

The reference parses each event with boost::regex full-match on a CPU thread
(core/plugin/processor/ProcessorParseRegexNative.cpp:186-253, RegexLogLineParser).
Log-parsing regexes are overwhelmingly *anchored sequences of character-class
runs separated by literal delimiters* — e.g. Apache/nginx access patterns,
grok expansions, delimiter formats.  Such patterns need no general automaton:
they compile to a **segment program** whose device execution is pure
vectorised arithmetic (interval compares, suffix scans, cursor gathers) over a
[batch, length] byte tensor — the TPU-idiomatic replacement for the per-event
NFA loop.

Tiers (SURVEY.md §7 step 4):
  Tier 1  segment program      → field_extract kernel (this module)
  Tier 2  general DFA (no captures, no backrefs/lookaround) → dfa_scan kernel
  Tier 3  anything else        → CPU fallback (Python `re`)

Semantics contract: FULL match of the event content (the reference uses
regex_match, i.e. anchored both ends), greedy quantifiers, captures as byte
(offset, length) spans.  The compiler REJECTS (raises Tier1Unsupported) any
pattern whose greedy semantics could require backtracking, so every accepted
program is exactly equivalent to the backtracking engine on all inputs —
enforced by differential tests (tests/test_regex_program.py).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple, Union

try:  # Python 3.11+
    from re import _constants as sre_c
    from re import _parser as sre_parse
except ImportError:  # pragma: no cover
    import sre_constants as sre_c
    import sre_parse

from .charclass import CharClass

MAXREPEAT = sre_c.MAXREPEAT
INF = 1 << 30


class Tier1Unsupported(Exception):
    """Pattern cannot be compiled to a backtracking-free segment program."""


class PatternTier(enum.IntEnum):
    SEGMENT = 1  # field_extract kernel
    DFA = 2      # dfa_scan kernel (match only)
    CPU = 3      # Python re fallback


# ---------------------------------------------------------------------------
# Program ops
# ---------------------------------------------------------------------------


@dataclass
class Lit:
    """Match a literal byte string at the cursor."""

    data: bytes


@dataclass
class Span:
    """Greedy run of `cls` bytes, min_len ≤ run ≤ max_len (max_len may be INF).

    Compiled only when maximal-munch is provably equivalent to backtracking
    semantics (the follow set is disjoint from `cls`), so the kernel can take
    the full run unconditionally.
    """

    class_id: int
    min_len: int
    max_len: int
    lazy: bool = False


@dataclass
class FixedSpan:
    """Exactly n bytes, all members of `cls` — validated via membership
    prefix-sums, so no disjointness requirement (e.g. `(\\d{4})(\\d{2})`)."""

    class_id: int
    n: int


@dataclass
class Optional_:
    """(?:...)?  — the body is evaluated in full (vectorised) and committed
    where it matches; rows where it fails skip the group.  This mirrors the
    greedy preference of the backtracking engine (take if takeable)."""

    body: List["Op"]


@dataclass
class Alt:
    """(a|b|c) — alternatives tried in order, committing to the first whose
    WHOLE branch matches at the cursor (leftmost-match).  Each branch must
    itself be backtracking-free w.r.t. the group's follow set."""

    branches: List[List["Op"]]


@dataclass
class CapStart:
    cap_id: int


@dataclass
class CapEnd:
    cap_id: int


Op = Union[Lit, Span, FixedSpan, "Optional_", "Alt", CapStart, CapEnd]


@dataclass
class SegmentProgram:
    pattern: str
    ops: List[Op] = field(default_factory=list)
    classes: List[CharClass] = field(default_factory=list)
    num_caps: int = 0
    group_names: Dict[int, str] = field(default_factory=dict)
    # bidirectional split (set when one ambiguous span pivots the pattern):
    # `ops` is then the forward PREFIX; the suffix executes right-to-left
    # from the line end; the pivot span covers whatever lies between the two
    # cursors (validated for membership/min/max via prefix sums).
    pivot: Optional["Span"] = None
    suffix_ops: Optional[List[Op]] = None      # stored pre-reversed
    split_caps: List[int] = field(default_factory=list)
    # double-pivot form (two ambiguous spans separated by a literal):
    # ops = prefix | pivot | mid_ops (one Lit + cap markers) | pivot2 |
    # suffix_ops. The boundary literal is located by a min- (both lazy) or
    # max-reduce (both greedy); soundness conditions in _try_double_pivot.
    pivot2: Optional["Span"] = None
    mid_ops: Optional[List[Op]] = None
    mid_end_caps: List[int] = field(default_factory=list)

    def class_id(self, cls: CharClass) -> int:
        for i, c in enumerate(self.classes):
            if c == cls:
                return i
        self.classes.append(cls)
        return len(self.classes) - 1

    # which classes need which auxiliary scans (kernel planning)
    def scan_requirements(self) -> Tuple[set, set]:
        """Returns (next_non_classes, cumsum_classes)."""
        next_non, cumsum = set(), set()

        def walk(ops):
            for op in ops:
                if isinstance(op, Span):
                    next_non.add(op.class_id)
                elif isinstance(op, FixedSpan):
                    cumsum.add(op.class_id)
                elif isinstance(op, Optional_):
                    walk(op.body)
                elif isinstance(op, Alt):
                    for b in op.branches:
                        walk(b)
        walk(self.ops)
        if self.suffix_ops is not None:
            walk(self.suffix_ops)
        if self.mid_ops is not None:
            walk(self.mid_ops)
        if self.pivot is not None:
            cumsum.add(self.pivot.class_id)
        if self.pivot2 is not None:
            cumsum.add(self.pivot2.class_id)
        return next_non, cumsum

    def max_reach(self) -> int:
        """Minimum event length that could possibly match (for bucketing)."""
        n = 0
        for op in self.ops:
            if isinstance(op, Lit):
                n += len(op.data)
            elif isinstance(op, (Span,)):
                n += op.min_len
            elif isinstance(op, FixedSpan):
                n += op.n
        return n


# ---------------------------------------------------------------------------
# sre AST → flat item list
# ---------------------------------------------------------------------------


def _flatten(tokens, prog: SegmentProgram, ops: List[Op]) -> None:
    """Recursively translate an sre token sequence into ops (no validation of
    backtracking-freedom yet — that's the second pass)."""
    pending_lit = bytearray()

    def flush_lit():
        if pending_lit:
            ops.append(Lit(bytes(pending_lit)))
            pending_lit.clear()

    for tok_op, av in tokens:
        if tok_op is sre_c.LITERAL:
            if av > 255:
                raise Tier1Unsupported("non-byte literal")
            pending_lit.append(av)
        elif tok_op is sre_c.NOT_LITERAL:
            flush_lit()
            cid = prog.class_id(CharClass.single(av).negated())
            ops.append(FixedSpan(cid, 1))
        elif tok_op is sre_c.IN:
            flush_lit()
            cid = prog.class_id(CharClass.from_sre_in(av))
            ops.append(FixedSpan(cid, 1))
        elif tok_op is sre_c.ANY:
            flush_lit()
            cid = prog.class_id(CharClass.dot())
            ops.append(FixedSpan(cid, 1))
        elif tok_op is sre_c.CATEGORY:
            flush_lit()
            cid = prog.class_id(CharClass.from_category(av))
            ops.append(FixedSpan(cid, 1))
        elif tok_op in (sre_c.MAX_REPEAT, sre_c.MIN_REPEAT):
            flush_lit()
            lo, hi, sub = av
            hi = INF if hi is MAXREPEAT else int(hi)
            lo = int(lo)
            cls = _single_class(sub)
            if cls is None:
                if lo == 0 and hi == 1:
                    body: List[Op] = []
                    _flatten(sub, prog, body)
                    ops.append(Optional_(body))
                    continue
                if hi != INF and lo <= 8 and hi - lo <= 8:
                    # counted repeat of a group: lo mandatory copies, then
                    # nested optionals (greedy: outer optional contains the
                    # next, preferring more copies)
                    for _ in range(lo):
                        _flatten(sub, prog, ops)
                    tail: List[Op] = []
                    for _ in range(hi - lo):
                        body2: List[Op] = []
                        _flatten(sub, prog, body2)
                        body2.extend(tail)
                        tail = [Optional_(body2)]
                    ops.extend(tail)
                    continue
                raise Tier1Unsupported("repeat of non-class subpattern")
            cid = prog.class_id(cls)
            if lo == hi:
                ops.append(FixedSpan(cid, lo))
            else:
                # Lazy repeats compile identically to greedy ones on the
                # strict path (the run is forced when the class is disjoint
                # from the follow set); laziness matters only when the span
                # becomes a bidirectional pivot.
                ops.append(Span(cid, lo, hi,
                               lazy=tok_op is sre_c.MIN_REPEAT))
        elif tok_op is sre_c.SUBPATTERN:
            flush_lit()
            group, add_flags, del_flags, sub = av
            if add_flags or del_flags:
                raise Tier1Unsupported("inline flags")
            if group is not None:
                cap = group - 1
                prog.num_caps = max(prog.num_caps, group)
                ops.append(CapStart(cap))
                _flatten(sub, prog, ops)
                ops.append(CapEnd(cap))
            else:
                _flatten(sub, prog, ops)
        elif tok_op is sre_c.AT:
            # Edge anchors are stripped at top level by compile_tier1 before
            # flattening; any AT surviving to here (interior ^/$, \b, \B)
            # has position-dependent semantics the segment walk can't model.
            raise Tier1Unsupported(f"assertion {av}")
        elif tok_op is sre_c.BRANCH:
            flush_lit()
            _, alts = av
            branches: List[List[Op]] = []
            for alt in alts:
                b: List[Op] = []
                _flatten(list(alt), prog, b)
                branches.append(b)
            ops.append(Alt(branches))
        else:
            raise Tier1Unsupported(f"op {tok_op}")
    flush_lit()


def _single_class(sub) -> Optional[CharClass]:
    """If an sre subpattern is a single char-class-like token, return it."""
    toks = list(sub)
    if len(toks) != 1:
        return None
    tok_op, av = toks[0]
    if tok_op is sre_c.LITERAL:
        return CharClass.single(av)
    if tok_op is sre_c.NOT_LITERAL:
        return CharClass.single(av).negated()
    if tok_op is sre_c.IN:
        return CharClass.from_sre_in(av)
    if tok_op is sre_c.ANY:
        return CharClass.dot()
    return None


# ---------------------------------------------------------------------------
# Validation: maximal munch ≡ backtracking
# ---------------------------------------------------------------------------


def _first_set(ops: Sequence[Op], i: int, prog: SegmentProgram) -> Tuple[CharClass, bool]:
    """Set of bytes that can begin the match of ops[i:]; bool = 'can be empty'
    (end of pattern reachable without consuming)."""
    mask = CharClass.from_bytes(b"")
    j = i
    while j < len(ops):
        op = ops[j]
        if isinstance(op, (CapStart, CapEnd)):
            j += 1
            continue
        if isinstance(op, Lit):
            return mask.union(CharClass.single(op.data[0])), False
        if isinstance(op, FixedSpan):
            if op.n == 0:
                j += 1
                continue
            return mask.union(prog.classes[op.class_id]), False
        if isinstance(op, Span):
            mask = mask.union(prog.classes[op.class_id])
            if op.min_len > 0:
                return mask, False
            j += 1
            continue
        if isinstance(op, Optional_):
            sub, _ = _first_set(op.body, 0, prog)
            mask = mask.union(sub)
            j += 1
            continue
        if isinstance(op, Alt):
            can_empty = False
            for b in op.branches:
                sub, e = _first_set(b, 0, prog)
                mask = mask.union(sub)
                can_empty = can_empty or e
            if not can_empty:
                return mask, False
            j += 1
            continue
        raise AssertionError(op)
    return mask, True


def _fixed_len(ops: Sequence[Op]) -> Optional[int]:
    """Total consumed length if statically fixed, else None."""
    total = 0
    for op in ops:
        if isinstance(op, (CapStart, CapEnd)):
            continue
        if isinstance(op, Lit):
            total += len(op.data)
        elif isinstance(op, FixedSpan):
            total += op.n
        elif isinstance(op, Span):
            if op.min_len != op.max_len:
                return None
            total += op.min_len
        elif isinstance(op, Alt):
            lens = [_fixed_len(b) for b in op.branches]
            if any(l is None for l in lens) or len(set(lens)) != 1:
                return None
            total += lens[0]
        else:  # Optional_ is never fixed
            return None
    return total


def _follow_of(ops: Sequence[Op], i: int, prog: SegmentProgram,
               outer: CharClass) -> CharClass:
    """First set of what can follow ops[i] (the rest of this sequence, or the
    outer follow when the tail can match empty)."""
    mask, can_empty = _first_set(ops, i + 1, prog)
    if can_empty:
        mask = mask.union(outer)
    return mask


def _guaranteed_nonabsorber(ops: Sequence[Op], prog: SegmentProgram,
                            absorber: CharClass) -> bool:
    """True if EVERY possible match of ops must contain at least one byte
    the absorber (pivot) class cannot consume — then the pivot can never
    swallow this content and take/skip decisions are forced."""
    for op in ops:
        if isinstance(op, Lit):
            if any(not absorber.contains(b) for b in op.data):
                return True
        elif isinstance(op, FixedSpan):
            if op.n >= 1 and not prog.classes[op.class_id].intersects(absorber):
                return True
        elif isinstance(op, Span):
            if op.min_len >= 1 and                     not prog.classes[op.class_id].intersects(absorber):
                return True
        elif isinstance(op, Alt):
            if all(_guaranteed_nonabsorber(b, prog, absorber)
                   for b in op.branches):
                return True
        # Optional_ is not mandatory; CapStart/End consume nothing
    return False


def _validate_ops(ops: Sequence[Op], prog: SegmentProgram,
                  outer_follow: CharClass,
                  absorber: "Optional[CharClass]" = None,
                  pivot_lazy: bool = False) -> None:
    """Backtracking-equivalence validation.  In bidirectional (reverse
    suffix) mode, `absorber` is the pivot span's class: content the pivot
    could alternatively consume.  Boundary-shifting ambiguity against the
    absorber is allowed only when the pivot is lazy (reverse maximal munch
    IS the lazy answer) or the content is guaranteed non-absorbable."""
    for i, op in enumerate(ops):
        if isinstance(op, Span):
            # maximal munch (plus the {m,n} length check) is equivalent to
            # backtracking only when the follow set is disjoint from the class
            follow_inner, reaches_end = _first_set(ops, i + 1, prog)
            cls = prog.classes[op.class_id]
            if cls.intersects(follow_inner):
                raise Tier1Unsupported(
                    f"greedy class {cls} overlaps follow set {follow_inner}")
            if reaches_end:
                # outer_follow is the enclosing continuation (nested Alt
                # branches still have one in absorber mode)
                if cls.intersects(outer_follow):
                    raise Tier1Unsupported(
                        f"greedy class {cls} overlaps follow set "
                        f"{outer_follow}")
                if absorber is not None and cls.intersects(absorber) \
                        and not pivot_lazy:
                    raise Tier1Unsupported(
                        "suffix span can trade bytes with a greedy pivot")
        elif isinstance(op, Optional_):
            follow = _follow_of(ops, i, prog, outer_follow)
            first, can_empty = _first_set(op.body, 0, prog)
            if can_empty:
                raise Tier1Unsupported("optional group can match empty")
            # greedy take/skip commits on body success; that equals
            # backtracking only when the body can never "absorb" what the
            # continuation needs — first(body) must not overlap follow
            # (counterexample otherwise: (?:ab)?abc on "abc")
            if first.intersects(follow):
                raise Tier1Unsupported(
                    "optional body first set overlaps follow set")
            # reverse-suffix mode: a greedy pivot prefers to absorb the
            # body's text (skipping the optional); taking-on-body-match is
            # only re-equivalent when the body is guaranteed to contain a
            # byte the pivot cannot consume, or the pivot is lazy
            if absorber is not None and not pivot_lazy and \
                    not _guaranteed_nonabsorber(op.body, prog, absorber):
                raise Tier1Unsupported(
                    "optional body could be absorbed by a greedy pivot")
            _validate_ops(op.body, prog, follow, absorber, pivot_lazy)
        elif isinstance(op, Alt):
            follow_inner, reaches_end = _first_set(ops, i + 1, prog)
            follow = (follow_inner.union(outer_follow) if reaches_end
                      else follow_inner)
            firsts = []
            flens = []
            empties = []
            for bi, b in enumerate(op.branches):
                _validate_ops(b, prog, follow, absorber, pivot_lazy)
                f, can_empty = _first_set(b, 0, prog)
                # commit-on-branch-success prefers earlier branches; an
                # empty-matchable branch always succeeds, so anywhere but
                # LAST it would shadow later branches the backtracking
                # engine could still reach (sre factors "GET|GETX" into
                # GET(?:|X) — empty-first — which must be rejected)
                if can_empty and bi != len(op.branches) - 1:
                    raise Tier1Unsupported(
                        "empty-matchable alternation branch before the last")
                firsts.append(f)
                flens.append(_fixed_len(b))
                empties.append(can_empty)
            # commit equals leftmost-with-backtracking only when, for every
            # branch pair, either at most one branch can apply (disjoint
            # first sets) or both consume the same fixed length (identical
            # continuation, so a continuation failure fails under both).
            # Counterexample otherwise: HOUR (2[0-3]|[0-9]) on "230"
            # followed by MINUTE.
            n_br = len(op.branches)
            lits = [b[0].data if len(b) == 1 and isinstance(b[0], Lit)
                    else None for b in op.branches]
            for a in range(n_br):
                for b2 in range(a + 1, n_br):
                    if empties[a] or empties[b2]:
                        continue  # empty last branch handled below
                    if lits[a] is not None and lits[b2] is not None:
                        # distinct literals: local matches are mutually
                        # exclusive unless one prefixes the other — and the
                        # dangerous ordering is shorter-prefix-first (re
                        # would backtrack into the longer: "GET|GETX")
                        if lits[b2].startswith(lits[a]) and lits[a] != lits[b2]:
                            raise Tier1Unsupported(
                                "alternation literal is a prefix of a later "
                                "branch (reorder longest-first)")
                        if lits[a].startswith(lits[b2]) and lits[a] != lits[b2]:
                            # longer-first (the normalized order): commit on
                            # the longer branch equals backtracking ONLY if
                            # the continuation can never consume the
                            # extension — counterexample: (WARNING|WARN)ING
                            ext_first = lits[a][len(lits[b2])]
                            if follow.contains(ext_first):
                                raise Tier1Unsupported(
                                    "literal prefix pair: follow set can "
                                    "consume the longer branch's extension")
                        if (absorber is not None and not pivot_lazy
                                and len(lits[a]) != len(lits[b2])
                                and not (_guaranteed_nonabsorber(
                                    [Lit(lits[a])], prog, absorber)
                                    and _guaranteed_nonabsorber(
                                        [Lit(lits[b2])], prog, absorber))):
                            raise Tier1Unsupported(
                                "unequal literal branches could trade bytes "
                                "with a greedy pivot")
                        continue
                    if firsts[a].intersects(firsts[b2]) and (
                            flens[a] is None or flens[a] != flens[b2]):
                        raise Tier1Unsupported(
                            "ambiguous alternation branches (overlapping "
                            "first sets, unequal lengths)")
            # an empty-matchable LAST branch makes the Alt optional-like:
            # the other branches must not absorb the continuation
            if empties and empties[-1]:
                union = CharClass.from_bytes(b"")
                for f, e in zip(firsts, empties):
                    if not e:
                        union = union.union(f)
                if union.intersects(follow):
                    raise Tier1Unsupported(
                        "alternation with empty branch overlaps follow set")
                if absorber is not None and not pivot_lazy:
                    for b, e in zip(op.branches, empties):
                        if not e and not _guaranteed_nonabsorber(b, prog,
                                                                 absorber):
                            raise Tier1Unsupported(
                                "optional-like branch could be absorbed by "
                                "a greedy pivot")


def _normalize_alts(ops: Sequence[Op]) -> None:
    """All-literal alternations with prefix pairs reorder LONGEST-FIRST
    (in place, recursive). For `re` this is match-equivalent — backtracking
    explores every branch and the continuation disambiguates — and it is
    the order the commit emitter needs (WARN before WARNING would shadow
    WARNING forever). Soundness of the commit itself is still checked by
    the follow-set guard in _validate_ops."""
    for op in ops:
        if isinstance(op, Optional_):
            _normalize_alts(op.body)
        elif isinstance(op, Alt):
            for b in op.branches:
                _normalize_alts(b)
            lits = [b[0].data if len(b) == 1 and isinstance(b[0], Lit)
                    else None for b in op.branches]
            if all(l is not None for l in lits):
                has_prefix_pair = any(
                    a != b and (a.startswith(b) or b.startswith(a))
                    for i, a in enumerate(lits) for b in lits[i + 1:])
                if has_prefix_pair:
                    op.branches.sort(key=lambda br: -len(br[0].data))


def _validate_and_bind(prog: SegmentProgram) -> None:
    _normalize_alts(prog.ops)
    _validate_ops(prog.ops, prog, CharClass.from_bytes(b""))


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


def _strip_edge_anchors(tokens):
    """Remove a leading ^ and trailing $ (redundant under full-match
    semantics).  Interior/boundary assertions are rejected in _flatten."""
    at_begin = (sre_c.AT_BEGINNING, sre_c.AT_BEGINNING_STRING)
    at_end = (sre_c.AT_END, sre_c.AT_END_STRING)
    while tokens and tokens[0][0] is sre_c.AT and tokens[0][1] in at_begin:
        tokens = tokens[1:]
    while tokens and tokens[-1][0] is sre_c.AT and tokens[-1][1] in at_end:
        tokens = tokens[:-1]
    return tokens


def _reverse_ops(ops: Sequence[Op]) -> List[Op]:
    """Mirror an op sequence for right-to-left execution.  Literal bytes
    reverse; composites reverse their bodies; CapStart/CapEnd swap roles is
    handled by the emitter (original CapEnd, encountered first in reverse,
    records the group's right edge)."""
    out: List[Op] = []
    for op in reversed(list(ops)):
        if isinstance(op, Lit):
            out.append(Lit(op.data[::-1]))
        elif isinstance(op, Optional_):
            out.append(Optional_(_reverse_ops(op.body)))
        elif isinstance(op, Alt):
            out.append(Alt([_reverse_ops(b) for b in op.branches]))
        else:
            out.append(op)
    return out


def _try_pivot_split(prog: SegmentProgram) -> bool:
    """Attempt the bidirectional rescue for a pattern that failed strict
    validation: exactly one top-level ambiguous Span becomes the pivot; the
    prefix must validate forward, the suffix (reversed, anchored at the line
    end) must validate in reverse.  Covers `"(.*?)"`-style fields.

    The suffix match is then UNIQUE (its reversed form is backtracking-
    free), so both greedy and lazy pivots take the same span — equal to the
    backtracking engine's answer."""
    ops = prog.ops
    for i, op in enumerate(ops):
        if not isinstance(op, Span):
            continue
        prefix = ops[:i]
        suffix = ops[i + 1 :]
        if not suffix:
            continue  # span-at-end is the strict path's job
        # follow of the prefix = pivot class (∪ first(suffix) if pivot may
        # be empty)
        follow = prog.classes[op.class_id]
        if op.min_len == 0:
            sf, _ = _first_set(suffix, 0, prog)
            follow = follow.union(sf)
        rev = _reverse_ops(suffix)
        try:
            _validate_ops(prefix, prog, follow)
            _validate_ops(rev, prog, CharClass.from_bytes(b""),
                          absorber=prog.classes[op.class_id],
                          pivot_lazy=op.lazy)
        except Tier1Unsupported:
            continue
        # captures spanning the split: CapStart in prefix whose CapEnd sits
        # in the suffix
        starts_prefix = _cap_ids(prefix, CapStart)
        ends_suffix = _cap_ids(suffix, CapEnd)
        split = sorted(starts_prefix & ends_suffix)
        # a capture OPENING in the suffix but closing... cannot happen
        # (well-formed nesting), and captures fully inside either side are
        # handled by their own walk
        prog.ops = prefix
        prog.pivot = op
        prog.suffix_ops = rev
        prog.split_caps = split
        return True
    return False


def _cap_ids(seq, cls) -> set:
    found = set()

    def walk(oo):
        for o in oo:
            if isinstance(o, cls):
                found.add(o.cap_id)
            elif isinstance(o, Optional_):
                walk(o.body)
            elif isinstance(o, Alt):
                for b in o.branches:
                    walk(b)
    walk(seq)
    return found


def _try_double_pivot(prog: SegmentProgram) -> bool:
    """Two ambiguous spans separated by a boundary literal — the common
    `%{DATA}` × 2 grok shape (processor_grok.go:55-56 semantics).

    Structure: prefix | pivot1 | middle | pivot2 | suffix, where middle is
    ONE literal L (plus capture markers). The kernel walks prefix forward,
    suffix in reverse, then locates L inside the gap with a min-reduce
    (both pivots lazy → first feasible occurrence) or max-reduce (both
    greedy → last), and validates both pivot regions by masked counts.

    Commit-to-first is equivalent to the backtracking engine iff a failure
    of the chosen occurrence implies failure of every later one. That holds
    when any byte pivot2 cannot absorb also cannot be re-assigned to a
    later boundary's pivot1 region or L match:
        class(pivot1) ⊆ class(pivot2)  and  bytes(L) ⊆ class(pivot2).
    Commit-to-last (greedy) mirrors:  class2 ⊆ class1 and bytes(L) ⊆ class1.
    Unbounded maxima are required — a max-length bound could force the
    engine to a different occurrence the reduce would skip."""
    ops = prog.ops
    span_idx = [k for k, op in enumerate(ops) if isinstance(op, Span)]
    for ii in range(len(span_idx)):
        for jj in range(ii + 1, len(span_idx)):
            i, j = span_idx[ii], span_idx[jj]
            p1, p2 = ops[i], ops[j]
            middle = ops[i + 1:j]
            lits = [o for o in middle if isinstance(o, Lit)]
            if len(lits) != 1 or not all(
                    isinstance(o, (Lit, CapStart, CapEnd)) for o in middle):
                continue
            lit = lits[0]
            c1 = prog.classes[p1.class_id]
            c2 = prog.classes[p2.class_id]
            if p1.max_len != INF or p2.max_len != INF:
                continue
            if p1.lazy and p2.lazy:
                if not (c1.issubset(c2)
                        and all(c2.contains(b) for b in lit.data)):
                    continue
            elif not p1.lazy and not p2.lazy:
                if not (c2.issubset(c1)
                        and all(c1.contains(b) for b in lit.data)):
                    continue
            else:
                continue  # mixed greedy/lazy: no sound commit order
            prefix = ops[:i]
            suffix = ops[j + 1:]
            if not suffix:
                continue  # pivot2-at-end belongs to the single-pivot path
            follow1 = c1
            if p1.min_len == 0:
                follow1 = follow1.union(CharClass.from_bytes(lit.data[:1]))
            rev = _reverse_ops(suffix)
            try:
                _validate_ops(prefix, prog, follow1)
                _validate_ops(rev, prog, CharClass.from_bytes(b""),
                              absorber=c2, pivot_lazy=p2.lazy)
            except Tier1Unsupported:
                continue
            starts_fwd = _cap_ids(prefix, CapStart) | _cap_ids(middle,
                                                               CapStart)
            ends_suffix = _cap_ids(suffix, CapEnd)
            prog.ops = prefix
            prog.pivot = p1
            prog.mid_ops = list(middle)
            prog.mid_end_caps = sorted(_cap_ids(middle, CapEnd))
            prog.pivot2 = p2
            prog.suffix_ops = rev
            prog.split_caps = sorted(starts_fwd & ends_suffix)
            return True
    return False


def compile_tier1(pattern: Union[str, bytes]) -> SegmentProgram:
    if isinstance(pattern, bytes):
        pattern = pattern.decode("latin-1")
    try:
        tree = sre_parse.parse(pattern)
    except Exception as e:  # noqa: BLE001
        raise Tier1Unsupported(f"parse error: {e}") from e
    prog = SegmentProgram(pattern=pattern)
    try:
        names = tree.state.groupdict
        prog.group_names = {v - 1: k for k, v in names.items()}
    except AttributeError:
        pass
    tokens = _strip_edge_anchors(list(tree))
    _flatten(tokens, prog, prog.ops)
    try:
        _validate_and_bind(prog)
    except Tier1Unsupported:
        if not _try_pivot_split(prog) and not _try_double_pivot(prog):
            raise
    return prog


def classify_pattern(pattern: Union[str, bytes]) -> PatternTier:
    try:
        compile_tier1(pattern)
        return PatternTier.SEGMENT
    except Tier1Unsupported:
        pass
    from .dfa import compile_dfa, DFAUnsupported
    try:
        compile_dfa(pattern)
        return PatternTier.DFA
    except DFAUnsupported:
        return PatternTier.CPU
