"""Regex → Tier-1 "segment program" compiler.

The reference parses each event with boost::regex full-match on a CPU thread
(core/plugin/processor/ProcessorParseRegexNative.cpp:186-253, RegexLogLineParser).
Log-parsing regexes are overwhelmingly *anchored sequences of character-class
runs separated by literal delimiters* — e.g. Apache/nginx access patterns,
grok expansions, delimiter formats.  Such patterns need no general automaton:
they compile to a **segment program** whose device execution is pure
vectorised arithmetic (interval compares, suffix scans, cursor gathers) over a
[batch, length] byte tensor — the TPU-idiomatic replacement for the per-event
NFA loop.

Tiers (SURVEY.md §7 step 4):
  Tier 1  segment program      → field_extract kernel (this module)
  Tier 2  general DFA (no captures, no backrefs/lookaround) → dfa_scan kernel
  Tier 3  anything else        → CPU fallback (Python `re`)

Semantics contract: FULL match of the event content (the reference uses
regex_match, i.e. anchored both ends), greedy quantifiers, captures as byte
(offset, length) spans.  The compiler REJECTS (raises Tier1Unsupported) any
pattern whose greedy semantics could require backtracking, so every accepted
program is exactly equivalent to the backtracking engine on all inputs —
enforced by differential tests (tests/test_regex_program.py).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple, Union

try:  # Python 3.11+
    from re import _constants as sre_c
    from re import _parser as sre_parse
except ImportError:  # pragma: no cover
    import sre_constants as sre_c
    import sre_parse

from .charclass import CharClass

MAXREPEAT = sre_c.MAXREPEAT
INF = 1 << 30


class Tier1Unsupported(Exception):
    """Pattern cannot be compiled to a backtracking-free segment program."""


class PatternTier(enum.IntEnum):
    SEGMENT = 1  # field_extract kernel
    DFA = 2      # dfa_scan kernel (match only)
    CPU = 3      # Python re fallback


# ---------------------------------------------------------------------------
# Program ops
# ---------------------------------------------------------------------------


@dataclass
class Lit:
    """Match a literal byte string at the cursor."""

    data: bytes


@dataclass
class Span:
    """Greedy run of `cls` bytes, min_len ≤ run ≤ max_len (max_len may be INF).

    Compiled only when maximal-munch is provably equivalent to backtracking
    semantics (the follow set is disjoint from `cls`), so the kernel can take
    the full run unconditionally.
    """

    class_id: int
    min_len: int
    max_len: int


@dataclass
class FixedSpan:
    """Exactly n bytes, all members of `cls` — validated via membership
    prefix-sums, so no disjointness requirement (e.g. `(\\d{4})(\\d{2})`)."""

    class_id: int
    n: int


@dataclass
class Optional_:
    """(?:...)?  — the body is evaluated in full (vectorised) and committed
    where it matches; rows where it fails skip the group.  This mirrors the
    greedy preference of the backtracking engine (take if takeable)."""

    body: List["Op"]


@dataclass
class Alt:
    """(a|b|c) — alternatives tried in order, committing to the first whose
    WHOLE branch matches at the cursor (leftmost-match).  Each branch must
    itself be backtracking-free w.r.t. the group's follow set."""

    branches: List[List["Op"]]


@dataclass
class CapStart:
    cap_id: int


@dataclass
class CapEnd:
    cap_id: int


Op = Union[Lit, Span, FixedSpan, "Optional_", "Alt", CapStart, CapEnd]


@dataclass
class SegmentProgram:
    pattern: str
    ops: List[Op] = field(default_factory=list)
    classes: List[CharClass] = field(default_factory=list)
    num_caps: int = 0
    group_names: Dict[int, str] = field(default_factory=dict)

    def class_id(self, cls: CharClass) -> int:
        for i, c in enumerate(self.classes):
            if c == cls:
                return i
        self.classes.append(cls)
        return len(self.classes) - 1

    # which classes need which auxiliary scans (kernel planning)
    def scan_requirements(self) -> Tuple[set, set]:
        """Returns (next_non_classes, cumsum_classes)."""
        next_non, cumsum = set(), set()

        def walk(ops):
            for op in ops:
                if isinstance(op, Span):
                    next_non.add(op.class_id)
                elif isinstance(op, FixedSpan):
                    cumsum.add(op.class_id)
                elif isinstance(op, Optional_):
                    walk(op.body)
                elif isinstance(op, Alt):
                    for b in op.branches:
                        walk(b)
        walk(self.ops)
        return next_non, cumsum

    def max_reach(self) -> int:
        """Minimum event length that could possibly match (for bucketing)."""
        n = 0
        for op in self.ops:
            if isinstance(op, Lit):
                n += len(op.data)
            elif isinstance(op, (Span,)):
                n += op.min_len
            elif isinstance(op, FixedSpan):
                n += op.n
        return n


# ---------------------------------------------------------------------------
# sre AST → flat item list
# ---------------------------------------------------------------------------


def _flatten(tokens, prog: SegmentProgram, ops: List[Op]) -> None:
    """Recursively translate an sre token sequence into ops (no validation of
    backtracking-freedom yet — that's the second pass)."""
    pending_lit = bytearray()

    def flush_lit():
        if pending_lit:
            ops.append(Lit(bytes(pending_lit)))
            pending_lit.clear()

    for tok_op, av in tokens:
        if tok_op is sre_c.LITERAL:
            if av > 255:
                raise Tier1Unsupported("non-byte literal")
            pending_lit.append(av)
        elif tok_op is sre_c.NOT_LITERAL:
            flush_lit()
            cid = prog.class_id(CharClass.single(av).negated())
            ops.append(FixedSpan(cid, 1))
        elif tok_op is sre_c.IN:
            flush_lit()
            cid = prog.class_id(CharClass.from_sre_in(av))
            ops.append(FixedSpan(cid, 1))
        elif tok_op is sre_c.ANY:
            flush_lit()
            cid = prog.class_id(CharClass.dot())
            ops.append(FixedSpan(cid, 1))
        elif tok_op is sre_c.CATEGORY:
            flush_lit()
            cid = prog.class_id(CharClass.from_category(av))
            ops.append(FixedSpan(cid, 1))
        elif tok_op in (sre_c.MAX_REPEAT, sre_c.MIN_REPEAT):
            flush_lit()
            lo, hi, sub = av
            hi = INF if hi is MAXREPEAT else int(hi)
            lo = int(lo)
            cls = _single_class(sub)
            if cls is None:
                if lo == 0 and hi == 1:
                    body: List[Op] = []
                    _flatten(sub, prog, body)
                    ops.append(Optional_(body))
                    continue
                if hi != INF and lo <= 8 and hi - lo <= 8:
                    # counted repeat of a group: lo mandatory copies, then
                    # nested optionals (greedy: outer optional contains the
                    # next, preferring more copies)
                    for _ in range(lo):
                        _flatten(sub, prog, ops)
                    tail: List[Op] = []
                    for _ in range(hi - lo):
                        body2: List[Op] = []
                        _flatten(sub, prog, body2)
                        body2.extend(tail)
                        tail = [Optional_(body2)]
                    ops.extend(tail)
                    continue
                raise Tier1Unsupported("repeat of non-class subpattern")
            cid = prog.class_id(cls)
            if lo == hi:
                ops.append(FixedSpan(cid, lo))
            else:
                # Lazy repeats compile identically to greedy ones: both are
                # only accepted when the class is disjoint from the follow
                # set, in which case the run is forced and lazy ≡ greedy.
                ops.append(Span(cid, lo, hi))
        elif tok_op is sre_c.SUBPATTERN:
            flush_lit()
            group, add_flags, del_flags, sub = av
            if add_flags or del_flags:
                raise Tier1Unsupported("inline flags")
            if group is not None:
                cap = group - 1
                prog.num_caps = max(prog.num_caps, group)
                ops.append(CapStart(cap))
                _flatten(sub, prog, ops)
                ops.append(CapEnd(cap))
            else:
                _flatten(sub, prog, ops)
        elif tok_op is sre_c.AT:
            # Edge anchors are stripped at top level by compile_tier1 before
            # flattening; any AT surviving to here (interior ^/$, \b, \B)
            # has position-dependent semantics the segment walk can't model.
            raise Tier1Unsupported(f"assertion {av}")
        elif tok_op is sre_c.BRANCH:
            flush_lit()
            _, alts = av
            branches: List[List[Op]] = []
            for alt in alts:
                b: List[Op] = []
                _flatten(list(alt), prog, b)
                branches.append(b)
            ops.append(Alt(branches))
        else:
            raise Tier1Unsupported(f"op {tok_op}")
    flush_lit()


def _single_class(sub) -> Optional[CharClass]:
    """If an sre subpattern is a single char-class-like token, return it."""
    toks = list(sub)
    if len(toks) != 1:
        return None
    tok_op, av = toks[0]
    if tok_op is sre_c.LITERAL:
        return CharClass.single(av)
    if tok_op is sre_c.NOT_LITERAL:
        return CharClass.single(av).negated()
    if tok_op is sre_c.IN:
        return CharClass.from_sre_in(av)
    if tok_op is sre_c.ANY:
        return CharClass.dot()
    return None


# ---------------------------------------------------------------------------
# Validation: maximal munch ≡ backtracking
# ---------------------------------------------------------------------------


def _first_set(ops: Sequence[Op], i: int, prog: SegmentProgram) -> Tuple[CharClass, bool]:
    """Set of bytes that can begin the match of ops[i:]; bool = 'can be empty'
    (end of pattern reachable without consuming)."""
    mask = CharClass.from_bytes(b"")
    j = i
    while j < len(ops):
        op = ops[j]
        if isinstance(op, (CapStart, CapEnd)):
            j += 1
            continue
        if isinstance(op, Lit):
            return mask.union(CharClass.single(op.data[0])), False
        if isinstance(op, FixedSpan):
            if op.n == 0:
                j += 1
                continue
            return mask.union(prog.classes[op.class_id]), False
        if isinstance(op, Span):
            mask = mask.union(prog.classes[op.class_id])
            if op.min_len > 0:
                return mask, False
            j += 1
            continue
        if isinstance(op, Optional_):
            sub, _ = _first_set(op.body, 0, prog)
            mask = mask.union(sub)
            j += 1
            continue
        if isinstance(op, Alt):
            can_empty = False
            for b in op.branches:
                sub, e = _first_set(b, 0, prog)
                mask = mask.union(sub)
                can_empty = can_empty or e
            if not can_empty:
                return mask, False
            j += 1
            continue
        raise AssertionError(op)
    return mask, True


def _fixed_len(ops: Sequence[Op]) -> Optional[int]:
    """Total consumed length if statically fixed, else None."""
    total = 0
    for op in ops:
        if isinstance(op, (CapStart, CapEnd)):
            continue
        if isinstance(op, Lit):
            total += len(op.data)
        elif isinstance(op, FixedSpan):
            total += op.n
        elif isinstance(op, Span):
            if op.min_len != op.max_len:
                return None
            total += op.min_len
        elif isinstance(op, Alt):
            lens = [_fixed_len(b) for b in op.branches]
            if any(l is None for l in lens) or len(set(lens)) != 1:
                return None
            total += lens[0]
        else:  # Optional_ is never fixed
            return None
    return total


def _follow_of(ops: Sequence[Op], i: int, prog: SegmentProgram,
               outer: CharClass) -> CharClass:
    """First set of what can follow ops[i] (the rest of this sequence, or the
    outer follow when the tail can match empty)."""
    mask, can_empty = _first_set(ops, i + 1, prog)
    if can_empty:
        mask = mask.union(outer)
    return mask


def _validate_ops(ops: Sequence[Op], prog: SegmentProgram,
                  outer_follow: CharClass) -> None:
    for i, op in enumerate(ops):
        if isinstance(op, Span):
            # maximal munch (plus the {m,n} length check) is equivalent to
            # backtracking only when the follow set is disjoint from the class
            follow = _follow_of(ops, i, prog, outer_follow)
            cls = prog.classes[op.class_id]
            if cls.intersects(follow):
                raise Tier1Unsupported(
                    f"greedy class {cls} overlaps follow set {follow}")
        elif isinstance(op, Optional_):
            follow = _follow_of(ops, i, prog, outer_follow)
            first, can_empty = _first_set(op.body, 0, prog)
            if can_empty:
                raise Tier1Unsupported("optional group can match empty")
            # greedy take/skip commits on body success; that equals
            # backtracking only when the body can never "absorb" what the
            # continuation needs — first(body) must not overlap follow
            # (counterexample otherwise: (?:ab)?abc on "abc")
            if first.intersects(follow):
                raise Tier1Unsupported(
                    "optional body first set overlaps follow set")
            _validate_ops(op.body, prog, follow)
        elif isinstance(op, Alt):
            follow = _follow_of(ops, i, prog, outer_follow)
            firsts = []
            flens = []
            empties = []
            for bi, b in enumerate(op.branches):
                _validate_ops(b, prog, follow)
                f, can_empty = _first_set(b, 0, prog)
                # commit-on-branch-success prefers earlier branches; an
                # empty-matchable branch always succeeds, so anywhere but
                # LAST it would shadow later branches the backtracking
                # engine could still reach (sre factors "GET|GETX" into
                # GET(?:|X) — empty-first — which must be rejected)
                if can_empty and bi != len(op.branches) - 1:
                    raise Tier1Unsupported(
                        "empty-matchable alternation branch before the last")
                firsts.append(f)
                flens.append(_fixed_len(b))
                empties.append(can_empty)
            # commit equals leftmost-with-backtracking only when, for every
            # branch pair, either at most one branch can apply (disjoint
            # first sets) or both consume the same fixed length (identical
            # continuation, so a continuation failure fails under both).
            # Counterexample otherwise: HOUR (2[0-3]|[0-9]) on "230"
            # followed by MINUTE.
            n_br = len(op.branches)
            lits = [b[0].data if len(b) == 1 and isinstance(b[0], Lit)
                    else None for b in op.branches]
            for a in range(n_br):
                for b2 in range(a + 1, n_br):
                    if empties[a] or empties[b2]:
                        continue  # empty last branch handled below
                    if lits[a] is not None and lits[b2] is not None:
                        # distinct literals: local matches are mutually
                        # exclusive unless one prefixes the other — and the
                        # dangerous ordering is shorter-prefix-first (re
                        # would backtrack into the longer: "GET|GETX")
                        if lits[b2].startswith(lits[a]) and lits[a] != lits[b2]:
                            raise Tier1Unsupported(
                                "alternation literal is a prefix of a later "
                                "branch (reorder longest-first)")
                        continue
                    if firsts[a].intersects(firsts[b2]) and (
                            flens[a] is None or flens[a] != flens[b2]):
                        raise Tier1Unsupported(
                            "ambiguous alternation branches (overlapping "
                            "first sets, unequal lengths)")
            # an empty-matchable LAST branch makes the Alt optional-like:
            # the other branches must not absorb the continuation
            if empties and empties[-1]:
                union = CharClass.from_bytes(b"")
                for f, e in zip(firsts, empties):
                    if not e:
                        union = union.union(f)
                if union.intersects(follow):
                    raise Tier1Unsupported(
                        "alternation with empty branch overlaps follow set")


def _validate_and_bind(prog: SegmentProgram) -> None:
    _validate_ops(prog.ops, prog, CharClass.from_bytes(b""))


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


def _strip_edge_anchors(tokens):
    """Remove a leading ^ and trailing $ (redundant under full-match
    semantics).  Interior/boundary assertions are rejected in _flatten."""
    at_begin = (sre_c.AT_BEGINNING, sre_c.AT_BEGINNING_STRING)
    at_end = (sre_c.AT_END, sre_c.AT_END_STRING)
    while tokens and tokens[0][0] is sre_c.AT and tokens[0][1] in at_begin:
        tokens = tokens[1:]
    while tokens and tokens[-1][0] is sre_c.AT and tokens[-1][1] in at_end:
        tokens = tokens[:-1]
    return tokens


def compile_tier1(pattern: Union[str, bytes]) -> SegmentProgram:
    if isinstance(pattern, bytes):
        pattern = pattern.decode("latin-1")
    try:
        tree = sre_parse.parse(pattern)
    except Exception as e:  # noqa: BLE001
        raise Tier1Unsupported(f"parse error: {e}") from e
    prog = SegmentProgram(pattern=pattern)
    try:
        names = tree.state.groupdict
        prog.group_names = {v - 1: k for k, v in names.items()}
    except AttributeError:
        pass
    tokens = _strip_edge_anchors(list(tree))
    _flatten(tokens, prog, prog.ops)
    _validate_and_bind(prog)
    return prog


def classify_pattern(pattern: Union[str, bytes]) -> PatternTier:
    try:
        compile_tier1(pattern)
        return PatternTier.SEGMENT
    except Tier1Unsupported:
        pass
    from .dfa import compile_dfa, DFAUnsupported
    try:
        compile_dfa(pattern)
        return PatternTier.DFA
    except DFAUnsupported:
        return PatternTier.CPU
