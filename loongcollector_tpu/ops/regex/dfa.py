"""Tier-2: regex → DFA with byte-class alphabet compression.

For patterns that don't segment-compile (alternation, overlapping classes)
but are still regular (no backreferences / lookaround), we build a Thompson
NFA from the sre AST, determinise it, and compress the alphabet into
equivalence classes.  The device kernel (ops/kernels/dfa_scan.py) advances
all events' DFA states in lockstep over byte columns — full-match semantics,
no captures (capture-needing Tier-2 patterns fall back to CPU).

Design notes for TPU: states are one-hot rows and each step is a batched
(state-onehot ⊗ class-onehot) × transition-tensor contraction on the MXU, so
the transition table lives in VMEM as a dense [K, S, S] tensor — the compiler
therefore caps S (default 64) and K (default 32).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, FrozenSet, List, Optional, Set, Tuple, Union

import numpy as np

try:  # Python 3.11+
    from re import _constants as sre_c
    from re import _parser as sre_parse
except ImportError:  # pragma: no cover
    import sre_constants as sre_c
    import sre_parse

from .charclass import CharClass

MAXREPEAT = sre_c.MAXREPEAT

MAX_NFA_STATES = 4096
MAX_DFA_STATES = 64
MAX_BYTE_CLASSES = 32


class DFAUnsupported(Exception):
    pass


# ---------------------------------------------------------------------------
# Thompson NFA
# ---------------------------------------------------------------------------


class _NFA:
    def __init__(self) -> None:
        self.eps: List[List[int]] = []          # state -> eps targets
        self.trans: List[List[Tuple[np.ndarray, int]]] = []  # state -> [(mask, target)]

    def new_state(self) -> int:
        if len(self.eps) >= MAX_NFA_STATES:
            raise DFAUnsupported("NFA too large")
        self.eps.append([])
        self.trans.append([])
        return len(self.eps) - 1

    def add_eps(self, a: int, b: int) -> None:
        self.eps[a].append(b)

    def add_trans(self, a: int, mask: np.ndarray, b: int) -> None:
        self.trans[a].append((mask, b))


def _build(nfa: _NFA, tokens, start: int) -> int:
    """Builds NFA fragment for token sequence beginning at `start`; returns
    the accepting tail state."""
    cur = start
    for tok_op, av in tokens:
        if tok_op is sre_c.LITERAL:
            nxt = nfa.new_state()
            nfa.add_trans(cur, CharClass.single(av).mask, nxt)
            cur = nxt
        elif tok_op is sre_c.NOT_LITERAL:
            nxt = nfa.new_state()
            nfa.add_trans(cur, CharClass.single(av).negated().mask, nxt)
            cur = nxt
        elif tok_op is sre_c.IN:
            nxt = nfa.new_state()
            nfa.add_trans(cur, CharClass.from_sre_in(av).mask, nxt)
            cur = nxt
        elif tok_op is sre_c.ANY:
            nxt = nfa.new_state()
            nfa.add_trans(cur, CharClass.dot().mask, nxt)
            cur = nxt
        elif tok_op is sre_c.CATEGORY:
            nxt = nfa.new_state()
            nfa.add_trans(cur, CharClass.from_category(av).mask, nxt)
            cur = nxt
        elif tok_op is sre_c.SUBPATTERN:
            _, add_flags, del_flags, sub = av
            if add_flags or del_flags:
                raise DFAUnsupported("inline flags")
            cur = _build(nfa, list(sub), cur)
        elif tok_op is sre_c.BRANCH:
            _, alts = av
            tail = nfa.new_state()
            for alt in alts:
                head = nfa.new_state()
                nfa.add_eps(cur, head)
                end = _build(nfa, list(alt), head)
                nfa.add_eps(end, tail)
            cur = tail
        elif tok_op in (sre_c.MAX_REPEAT, sre_c.MIN_REPEAT):
            lo, hi, sub = av
            sub = list(sub)
            # expand lo mandatory copies
            if lo > 64:
                raise DFAUnsupported("huge repeat")
            for _ in range(lo):
                cur = _build(nfa, sub, cur)
            if hi is MAXREPEAT:
                # star: loop state
                loop_in = nfa.new_state()
                nfa.add_eps(cur, loop_in)
                body_end = _build(nfa, sub, loop_in)
                nfa.add_eps(body_end, loop_in)
                cur = loop_in
            else:
                hi = int(hi)
                if hi - lo > 64:
                    raise DFAUnsupported("huge repeat")
                tail = nfa.new_state()
                nfa.add_eps(cur, tail)
                for _ in range(hi - lo):
                    cur = _build(nfa, sub, cur)
                    nfa.add_eps(cur, tail)
                cur = tail
        elif tok_op is sre_c.AT:
            # Edge anchors are stripped at top level by compile_dfa; any AT
            # reaching here (interior ^/$, \b, \B, anchors inside branches)
            # is position-dependent and unsupported.
            raise DFAUnsupported(f"assertion {av}")
        elif tok_op in (sre_c.ASSERT, sre_c.ASSERT_NOT):
            raise DFAUnsupported("lookaround")
        elif tok_op is sre_c.GROUPREF:
            raise DFAUnsupported("backreference")
        else:
            raise DFAUnsupported(f"op {tok_op}")
    return cur


# ---------------------------------------------------------------------------
# Subset construction + alphabet compression
# ---------------------------------------------------------------------------


@dataclass
class DFA:
    pattern: str
    num_states: int
    num_classes: int
    byte_class: np.ndarray        # [256] uint8 — byte -> class id
    transitions: np.ndarray       # [num_states, num_classes] int32 (dead = 0? no: dead state id)
    start: int
    accepting: np.ndarray         # [num_states] bool
    dead: int

    def byte_class_intervals(self) -> List[List[Tuple[int, int]]]:
        """Per class id, the byte intervals mapping to it (for gather-free
        class computation on device)."""
        out = []
        for k in range(self.num_classes):
            out.append(CharClass(self.byte_class == k).intervals())
        return out

    def match_cpu(self, data: bytes) -> bool:
        """Reference interpreter (for tests)."""
        s = self.start
        for b in data:
            s = int(self.transitions[s, self.byte_class[b]])
        return bool(self.accepting[s])


def strip_anchors(tokens: list) -> list:
    """Drop leading ^/\\A and trailing $/\\Z anchor tokens — batch rows are
    whole lines, so every scan is implicitly anchored (shared by the NFA
    builder here and loongfuse's variant AST)."""
    at_begin = (sre_c.AT_BEGINNING, sre_c.AT_BEGINNING_STRING)
    at_end = (sre_c.AT_END, sre_c.AT_END_STRING)
    while tokens and tokens[0][0] is sre_c.AT and tokens[0][1] in at_begin:
        tokens = tokens[1:]
    while tokens and tokens[-1][0] is sre_c.AT and tokens[-1][1] in at_end:
        tokens = tokens[:-1]
    return tokens


def build_pattern_nfa(pattern: Union[str, bytes],
                      nfa: Optional[_NFA] = None) -> Tuple[_NFA, int, int]:
    """Thompson NFA for one pattern: returns (nfa, start, accept).

    When `nfa` is given, the fragment is built INTO it (loongfuse product
    construction: every pattern of a fused set shares one state space, and
    the fused compiler adds a common start with epsilon edges to each
    pattern's start)."""
    if isinstance(pattern, bytes):
        pattern = pattern.decode("latin-1")
    try:
        tree = sre_parse.parse(pattern)
    except Exception as e:  # noqa: BLE001
        raise DFAUnsupported(f"parse error: {e}") from e

    tokens = strip_anchors(list(tree))
    if nfa is None:
        nfa = _NFA()
    start = nfa.new_state()
    accept = _build(nfa, tokens, start)
    return nfa, start, accept


def compile_dfa(pattern: Union[str, bytes],
                max_states: int = MAX_DFA_STATES,
                max_classes: int = MAX_BYTE_CLASSES) -> DFA:
    if isinstance(pattern, bytes):
        pattern = pattern.decode("latin-1")
    nfa, start, accept = build_pattern_nfa(pattern)

    # epsilon closures
    n = len(nfa.eps)
    closure: List[FrozenSet[int]] = []
    for i in range(n):
        seen = {i}
        stack = [i]
        while stack:
            s = stack.pop()
            for t in nfa.eps[s]:
                if t not in seen:
                    seen.add(t)
                    stack.append(t)
        closure.append(frozenset(seen))

    # alphabet partition: signature per byte over all distinct transition masks
    masks: List[np.ndarray] = []
    for s in range(n):
        for mask, _ in nfa.trans[s]:
            masks.append(mask)
    if masks:
        sig = np.stack(masks).astype(np.uint8)  # [M, 256]
        # unique signature per byte column
        _, byte_class = np.unique(sig.T, axis=0, return_inverse=True)
        byte_class = byte_class.astype(np.uint8)
    else:
        byte_class = np.zeros(256, dtype=np.uint8)
    num_classes = int(byte_class.max()) + 1
    if num_classes > max_classes:
        raise DFAUnsupported(f"{num_classes} byte classes > {max_classes}")
    class_rep = np.zeros(num_classes, dtype=np.int32)  # a representative byte
    for k in range(num_classes):
        class_rep[k] = int(np.argmax(byte_class == k))

    # subset construction over byte classes
    def step(states: FrozenSet[int], byte: int) -> FrozenSet[int]:
        out: Set[int] = set()
        for s in states:
            for mask, t in nfa.trans[s]:
                if mask[byte]:
                    out.update(closure[t])
        return frozenset(out)

    start_set = closure[start]
    dfa_states: Dict[FrozenSet[int], int] = {}
    order: List[FrozenSet[int]] = []

    def intern(fs: FrozenSet[int]) -> int:
        if fs not in dfa_states:
            if len(order) >= max_states:
                raise DFAUnsupported(f"DFA exceeds {max_states} states")
            dfa_states[fs] = len(order)
            order.append(fs)
        return dfa_states[fs]

    dead_id = intern(frozenset())
    start_id = intern(start_set)
    trans_rows: List[List[int]] = [[dead_id] * num_classes]  # dead loops
    i = 1
    while i < len(order):
        fs = order[i]
        row = []
        for k in range(num_classes):
            row.append(intern(step(fs, int(class_rep[k]))))
        trans_rows.append(row)
        i += 1

    transitions = np.array(trans_rows, dtype=np.int32)
    accepting = np.array([accept in fs for fs in order], dtype=bool)
    return DFA(
        pattern=pattern,
        num_states=len(order),
        num_classes=num_classes,
        byte_class=byte_class,
        transitions=transitions,
        start=start_id,
        accepting=accepting,
        dead=dead_id,
    )
