"""Batched DFA execution on device (Tier-2 match kernel).

For regular patterns that don't segment-compile (alternation, overlapping
classes), all events advance a shared DFA in lockstep over byte columns.

TPU mapping: gathers from a [S,K] table are per-element and slow, so the
state is carried ONE-HOT [B, S] in bfloat16 and each step contracts
(state ⊗ byte-class one-hot) with a dense [K·S, S] transition matrix on the
MXU:

    z[b, k·S+s] = cls_onehot[b,k] · state[b,s]       (VPU outer product)
    state'      = z @ T                               (MXU matmul)

Byte classes for all positions are precomputed with interval compares
(no LUT gather).  The scan over positions is a lax.scan compiled once per
(dfa, B, L) geometry.  Used by processor_filter and as the match-gate for
capture-free paths; capture-needing Tier-2 patterns go to CPU (SURVEY.md §7).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..regex.dfa import DFA


def _lockstep_core(automaton):
    """The shared half of every lockstep matcher — works for a
    single-pattern DFA and a fused multi-accept automaton alike (both
    carry num_states/num_classes/transitions/start/byte_class_intervals).

    Returns (K, byte_classes, run): ``byte_classes`` classifies a [B, L]
    byte tensor via interval compares (no LUT gather); ``run(cls)``
    advances all rows in lockstep — state carried ONE-HOT [B, S] in
    bfloat16, each step contracting (state ⊗ class one-hot) with the
    dense [(K+1)·S, S] transition tensor on the MXU, class K being the
    identity freeze class — and returns the final one-hot states.  The
    builders below differ only in how they VALIDITY-mask the class ids
    (whole row vs span) and what they read off the final states (accept
    bit vs tag bitmask)."""
    S = automaton.num_states
    K = automaton.num_classes
    # dense transition tensor T[k*S+s, s'] = 1 iff δ(s, k) = s'
    T = np.zeros((K * S, S), dtype=np.float32)
    for s in range(S):
        for k in range(K):
            T[k * S + s, int(automaton.transitions[s, k])] = 1.0
    T_dev = jnp.asarray(T, dtype=jnp.bfloat16)
    # extend T with an identity block for the freeze class
    T_ext = jnp.concatenate([T_dev, jnp.eye(S, dtype=jnp.bfloat16)], axis=0)
    class_intervals = automaton.byte_class_intervals()

    def byte_classes(rows: jnp.ndarray) -> jnp.ndarray:
        """uint8 [B, L] -> int32 [B, L] class ids via interval compares."""
        cls = jnp.zeros(rows.shape, dtype=jnp.int32)
        for k in range(1, K):  # class 0 is the default
            m = jnp.zeros(rows.shape, dtype=bool)
            for lo, hi in class_intervals[k]:
                if lo == hi:
                    m = m | (rows == lo)
                else:
                    m = m | ((rows >= lo) & (rows <= hi))
            cls = jnp.where(m, k, cls)
        return cls

    def run(cls: jnp.ndarray) -> jnp.ndarray:
        B = cls.shape[0]
        state0 = jax.nn.one_hot(automaton.start, S, dtype=jnp.bfloat16)
        state0 = jnp.broadcast_to(state0, (B, S))

        def step(state, cls_t):
            # cls_t: [B] int32
            coh = jax.nn.one_hot(cls_t, K + 1, dtype=jnp.bfloat16)  # [B, K+1]
            z = (coh[:, :, None] * state[:, None, :]).reshape(B, (K + 1) * S)
            nxt = jnp.dot(z, T_ext, preferred_element_type=jnp.bfloat16)
            return nxt, None

        final, _ = jax.lax.scan(step, state0, cls.T)       # scan over L
        return final

    return K, byte_classes, run


def build_dfa_match_fn(dfa: DFA):
    """Returns jit-able f(rows u8 [B,L], lengths i32 [B]) -> ok bool [B]."""
    K, byte_classes, run = _lockstep_core(dfa)
    accepting = jnp.asarray(dfa.accepting)

    def match(rows: jnp.ndarray, lengths: jnp.ndarray) -> jnp.ndarray:
        L = rows.shape[1]
        cls = byte_classes(rows)                                   # [B, L]
        pos_valid = jnp.arange(L, dtype=jnp.int32)[None, :] < lengths[:, None]
        # past-the-end positions freeze the state: encode as class K (identity)
        cls = jnp.where(pos_valid, cls, K)
        final_state = jnp.argmax(run(cls), axis=1)
        return jnp.take(accepting, final_state)

    return match


def build_dfa_span_match_fn(dfa: DFA):
    """jit-able f(rows u8 [B,L], lengths i32 [B], starts i32 [B],
    spanlens i32 [B]) -> ok bool [B]: full-match of the DFA against the
    row-relative SPAN [starts, starts+spanlens) of each row instead of the
    whole row.

    loongresident: this is the inter-stage composition primitive of the
    fused pipeline program — a filter condition on a field the in-program
    extract stage just captured runs here with the capture spans still
    DEVICE-RESIDENT (no host bounce, no re-pack).  The lockstep advance is
    the single-pattern match kernel's; positions outside the span carry
    the identity freeze class, so the automaton only consumes the field
    bytes.  A row whose span is absent (spanlen < 0, the failed-parse
    convention) never matches — mirroring the staged filter's
    ``ok & src.present`` algebra."""
    K, byte_classes, run = _lockstep_core(dfa)
    accepting = jnp.asarray(dfa.accepting)

    def match(rows: jnp.ndarray, lengths: jnp.ndarray,
              starts: jnp.ndarray, spanlens: jnp.ndarray) -> jnp.ndarray:
        L = rows.shape[1]
        cls = byte_classes(rows)
        pos = jnp.arange(L, dtype=jnp.int32)[None, :]
        span_end = starts + jnp.maximum(spanlens, 0)
        inside = ((pos >= starts[:, None]) & (pos < span_end[:, None])
                  & (pos < lengths[:, None]))
        cls = jnp.where(inside, cls, K)    # freeze outside the span
        final_state = jnp.argmax(run(cls), axis=1)
        return jnp.take(accepting, final_state) & (spanlens >= 0)

    return match


class DFASpanMatchKernel:
    """Owns the jitted span-bound match for one DFA — the per-stage
    (demoted) twin of the in-program span condition: the fused dispatcher
    re-runs a faulted chunk through this kernel with the producer stage's
    materialised spans, so demotion costs dispatches, never answers."""

    def __init__(self, dfa: DFA):
        from ..compile_watch import watched_jit
        self.dfa = dfa
        self._fn = watched_jit(build_dfa_span_match_fn(dfa),
                               "dfa_span_match")

    def __call__(self, rows, lengths, starts, spanlens) -> np.ndarray:
        return self._fn(rows, lengths, starts, spanlens)


class LazySpanMatchKernel:
    """DFASpanMatchKernel built on FIRST call.  The fused planner stores
    this as a capture-bound keep-condition's staged twin, so pipeline
    init never pays the transition-matrix build and host→device constant
    transfer for a kernel only the (rare) demotion path runs."""

    __slots__ = ("dfa", "_k")

    def __init__(self, dfa: DFA):
        self.dfa = dfa
        self._k = None

    def __call__(self, rows, lengths, starts, spanlens) -> np.ndarray:
        if self._k is None:
            self._k = DFASpanMatchKernel(self.dfa)
        return self._k(rows, lengths, starts, spanlens)


def build_fused_scan_fn(fdfa):
    """jit-able f(rows u8 [B,L], lengths i32 [B]) -> tags u32-as-i32 [B].

    loongfuse: the lockstep advance is IDENTICAL to the single-pattern
    match kernel (state one-hot ⊗ class one-hot contracted with the dense
    transition tensor on the MXU) — the widening is in the EPILOGUE, a
    multi-accept one-hot contraction: final [B,S] @ tag-bit matrix [S,P]
    yields per-pattern indicators, folded into one accept-tag bitmask.
    One device pass classifies every pattern of the fused set at once."""
    S = fdfa.num_states
    K, byte_classes, run = _lockstep_core(fdfa)
    P = max(int(fdfa.accept_tags.max()).bit_length(), 1)
    tag_bits = np.zeros((S, P), dtype=np.float32)
    for s in range(S):
        for p in range(P):
            if int(fdfa.accept_tags[s]) & (1 << p):
                tag_bits[s, p] = 1.0
    bits_dev = jnp.asarray(tag_bits, dtype=jnp.bfloat16)
    # bit 31 (MAX_PATTERNS=32) does not fit a python-int->int32 cast;
    # build u32 and bit-cast — callers read the result as uint32 anyway
    pow2 = jnp.asarray(
        np.array([1 << p for p in range(P)], dtype=np.uint32).view(np.int32))

    def scan_tags(rows: jnp.ndarray, lengths: jnp.ndarray) -> jnp.ndarray:
        L = rows.shape[1]
        cls = byte_classes(rows)
        pos_valid = jnp.arange(L, dtype=jnp.int32)[None, :] < lengths[:, None]
        cls = jnp.where(pos_valid, cls, K)      # freeze class past the end
        final = run(cls)
        # multi-accept one-hot contraction: per-pattern indicator columns,
        # folded to a bitmask on the VPU
        ind = jnp.dot(final, bits_dev,
                      preferred_element_type=jnp.float32)
        ind_i = (ind > 0.5).astype(jnp.int32)
        return jnp.sum(ind_i * pow2[None, :], axis=1)

    return scan_tags


class FusedScanKernel:
    """Device execution of a fused multi-accept automaton.  One invocation
    returns the accept-tag bitmask for every event in the batch —
    `invocations` counts dispatches so tests can assert that a ≥4-pattern
    set classifies in a SINGLE kernel pass."""

    def __init__(self, fdfa):
        from ..compile_watch import watched_jit
        self.fdfa = fdfa
        self._fn = watched_jit(build_fused_scan_fn(fdfa), "fused_scan")
        self._fn_donated = None
        self.invocations = 0

    def __call__(self, rows, lengths) -> np.ndarray:
        self.invocations += 1
        return self._fn(rows, lengths)

    def donated_call(self, rows, lengths) -> np.ndarray:
        """Streaming-path variant (see ExtractKernel.donated_call)."""
        from .field_extract import donation_supported
        if not donation_supported():
            return self.__call__(rows, lengths)
        if self._fn_donated is None:
            from ..compile_watch import watched_jit
            self._fn_donated = watched_jit(build_fused_scan_fn(self.fdfa),
                                           "fused_scan",
                                           donate_argnums=(0, 1))
        self.invocations += 1
        return self._fn_donated(rows, lengths)


class DFAMatchKernel:
    def __init__(self, dfa: DFA):
        from ..compile_watch import watched_jit
        self.dfa = dfa
        self._fn = watched_jit(build_dfa_match_fn(dfa), "dfa_match")
        self._fn_donated = None

    def __call__(self, rows, lengths) -> np.ndarray:
        return self._fn(rows, lengths)

    def donated_call(self, rows, lengths) -> np.ndarray:
        """Streaming-path variant: donate the per-dispatch staging buffers
        so XLA reuses their HBM (see ExtractKernel.donated_call — same
        contract, same CPU gating)."""
        from .field_extract import donation_supported
        if not donation_supported():
            return self._fn(rows, lengths)
        if self._fn_donated is None:
            from ..compile_watch import watched_jit
            self._fn_donated = watched_jit(build_dfa_match_fn(self.dfa),
                                           "dfa_match",
                                           donate_argnums=(0, 1))
        return self._fn_donated(rows, lengths)
