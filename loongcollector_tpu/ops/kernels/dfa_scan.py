"""Batched DFA execution on device (Tier-2 match kernel).

For regular patterns that don't segment-compile (alternation, overlapping
classes), all events advance a shared DFA in lockstep over byte columns.

TPU mapping: gathers from a [S,K] table are per-element and slow, so the
state is carried ONE-HOT [B, S] in bfloat16 and each step contracts
(state ⊗ byte-class one-hot) with a dense [K·S, S] transition matrix on the
MXU:

    z[b, k·S+s] = cls_onehot[b,k] · state[b,s]       (VPU outer product)
    state'      = z @ T                               (MXU matmul)

Byte classes for all positions are precomputed with interval compares
(no LUT gather).  The scan over positions is a lax.scan compiled once per
(dfa, B, L) geometry.  Used by processor_filter and as the match-gate for
capture-free paths; capture-needing Tier-2 patterns go to CPU (SURVEY.md §7).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..regex.dfa import DFA


def build_dfa_match_fn(dfa: DFA):
    """Returns jit-able f(rows u8 [B,L], lengths i32 [B]) -> ok bool [B]."""
    S = dfa.num_states
    K = dfa.num_classes
    # dense transition tensor T[k*S+s, s'] = 1 iff δ(s, k) = s'
    T = np.zeros((K * S, S), dtype=np.float32)
    for s in range(S):
        for k in range(K):
            T[k * S + s, int(dfa.transitions[s, k])] = 1.0
    T_dev = jnp.asarray(T, dtype=jnp.bfloat16)
    class_intervals = dfa.byte_class_intervals()
    accepting = jnp.asarray(dfa.accepting)

    def byte_classes(rows: jnp.ndarray) -> jnp.ndarray:
        """uint8 [B, L] -> int32 [B, L] class ids via interval compares."""
        cls = jnp.zeros(rows.shape, dtype=jnp.int32)
        for k in range(1, K):  # class 0 is the default
            m = jnp.zeros(rows.shape, dtype=bool)
            for lo, hi in class_intervals[k]:
                if lo == hi:
                    m = m | (rows == lo)
                else:
                    m = m | ((rows >= lo) & (rows <= hi))
            cls = jnp.where(m, k, cls)
        return cls

    def match(rows: jnp.ndarray, lengths: jnp.ndarray) -> jnp.ndarray:
        B, L = rows.shape
        cls = byte_classes(rows)                                   # [B, L]
        pos_valid = jnp.arange(L, dtype=jnp.int32)[None, :] < lengths[:, None]
        # past-the-end positions freeze the state: encode as class K (identity)
        cls = jnp.where(pos_valid, cls, K)
        # extend T with an identity block for the freeze class
        T_ext = jnp.concatenate(
            [T_dev, jnp.tile(jnp.eye(S, dtype=jnp.bfloat16), (1, 1))], axis=0)

        state0 = jax.nn.one_hot(dfa.start, S, dtype=jnp.bfloat16)
        state0 = jnp.broadcast_to(state0, (B, S))

        def step(state, cls_t):
            # cls_t: [B] int32
            coh = jax.nn.one_hot(cls_t, K + 1, dtype=jnp.bfloat16)  # [B, K+1]
            z = (coh[:, :, None] * state[:, None, :]).reshape(B, (K + 1) * S)
            nxt = jnp.dot(z, T_ext, preferred_element_type=jnp.bfloat16)
            return nxt, None

        final, _ = jax.lax.scan(step, state0, cls.T)               # scan over L
        final_state = jnp.argmax(final, axis=1)
        return jnp.take(accepting, final_state)

    return match


class DFAMatchKernel:
    def __init__(self, dfa: DFA):
        self.dfa = dfa
        self._fn = jax.jit(build_dfa_match_fn(dfa))
        self._fn_donated = None

    def __call__(self, rows, lengths) -> np.ndarray:
        return self._fn(rows, lengths)

    def donated_call(self, rows, lengths) -> np.ndarray:
        """Streaming-path variant: donate the per-dispatch staging buffers
        so XLA reuses their HBM (see ExtractKernel.donated_call — same
        contract, same CPU gating)."""
        from .field_extract import donation_supported
        if not donation_supported():
            return self._fn(rows, lengths)
        if self._fn_donated is None:
            self._fn_donated = jax.jit(build_dfa_match_fn(self.dfa),
                                       donate_argnums=(0, 1))
        return self._fn_donated(rows, lengths)
