"""Device kernels: batched field extraction and structural indexing.

Public surface:

* ``field_extract`` / ``field_extract_pallas`` — Tier-1 segment-program
  execution over [B, L] row tensors (the regex/grok/delimiter plane);
* ``dfa_scan`` — fused multi-accept DFA classification (loongfuse);
* ``struct_index`` — structural bitmaps for JSON / quote-mode delimiter
  parsing (loongstruct): one dispatch indexes a whole batch-ring slot.
"""

from .struct_index import (MODE_DELIM, MODE_JSON,  # noqa: F401
                           StructIndexKernel, struct_index_numpy)
