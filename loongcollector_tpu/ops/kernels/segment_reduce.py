"""Segment-reduce twins for the loongagg metric fold.

The native `lct_group_reduce` is the production substrate: hash the
(window slot, key spans) identity per row, then fold the value column
per group — sum/count/min/max/last plus the metrics.py-shaped log2-bucket
histogram — in f64, in row order.  This module carries its two siblings:

* the **numpy twin** — the no-native tier and the shared reference.  The
  segment identity comes from one vectorised length-prefixed key-matrix
  gather + ``np.unique`` remapped to first-seen order (the native group-id
  order), and the fold accumulates with ``np.add.at`` — sequential adds in
  row index order, the exact accumulation order of the native loop, so
  sums are **bit-identical**, not merely close (min/max/count/hist are
  order-free).  Value-span parsing is the one per-row loop in this tier
  (no vectorised strtod exists); it is the degraded path by contract —
  the native plane is the throughput claim;

* the **device twin** (`SegmentReduceKernel`) — the wide data-parallel
  half for the accelerator, `jax.ops.segment_*` over a padded batch slot:
  ONE jitted dispatch per ``device_batch`` geometry computes every
  aggregate including the histogram (a segment-sum over ``seg * NB +
  bucket``).  Keying, value parsing and bucket ids stay on the host (f64,
  shared helpers — frexp on f32 would disagree at power-of-two
  boundaries); the device owns the reduction, ParPaRaw-style.  Sums
  accumulate in f32 on default-precision backends, so the
  ``scripts/agg_equivalence.py`` gate compares device sums with a stated
  tolerance and everything else exactly.

All three substrates are differentially gated (lint.sh + tier-1) — same
partition, same aggregates, or the gate fails per row.
"""

from __future__ import annotations

import math
import os
import re
from dataclasses import dataclass
from typing import Optional

import numpy as np

#: metrics.py Histogram geometry applied to metric VALUES: base 1.0
#: (values ≤ 1 land in bucket 0), 40 log2 buckets + the +Inf slot
HIST_BASE = 1.0
N_HIST = 41

#: the strtod-subset value grammar shared with the native plane (see
#: lct_group_reduce): sign, decimal digits with optional fraction and
#: exponent, or inf/infinity.  NaN is invalid BY GRAMMAR — it would make
#: min/max accumulation order-visible across substrates.
_VALUE_RE = re.compile(
    rb"^[+-]?(?:(?:\d+\.?\d*|\.\d+)(?:[eE][+-]?\d+)?|"
    rb"[iI][nN][fF](?:[iI][nN][iI][tT][yY])?)$")


def hist_bucket(values: np.ndarray, base: float = HIST_BASE,
                n_hist: int = N_HIST) -> np.ndarray:
    """Vectorised metrics.py bucket shape on f64: v <= base (and
    negatives) -> 0, +inf -> the last slot, else ceil(log2(v/base))
    clamped.  Shared by the numpy twin and the device path (bucket ids
    are computed on the host in f64 for all substrates)."""
    v = np.asarray(values, dtype=np.float64)
    m, e = np.frexp(np.where(v > base, v / base, 1.0))
    idx = np.where(m == 0.5, e - 1, e).astype(np.int64)
    idx = np.clip(idx, 0, n_hist - 1)
    idx = np.where(v > base, idx, 0)
    return np.where(np.isinf(v) & (v > 0), n_hist - 1, idx)


#: vector parse only reads this many bytes per span; longer tokens (rare:
#: huge paddings, absurd precision) take the per-row reference path
_VEC_WIDTH = 32
#: ≤ 15 decimal digits ⇒ the mantissa integer is exact in f64 and
#: m / 10^frac is a single correctly-rounded division (Clinger) — the
#: same fast-path argument the native strtod subset uses
_VEC_MAX_DIGITS = 15


def _parse_values_rows(arena: np.ndarray, val_offs: np.ndarray,
                       val_lens: np.ndarray, rows, values: np.ndarray,
                       valid: np.ndarray) -> None:
    """Reference per-row parse of selected rows: the shared grammar regex
    gates, Python float() converts (correctly rounded ⇒ bit-identical to
    the native strtod)."""
    buf = memoryview(np.ascontiguousarray(arena))
    for i in rows:
        ln = int(val_lens[i])
        if ln < 0:
            continue
        off = int(val_offs[i])
        tok = bytes(buf[off:off + ln]).strip(b" \t")
        if not _VALUE_RE.match(tok):
            continue
        values[i] = float(tok)
        valid[i] = True


def parse_values(arena: np.ndarray, val_offs: np.ndarray,
                 val_lens: np.ndarray):
    """(values f64 [n], valid bool [n]) from value text spans.

    The common shape — optional sign, ≤ 15 digits, at most one '.' , no
    exponent — parses VECTORISED: one byte-matrix gather, per-column
    digit folds into an exact int64 mantissa, one correctly-rounded
    division by an exact power of ten.  Clinger's fast-path argument
    makes that bit-identical to Python float(), which the
    scripts/agg_equivalence.py gate asserts against the reference loop.
    Everything else (exponents, inf, over-long, malformed) drops to the
    per-row reference path — the counted exception, not the steady
    state.  Part of the BENCH_r11 device-substrate cliff fix: the per-row
    float() loop priced every twin's fold, not the kernel
    (``LOONG_AGG_PREP=0`` restores the r11 prep for the bench's
    before/after)."""
    n = len(val_offs)
    values = np.zeros(n, dtype=np.float64)
    valid = np.zeros(n, dtype=bool)
    if n == 0:
        return values, valid
    if not _prep_opt_enabled():
        _parse_values_rows(arena, val_offs, val_lens, range(n), values,
                           valid)
        return values, valid
    offs = np.asarray(val_offs, dtype=np.int64)
    lens = np.asarray(val_lens, dtype=np.int64)
    W = min(int(lens.max()), _VEC_WIDTH)
    if W <= 0:
        # nothing with a positive length; empty spans are invalid by
        # grammar, negative lengths are the absent convention
        return values, valid
    arena_hi = max(len(arena) - 1, 0)
    idx = offs[:, None] + np.arange(W, dtype=np.int64)[None, :]
    np.clip(idx, 0, arena_hi, out=idx)
    mat = arena[idx] if len(arena) else np.zeros((n, W), np.uint8)
    inrow = np.arange(W, dtype=np.int64)[None, :] < lens[:, None]
    SPACE = np.uint8(0x20)
    mat = np.where(inrow, mat, SPACE)      # pad reads as trimmable space
    is_sp = (mat == 0x20) | (mat == 0x09)
    nonsp = ~is_sp
    any_ns = nonsp.any(axis=1)
    first = np.argmax(nonsp, axis=1)
    last = W - 1 - np.argmax(nonsp[:, ::-1], axis=1)
    colpos = np.arange(W, dtype=np.int64)[None, :]
    is_digit = (mat >= 0x30) & (mat <= 0x39)
    is_dot = mat == 0x2E
    sign_byte = mat[np.arange(n), first]
    has_sign = (sign_byte == 0x2B) | (sign_byte == 0x2D)
    body_lo = first + has_sign
    within = (colpos >= body_lo[:, None]) & (colpos <= last[:, None])
    digits = np.count_nonzero(is_digit & within, axis=1)
    dots = np.count_nonzero(is_dot & within, axis=1)
    clean = (within & ~(is_digit | is_dot)).sum(axis=1) == 0
    fast = (any_ns & clean & (dots <= 1) & (digits >= 1)
            & (digits <= _VEC_MAX_DIGITS) & (lens <= _VEC_WIDTH)
            & (body_lo <= last))
    # per-column mantissa fold: m = m*10 + d over the token's digit
    # positions (int64-exact: ≤ 15 digits), frac counts digits after the
    # dot — vector ops per COLUMN, never per row
    m = np.zeros(n, dtype=np.int64)
    frac = np.zeros(n, dtype=np.int64)
    seen_dot = np.zeros(n, dtype=bool)
    for c in range(W):
        active = fast & within[:, c]
        d = is_digit[:, c] & active
        m = np.where(d, m * 10 + (mat[:, c].astype(np.int64) - 0x30), m)
        frac = np.where(d & seen_dot, frac + 1, frac)
        seen_dot = seen_dot | (is_dot[:, c] & active)
    v = m.astype(np.float64) / np.power(10.0, frac)
    v = np.where(sign_byte == 0x2D, -v, v)
    values[fast] = v[fast]
    valid[fast] = True
    # rows longer than the window may hide their token past byte W (all
    # leading spaces): they must take the reference path, not "invalid"
    slow = np.nonzero((lens >= 0) & ~fast & (any_ns | (lens > W)))[0]
    if len(slow):
        _parse_values_rows(arena, val_offs, val_lens, slow, values, valid)
    return values, valid


def _key_matrix(arena: np.ndarray, slots: np.ndarray,
                key_offs: np.ndarray, key_lens: np.ndarray):
    """Length-prefixed key bytes as one uint8 matrix [n, W] — the
    vectorised identity the first-seen grouping runs np.unique over.
    The i32 length prefix keeps absent (-1) distinct from empty and
    ("ab","") distinct from ("a","b"); the slot rides as an i64 prefix
    column so window identity is part of the segment key, exactly as in
    the native hash.

    Returns (mat, widths): ``widths`` is the per-key padded column width
    (the batch max per key) — matrix rows are only comparable ACROSS
    batches together with their widths, because the zero padding between
    key segments is width-dependent (the merge-side intern cache keys on
    both)."""
    n, K = key_lens.shape
    parts = [np.ascontiguousarray(slots, dtype="<i8").view(
        np.uint8).reshape(n, 8)]
    arena_hi = max(len(arena) - 1, 0)
    widths = []
    for k in range(K):
        lens = key_lens[:, k]
        parts.append(np.ascontiguousarray(lens, dtype="<i4").view(
            np.uint8).reshape(n, 4))
        m = int(lens.max()) if n else 0
        widths.append(max(m, 0))
        if m > 0:
            idx = key_offs[:, k, None] + np.arange(m, dtype=np.int64)[None, :]
            np.clip(idx, 0, arena_hi, out=idx)
            body = (arena[idx] if len(arena)
                    else np.zeros((n, m), np.uint8))
            mask = np.arange(m, dtype=np.int32)[None, :] < lens[:, None]
            parts.append(np.where(mask, body, 0).astype(np.uint8))
    return np.concatenate(parts, axis=1), tuple(widths)


def _prep_opt_enabled() -> bool:
    """``LOONG_AGG_PREP=0`` restores the r11 host-prep path (per-row
    float() parse + full-byte-matrix np.unique) — the bench's before/after
    comparator for the device-substrate cliff fix."""
    return os.environ.get("LOONG_AGG_PREP") != "0"


def _first_seen_ids_exact(mat: np.ndarray):
    """Reference grouping: np.unique over the whole byte matrix is
    lexicographic, so remap through the argsort of first occurrences to
    match the native assignment order.  This was the BENCH_r11 device
    cliff's dominant term (~107 of 137 ms per 16 k-row fold)."""
    _uniq, first_idx, inv = np.unique(mat, axis=0, return_index=True,
                                      return_inverse=True)
    order = np.argsort(first_idx, kind="stable")
    remap = np.empty(len(order), dtype=np.int64)
    remap[order] = np.arange(len(order))
    return remap[np.asarray(inv).reshape(-1)], first_idx[order]


def _first_seen_ids(mat: np.ndarray):
    """(group ids [rows] in first-seen order, representative row per
    group).

    Fast path: a vectorised 64-bit FNV-1a over the matrix columns gives
    one hash per row; np.unique on the [n] u64 vector replaces the
    lexicographic sort of the full byte matrix.  Grouping stays EXACT —
    every row's bytes are compared against its hash-group
    representative's (one gather + one matrix compare); any mismatch (a
    64-bit collision, astronomically rare) falls back to the byte-exact
    reference, so the partition and the first-seen id order are always
    identical to the native assignment."""
    if not _prep_opt_enabled():
        return _first_seen_ids_exact(mat)
    n, W = mat.shape
    if n == 0:
        return _first_seen_ids_exact(mat)
    h = np.full(n, 0xcbf29ce484222325, dtype=np.uint64)
    prime = np.uint64(0x100000001B3)
    for c in range(W):
        h = (h ^ mat[:, c].astype(np.uint64)) * prime
    _uniq, first_idx, inv = np.unique(h, return_index=True,
                                      return_inverse=True)
    inv = np.asarray(inv).reshape(-1)
    rep_rows = first_idx[inv]
    if not np.array_equal(mat, mat[rep_rows]):
        return _first_seen_ids_exact(mat)
    order = np.argsort(first_idx, kind="stable")
    remap = np.empty(len(order), dtype=np.int64)
    remap[order] = np.arange(len(order))
    return remap[inv], first_idx[order]


@dataclass
class BatchFold:
    """One batch's partial fold, identical shape across substrates."""

    group_id: np.ndarray   # i32/i64 [n]; -1 = invalid-value row
    rep_row: np.ndarray    # [G] first row index per group
    sum: np.ndarray        # f64 [G]
    count: np.ndarray      # i64 [G]
    min: np.ndarray        # f64 [G]
    max: np.ndarray        # f64 [G]
    last: np.ndarray       # f64 [G]
    hist: np.ndarray       # i64 [G, N_HIST]
    #: [G, W] uint8 key-matrix rows of the representatives, when the
    #: substrate already gathered them (numpy/device twins): the fold's
    #: hash-key bytes, reusable by the window merge as interning keys so
    #: steady-state batches never rebuild per-group key tuples
    #: (BENCH_r11 device-cliff satellite).  None on the native substrate.
    rep_key_blob: Optional[np.ndarray] = None
    #: per-key padded widths of ``rep_key_blob`` (see _key_matrix): blob
    #: rows are only comparable across batches together with these —
    #: interning on the bytes alone would let two different key tuples
    #: from different-width batches collide
    key_widths: Optional[tuple] = None

    @property
    def n_groups(self) -> int:
        return int(len(self.rep_row))

    @property
    def n_invalid(self) -> int:
        return int(np.count_nonzero(self.group_id < 0))


def fold_batch_numpy(arena: np.ndarray, slots: np.ndarray,
                     key_offs: np.ndarray, key_lens: np.ndarray,
                     val_offs: np.ndarray, val_lens: np.ndarray,
                     hist_base: float = HIST_BASE,
                     n_hist: int = N_HIST) -> BatchFold:
    """The numpy substrate / shared reference (see module docstring)."""
    n = len(slots)
    values, valid = parse_values(arena, val_offs, val_lens)
    group_id = np.full(n, -1, dtype=np.int32)
    vrows = np.nonzero(valid)[0]
    if len(vrows) == 0:
        z = np.zeros(0)
        return BatchFold(group_id, np.zeros(0, np.int32), z,
                         np.zeros(0, np.int64), z, z, z,
                         np.zeros((0, n_hist), np.int64))
    mat, widths = _key_matrix(arena, slots[vrows], key_offs[vrows],
                              key_lens[vrows])
    ids, first = _first_seen_ids(mat)
    group_id[vrows] = ids
    rep_row = vrows[first].astype(np.int32)
    G = int(ids.max()) + 1
    vv = values[vrows]
    sums = np.zeros(G, dtype=np.float64)
    # np.add.at applies adds in index order — the native loop's exact
    # accumulation order, which is what makes sums bit-identical (np.sum
    # style pairwise reduction would not be).  inf + -inf inside one key
    # is legal (sum -> NaN on every substrate): silence the warning
    with np.errstate(invalid="ignore"):
        np.add.at(sums, ids, vv)
    counts = np.bincount(ids, minlength=G).astype(np.int64)
    order = np.argsort(ids, kind="stable")
    sv = vv[order]
    starts = np.searchsorted(ids[order], np.arange(G))
    mins = np.minimum.reduceat(sv, starts)
    maxs = np.maximum.reduceat(sv, starts)
    ends = np.append(starts[1:], len(sv))
    last = sv[ends - 1]
    hist = np.zeros((G, n_hist), dtype=np.int64)
    np.add.at(hist, (ids, hist_bucket(vv, hist_base, n_hist)), 1)
    return BatchFold(group_id, rep_row, sums, counts, mins, maxs, last,
                     hist, rep_key_blob=mat[first], key_widths=widths)


def fold_batch_native(arena: np.ndarray, slots: np.ndarray,
                      key_offs: np.ndarray, key_lens: np.ndarray,
                      val_offs: np.ndarray, val_lens: np.ndarray,
                      hist_base: float = HIST_BASE,
                      n_hist: int = N_HIST) -> Optional[BatchFold]:
    """The native substrate; None when the library is unavailable."""
    from ...native import group_reduce
    res = group_reduce(arena, slots, key_offs, key_lens, val_offs,
                       val_lens, hist_base=hist_base, n_hist=n_hist)
    if res is None:
        return None
    return BatchFold(*res)


# ---------------------------------------------------------------------------
# device twin


def build_reduce_fn(n_hist: int):
    """Returns jit-able f(values f32 [B], seg i32 [B], buckets i32 [B],
    valid bool [B], G static) -> (sum, count, min, max, last, hist).
    Invalid/padding rows route to segment id G — out of range, dropped by
    the scatter, never a branch."""
    import jax
    import jax.numpy as jnp

    def reduce_fn(values, seg, buckets, valid, G):
        seg = jnp.where(valid, seg, G)
        data = jnp.where(valid, values, jnp.float32(0))
        sums = jax.ops.segment_sum(data, seg, num_segments=G)
        cnt = jax.ops.segment_sum(valid.astype(jnp.int32), seg,
                                  num_segments=G)
        mins = jax.ops.segment_min(
            jnp.where(valid, values, jnp.float32(jnp.inf)), seg,
            num_segments=G)
        maxs = jax.ops.segment_max(
            jnp.where(valid, values, jnp.float32(-jnp.inf)), seg,
            num_segments=G)
        idx = jnp.arange(values.shape[0], dtype=jnp.int32)
        last_idx = jax.ops.segment_max(
            jnp.where(valid, idx, jnp.int32(-1)), seg, num_segments=G)
        last = jnp.where(last_idx >= 0,
                         values[jnp.clip(last_idx, 0, None)],
                         jnp.float32(0))
        hist = jax.ops.segment_sum(
            valid.astype(jnp.int32), seg * n_hist + buckets,
            num_segments=G * n_hist).reshape(G, n_hist)
        return sums, cnt, mins, maxs, last, hist

    return reduce_fn


class SegmentReduceKernel:
    """Owns the jitted segment-reduce for one histogram geometry.

    jit caches per (B, G) — `fold_batch` quantises B through
    ``ops.device_batch.pad_batch`` and G to a power of two, so a batch
    slot is ONE dispatch (`dispatch_count` asserted in the device test).
    `donated_call` mirrors the loongstream donated-buffer contract for
    the transient staging arrays."""

    def __init__(self, n_hist: int = N_HIST):
        from ..compile_watch import watched_jit
        self.n_hist = n_hist
        self._fn = watched_jit(build_reduce_fn(n_hist), "segment_reduce",
                               static_argnums=(4,))
        self._fn_donated = None
        self.dispatch_count = 0
        # per-geometry staging buffers (the batch-slot idiom): the padded
        # value/segment/bucket arrays are reused across folds instead of
        # re-allocated per batch — part of the BENCH_r11 device-cliff fix
        # (host prep must not price the kernel).  Buffers are LEASED out
        # of the pool under the lock and returned after the fold, so two
        # pipelines sharing the module-global kernel never race one
        # tuple yet still overlap their device round trips.
        import threading
        self._staging: dict = {}
        self._staging_lock = threading.Lock()

    def __call__(self, values, seg, buckets, valid, G: int):
        self.dispatch_count += 1
        return self._fn(values, seg, buckets, valid, G)

    def donated_call(self, values, seg, buckets, valid, G: int):
        from .field_extract import donation_supported
        if not donation_supported():
            return self(values, seg, buckets, valid, G)
        if self._fn_donated is None:
            from ..compile_watch import watched_jit
            self._fn_donated = watched_jit(build_reduce_fn(self.n_hist),
                                           "segment_reduce",
                                           static_argnums=(4,),
                                           donate_argnums=(0, 1, 2, 3))
        self.dispatch_count += 1
        return self._fn_donated(values, seg, buckets, valid, G)

    def fold_batch(self, arena: np.ndarray, slots: np.ndarray,
                   key_offs: np.ndarray, key_lens: np.ndarray,
                   val_offs: np.ndarray, val_lens: np.ndarray,
                   hist_base: float = HIST_BASE) -> BatchFold:
        """Device substrate: host keying + bucketing (exact f64), padded
        single-dispatch segment reduction on the accelerator."""
        import jax

        from ..device_batch import pad_batch
        n_hist = self.n_hist
        n = len(slots)
        values, valid = parse_values(arena, val_offs, val_lens)
        group_id = np.full(n, -1, dtype=np.int32)
        vrows = np.nonzero(valid)[0]
        if len(vrows) == 0:
            z = np.zeros(0)
            return BatchFold(group_id, np.zeros(0, np.int32), z,
                             np.zeros(0, np.int64), z, z, z,
                             np.zeros((0, n_hist), np.int64))
        mat, widths = _key_matrix(arena, slots[vrows], key_offs[vrows],
                                  key_lens[vrows])
        ids, first = _first_seen_ids(mat)
        group_id[vrows] = ids
        rep_row = vrows[first].astype(np.int32)
        G = int(ids.max()) + 1
        B = pad_batch(n)
        Gq = 16
        while Gq < G:
            Gq *= 2
        # lease the geometry's staging tuple OUT of the pool (lock held
        # only for the checkout/return, never across the device round
        # trip — concurrent pipelines overlap their folds); a concurrent
        # lease of the same geometry just allocates a transient tuple
        # and the later return drops it
        from ..device_plane import mem_note_alloc, mem_note_free
        with self._staging_lock:
            bufs = self._staging.pop(B, None)
        if bufs is None:
            bufs = (np.zeros(B, dtype=np.float32),
                    np.zeros(B, dtype=np.int32),
                    np.zeros(B, dtype=np.int32),
                    np.zeros(B, dtype=bool))
            # side_arenas ledger (loongxprof): a freshly allocated staging
            # tuple joins the pool's live footprint; a transient tuple
            # dropped at return (pool already holds this geometry) credits
            # back below
            mem_note_alloc("side_arenas", sum(a.nbytes for a in bufs))
        try:
            vals, seg, buckets, ok = bufs
            vals[:n] = values.astype(np.float32)
            vals[n:] = 0
            seg[:n] = group_id.clip(min=0)
            seg[n:] = Gq
            ok[:n] = valid
            ok[n:] = False
            buckets[:n] = hist_bucket(values, hist_base, n_hist)
            buckets[n:] = 0
            out = self.donated_call(vals, seg, buckets, ok, Gq)
            sums, cnt, mins, maxs, last, hist = (np.asarray(a) for a in
                                                 jax.device_get(out))
        finally:
            with self._staging_lock:
                kept = self._staging.setdefault(B, bufs) is bufs
            if not kept:
                mem_note_free("side_arenas", sum(a.nbytes for a in bufs))
        return BatchFold(group_id, rep_row,
                         sums[:G].astype(np.float64),
                         cnt[:G].astype(np.int64),
                         mins[:G].astype(np.float64),
                         maxs[:G].astype(np.float64),
                         last[:G].astype(np.float64),
                         hist[:G].astype(np.int64),
                         rep_key_blob=mat[first], key_widths=widths)


_device_kernel: Optional[SegmentReduceKernel] = None


def device_kernel() -> SegmentReduceKernel:
    global _device_kernel
    if _device_kernel is None:
        _device_kernel = SegmentReduceKernel()
    return _device_kernel


def hist_bucket_scalar(v: float, base: float = HIST_BASE,
                       n_hist: int = N_HIST) -> int:
    """Scalar shape twin for the per-event dict path (exactly the
    vectorised hist_bucket, which itself mirrors metrics.py)."""
    if math.isinf(v) and v > 0:
        return n_hist - 1
    if not v > base:
        return 0
    m, e = math.frexp(v / base)
    idx = e - 1 if m == 0.5 else e
    return min(max(idx, 0), n_hist - 1)
