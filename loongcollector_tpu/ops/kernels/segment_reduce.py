"""Segment-reduce twins for the loongagg metric fold.

The native `lct_group_reduce` is the production substrate: hash the
(window slot, key spans) identity per row, then fold the value column
per group — sum/count/min/max/last plus the metrics.py-shaped log2-bucket
histogram — in f64, in row order.  This module carries its two siblings:

* the **numpy twin** — the no-native tier and the shared reference.  The
  segment identity comes from one vectorised length-prefixed key-matrix
  gather + ``np.unique`` remapped to first-seen order (the native group-id
  order), and the fold accumulates with ``np.add.at`` — sequential adds in
  row index order, the exact accumulation order of the native loop, so
  sums are **bit-identical**, not merely close (min/max/count/hist are
  order-free).  Value-span parsing is the one per-row loop in this tier
  (no vectorised strtod exists); it is the degraded path by contract —
  the native plane is the throughput claim;

* the **device twin** (`SegmentReduceKernel`) — the wide data-parallel
  half for the accelerator, `jax.ops.segment_*` over a padded batch slot:
  ONE jitted dispatch per ``device_batch`` geometry computes every
  aggregate including the histogram (a segment-sum over ``seg * NB +
  bucket``).  Keying, value parsing and bucket ids stay on the host (f64,
  shared helpers — frexp on f32 would disagree at power-of-two
  boundaries); the device owns the reduction, ParPaRaw-style.  Sums
  accumulate in f32 on default-precision backends, so the
  ``scripts/agg_equivalence.py`` gate compares device sums with a stated
  tolerance and everything else exactly.

All three substrates are differentially gated (lint.sh + tier-1) — same
partition, same aggregates, or the gate fails per row.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass
from typing import Optional

import numpy as np

#: metrics.py Histogram geometry applied to metric VALUES: base 1.0
#: (values ≤ 1 land in bucket 0), 40 log2 buckets + the +Inf slot
HIST_BASE = 1.0
N_HIST = 41

#: the strtod-subset value grammar shared with the native plane (see
#: lct_group_reduce): sign, decimal digits with optional fraction and
#: exponent, or inf/infinity.  NaN is invalid BY GRAMMAR — it would make
#: min/max accumulation order-visible across substrates.
_VALUE_RE = re.compile(
    rb"^[+-]?(?:(?:\d+\.?\d*|\.\d+)(?:[eE][+-]?\d+)?|"
    rb"[iI][nN][fF](?:[iI][nN][iI][tT][yY])?)$")


def hist_bucket(values: np.ndarray, base: float = HIST_BASE,
                n_hist: int = N_HIST) -> np.ndarray:
    """Vectorised metrics.py bucket shape on f64: v <= base (and
    negatives) -> 0, +inf -> the last slot, else ceil(log2(v/base))
    clamped.  Shared by the numpy twin and the device path (bucket ids
    are computed on the host in f64 for all substrates)."""
    v = np.asarray(values, dtype=np.float64)
    m, e = np.frexp(np.where(v > base, v / base, 1.0))
    idx = np.where(m == 0.5, e - 1, e).astype(np.int64)
    idx = np.clip(idx, 0, n_hist - 1)
    idx = np.where(v > base, idx, 0)
    return np.where(np.isinf(v) & (v > 0), n_hist - 1, idx)


def parse_values(arena: np.ndarray, val_offs: np.ndarray,
                 val_lens: np.ndarray):
    """(values f64 [n], valid bool [n]) from value text spans.

    Degraded-tier loop by contract (documented above): validation is the
    shared grammar regex, conversion is Python float() — correctly
    rounded, so results are bit-identical to the native strtod."""
    n = len(val_offs)
    values = np.zeros(n, dtype=np.float64)
    valid = np.zeros(n, dtype=bool)
    buf = memoryview(np.ascontiguousarray(arena))
    for i in range(n):
        ln = int(val_lens[i])
        if ln < 0:
            continue
        off = int(val_offs[i])
        tok = bytes(buf[off:off + ln]).strip(b" \t")
        if not _VALUE_RE.match(tok):
            continue
        values[i] = float(tok)
        valid[i] = True
    return values, valid


def _key_matrix(arena: np.ndarray, slots: np.ndarray,
                key_offs: np.ndarray, key_lens: np.ndarray) -> np.ndarray:
    """Length-prefixed key bytes as one uint8 matrix [n, W] — the
    vectorised identity the first-seen grouping runs np.unique over.
    The i32 length prefix keeps absent (-1) distinct from empty and
    ("ab","") distinct from ("a","b"); the slot rides as an i64 prefix
    column so window identity is part of the segment key, exactly as in
    the native hash."""
    n, K = key_lens.shape
    parts = [np.ascontiguousarray(slots, dtype="<i8").view(
        np.uint8).reshape(n, 8)]
    arena_hi = max(len(arena) - 1, 0)
    for k in range(K):
        lens = key_lens[:, k]
        parts.append(np.ascontiguousarray(lens, dtype="<i4").view(
            np.uint8).reshape(n, 4))
        m = int(lens.max()) if n else 0
        if m > 0:
            idx = key_offs[:, k, None] + np.arange(m, dtype=np.int64)[None, :]
            np.clip(idx, 0, arena_hi, out=idx)
            body = (arena[idx] if len(arena)
                    else np.zeros((n, m), np.uint8))
            mask = np.arange(m, dtype=np.int32)[None, :] < lens[:, None]
            parts.append(np.where(mask, body, 0).astype(np.uint8))
    return np.concatenate(parts, axis=1)


def _first_seen_ids(mat: np.ndarray):
    """(group ids [rows] in first-seen order, representative row per
    group) — np.unique is lexicographic, so remap through the argsort of
    first occurrences to match the native assignment order."""
    _uniq, first_idx, inv = np.unique(mat, axis=0, return_index=True,
                                      return_inverse=True)
    order = np.argsort(first_idx, kind="stable")
    remap = np.empty(len(order), dtype=np.int64)
    remap[order] = np.arange(len(order))
    return remap[np.asarray(inv).reshape(-1)], first_idx[order]


@dataclass
class BatchFold:
    """One batch's partial fold, identical shape across substrates."""

    group_id: np.ndarray   # i32/i64 [n]; -1 = invalid-value row
    rep_row: np.ndarray    # [G] first row index per group
    sum: np.ndarray        # f64 [G]
    count: np.ndarray      # i64 [G]
    min: np.ndarray        # f64 [G]
    max: np.ndarray        # f64 [G]
    last: np.ndarray       # f64 [G]
    hist: np.ndarray       # i64 [G, N_HIST]

    @property
    def n_groups(self) -> int:
        return int(len(self.rep_row))

    @property
    def n_invalid(self) -> int:
        return int(np.count_nonzero(self.group_id < 0))


def fold_batch_numpy(arena: np.ndarray, slots: np.ndarray,
                     key_offs: np.ndarray, key_lens: np.ndarray,
                     val_offs: np.ndarray, val_lens: np.ndarray,
                     hist_base: float = HIST_BASE,
                     n_hist: int = N_HIST) -> BatchFold:
    """The numpy substrate / shared reference (see module docstring)."""
    n = len(slots)
    values, valid = parse_values(arena, val_offs, val_lens)
    group_id = np.full(n, -1, dtype=np.int32)
    vrows = np.nonzero(valid)[0]
    if len(vrows) == 0:
        z = np.zeros(0)
        return BatchFold(group_id, np.zeros(0, np.int32), z,
                         np.zeros(0, np.int64), z, z, z,
                         np.zeros((0, n_hist), np.int64))
    mat = _key_matrix(arena, slots[vrows], key_offs[vrows],
                      key_lens[vrows])
    ids, first = _first_seen_ids(mat)
    group_id[vrows] = ids
    rep_row = vrows[first].astype(np.int32)
    G = int(ids.max()) + 1
    vv = values[vrows]
    sums = np.zeros(G, dtype=np.float64)
    # np.add.at applies adds in index order — the native loop's exact
    # accumulation order, which is what makes sums bit-identical (np.sum
    # style pairwise reduction would not be).  inf + -inf inside one key
    # is legal (sum -> NaN on every substrate): silence the warning
    with np.errstate(invalid="ignore"):
        np.add.at(sums, ids, vv)
    counts = np.bincount(ids, minlength=G).astype(np.int64)
    order = np.argsort(ids, kind="stable")
    sv = vv[order]
    starts = np.searchsorted(ids[order], np.arange(G))
    mins = np.minimum.reduceat(sv, starts)
    maxs = np.maximum.reduceat(sv, starts)
    ends = np.append(starts[1:], len(sv))
    last = sv[ends - 1]
    hist = np.zeros((G, n_hist), dtype=np.int64)
    np.add.at(hist, (ids, hist_bucket(vv, hist_base, n_hist)), 1)
    return BatchFold(group_id, rep_row, sums, counts, mins, maxs, last,
                     hist)


def fold_batch_native(arena: np.ndarray, slots: np.ndarray,
                      key_offs: np.ndarray, key_lens: np.ndarray,
                      val_offs: np.ndarray, val_lens: np.ndarray,
                      hist_base: float = HIST_BASE,
                      n_hist: int = N_HIST) -> Optional[BatchFold]:
    """The native substrate; None when the library is unavailable."""
    from ...native import group_reduce
    res = group_reduce(arena, slots, key_offs, key_lens, val_offs,
                       val_lens, hist_base=hist_base, n_hist=n_hist)
    if res is None:
        return None
    return BatchFold(*res)


# ---------------------------------------------------------------------------
# device twin


def build_reduce_fn(n_hist: int):
    """Returns jit-able f(values f32 [B], seg i32 [B], buckets i32 [B],
    valid bool [B], G static) -> (sum, count, min, max, last, hist).
    Invalid/padding rows route to segment id G — out of range, dropped by
    the scatter, never a branch."""
    import jax
    import jax.numpy as jnp

    def reduce_fn(values, seg, buckets, valid, G):
        seg = jnp.where(valid, seg, G)
        data = jnp.where(valid, values, jnp.float32(0))
        sums = jax.ops.segment_sum(data, seg, num_segments=G)
        cnt = jax.ops.segment_sum(valid.astype(jnp.int32), seg,
                                  num_segments=G)
        mins = jax.ops.segment_min(
            jnp.where(valid, values, jnp.float32(jnp.inf)), seg,
            num_segments=G)
        maxs = jax.ops.segment_max(
            jnp.where(valid, values, jnp.float32(-jnp.inf)), seg,
            num_segments=G)
        idx = jnp.arange(values.shape[0], dtype=jnp.int32)
        last_idx = jax.ops.segment_max(
            jnp.where(valid, idx, jnp.int32(-1)), seg, num_segments=G)
        last = jnp.where(last_idx >= 0,
                         values[jnp.clip(last_idx, 0, None)],
                         jnp.float32(0))
        hist = jax.ops.segment_sum(
            valid.astype(jnp.int32), seg * n_hist + buckets,
            num_segments=G * n_hist).reshape(G, n_hist)
        return sums, cnt, mins, maxs, last, hist

    return reduce_fn


class SegmentReduceKernel:
    """Owns the jitted segment-reduce for one histogram geometry.

    jit caches per (B, G) — `fold_batch` quantises B through
    ``ops.device_batch.pad_batch`` and G to a power of two, so a batch
    slot is ONE dispatch (`dispatch_count` asserted in the device test).
    `donated_call` mirrors the loongstream donated-buffer contract for
    the transient staging arrays."""

    def __init__(self, n_hist: int = N_HIST):
        import jax
        self.n_hist = n_hist
        self._fn = jax.jit(build_reduce_fn(n_hist), static_argnums=(4,))
        self._fn_donated = None
        self.dispatch_count = 0

    def __call__(self, values, seg, buckets, valid, G: int):
        self.dispatch_count += 1
        return self._fn(values, seg, buckets, valid, G)

    def donated_call(self, values, seg, buckets, valid, G: int):
        from .field_extract import donation_supported
        if not donation_supported():
            return self(values, seg, buckets, valid, G)
        if self._fn_donated is None:
            import jax
            self._fn_donated = jax.jit(build_reduce_fn(self.n_hist),
                                       static_argnums=(4,),
                                       donate_argnums=(0, 1, 2, 3))
        self.dispatch_count += 1
        return self._fn_donated(values, seg, buckets, valid, G)

    def fold_batch(self, arena: np.ndarray, slots: np.ndarray,
                   key_offs: np.ndarray, key_lens: np.ndarray,
                   val_offs: np.ndarray, val_lens: np.ndarray,
                   hist_base: float = HIST_BASE) -> BatchFold:
        """Device substrate: host keying + bucketing (exact f64), padded
        single-dispatch segment reduction on the accelerator."""
        import jax

        from ..device_batch import pad_batch
        n_hist = self.n_hist
        n = len(slots)
        values, valid = parse_values(arena, val_offs, val_lens)
        group_id = np.full(n, -1, dtype=np.int32)
        vrows = np.nonzero(valid)[0]
        if len(vrows) == 0:
            z = np.zeros(0)
            return BatchFold(group_id, np.zeros(0, np.int32), z,
                             np.zeros(0, np.int64), z, z, z,
                             np.zeros((0, n_hist), np.int64))
        mat = _key_matrix(arena, slots[vrows], key_offs[vrows],
                          key_lens[vrows])
        ids, first = _first_seen_ids(mat)
        group_id[vrows] = ids
        rep_row = vrows[first].astype(np.int32)
        G = int(ids.max()) + 1
        B = pad_batch(n)
        Gq = 16
        while Gq < G:
            Gq *= 2
        vals = np.zeros(B, dtype=np.float32)
        vals[:n] = values.astype(np.float32)
        seg = np.full(B, Gq, dtype=np.int32)
        seg[:n] = group_id.clip(min=0)
        ok = np.zeros(B, dtype=bool)
        ok[:n] = valid
        buckets = np.zeros(B, dtype=np.int32)
        buckets[:n] = hist_bucket(values, hist_base, n_hist)
        out = self.donated_call(vals, seg, buckets, ok, Gq)
        sums, cnt, mins, maxs, last, hist = (np.asarray(a) for a in
                                             jax.device_get(out))
        return BatchFold(group_id, rep_row,
                         sums[:G].astype(np.float64),
                         cnt[:G].astype(np.int64),
                         mins[:G].astype(np.float64),
                         maxs[:G].astype(np.float64),
                         last[:G].astype(np.float64),
                         hist[:G].astype(np.int64))


_device_kernel: Optional[SegmentReduceKernel] = None


def device_kernel() -> SegmentReduceKernel:
    global _device_kernel
    if _device_kernel is None:
        _device_kernel = SegmentReduceKernel()
    return _device_kernel


def hist_bucket_scalar(v: float, base: float = HIST_BASE,
                       n_hist: int = N_HIST) -> int:
    """Scalar shape twin for the per-event dict path (exactly the
    vectorised hist_bucket, which itself mirrors metrics.py)."""
    if math.isinf(v) and v > 0:
        return n_hist - 1
    if not v > base:
        return 0
    m, e = math.frexp(v / base)
    idx = e - 1 if m == 0.5 else e
    return min(max(idx, 0), n_hist - 1)
