"""Batched field extraction: Tier-1 segment programs on device.

Replaces the reference's hottest loop — per-event boost::regex_match with
capture-group extraction (ProcessorParseRegexNative.cpp:186-253) — with a
fully vectorised computation over a [B, L] byte tensor.

TPU-first formulation: NO gathers and NO sequential scans.  Per-element
gathers (LUT lookups, take_along_axis) and lax.scan/cummin chains are
TPU-hostile; every data-dependent query in the cursor walk is instead a
masked reduction over the length axis, which XLA fuses into tight VPU
loops:

    membership   m_c[b,l]        interval compares (elementwise)
    greedy end   min_l { l : ¬m_c[b,l] ∧ l ≥ cur[b] }        (min-reduce)
    run count    Σ_l   { m_c[b,l] ∧ cur ≤ l < cur+n }        (sum-reduce)
    literal ok   any_l { l = cur[b] ∧ lit_c[b,l] }           (or-reduce)

with lit_c precomputed by statically-shifted compares.  Composite ops
(optional groups, alternation) evaluate their bodies vectorised over ALL
rows and COMMIT per-row with masks — the branchless analogue of leftmost
/ greedy-preference semantics.  Everything is static-shape, jit-compiled
once per (program, B, L) geometry; the batch builder quantises B and L into
buckets to avoid recompilation storms (SURVEY.md §7 hard parts).

All per-row state is kept as [B, 1] columns (keepdims reductions) rather
than [B] vectors: the layout maps directly onto the VPU's (sublane, lane)
vregs, which lets the SAME walk body serve as the Pallas kernel body
(field_extract_pallas.py) where a [bB, L] tile is VMEM-resident and every
program op reads it without another HBM pass.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..regex.program import (INF, Alt, CapEnd, CapStart, FixedSpan, Lit,
                             Optional_, SegmentProgram, Span)


def _membership(rows: jnp.ndarray, intervals, complement_intervals) -> jnp.ndarray:
    """bool [B, L] membership via the cheaper of (intervals, ~complement).

    The OR-chain is seeded from the first interval compare, NOT from a
    `jnp.zeros` constant: constant i1 seeds get a sublane-replicated Mosaic
    layout, and `or`-ing a replicated mask with a data-derived one hits an
    unsupported i1 relayout ("non-singleton dimension replicated in
    destination but not source") when the Pallas path compiles on a real
    TPU.  Every mask here must stay data-dependent."""
    negate = len(complement_intervals) < len(intervals)
    if negate:
        intervals = complement_intervals
    m = None
    for lo, hi in intervals:
        t = (rows == lo) if lo == hi else ((rows >= lo) & (rows <= hi))
        m = t if m is None else (m | t)
    if m is None:                     # empty class: never matches
        m = rows != rows
    return ~m if negate else m


class _WalkState:
    """Per-row cursor/match/capture state threaded through the emitter.
    Everything is a [B, 1] column; capture columns are concrete default
    vectors from the start (offset 0, length -1 = absent), so branch
    merging is a pure element-wise select.  `ok` is carried as i32 0/1,
    not bool: Mosaic legalizes `select` on i1 VALUES through an i8
    round-trip whose final `arith.trunci i8 -> i1` the TPU backend
    rejects — predicates stay i1, selected data stays i32."""

    __slots__ = ("cur", "ok", "cap_off", "cap_len", "cap_start")

    def __init__(self, cur, ok, ncaps, init_caps: bool = True):
        self.cur = cur
        self.ok = ok
        if init_caps:
            B = cur.shape[0]
            zero = jnp.zeros((B, 1), jnp.int32)
            absent = jnp.full((B, 1), -1, jnp.int32)
            self.cap_off = [zero] * ncaps
            self.cap_len = [absent] * ncaps
            self.cap_start = [zero] * ncaps
        else:
            self.cap_off = []
            self.cap_len = []
            self.cap_start = []

    def copy(self) -> "_WalkState":
        st = _WalkState(self.cur, self.ok, 0, init_caps=False)
        st.cap_off = list(self.cap_off)
        st.cap_len = list(self.cap_len)
        st.cap_start = list(self.cap_start)
        return st

    def select(self, mask, taken: "_WalkState", other: "_WalkState") -> None:
        """self := taken where mask else other (element-wise per row)."""
        self.cur = jnp.where(mask, taken.cur, other.cur)
        self.ok = jnp.where(mask, taken.ok, other.ok)
        self.cap_off = [jnp.where(mask, a, b)
                        for a, b in zip(taken.cap_off, other.cap_off)]
        self.cap_len = [jnp.where(mask, a, b)
                        for a, b in zip(taken.cap_len, other.cap_len)]
        self.cap_start = [jnp.where(mask, a, b)
                          for a, b in zip(taken.cap_start, other.cap_start)]


def _any_row(mask: jnp.ndarray) -> jnp.ndarray:
    """`jnp.any(mask, axis=1, keepdims=True)` expressed as an i32
    max-reduction.  Mosaic lowers a bool (i1) row reduction through an i8
    accumulator and then emits `arith.trunci i8 -> i1`, which the TPU
    backend rejects ("Unsupported target bitwidth for truncation").
    Reducing in i32 and comparing sidesteps the i8 path entirely; under
    plain XLA the two forms fuse identically."""
    return jnp.max(mask.astype(jnp.int32), axis=1, keepdims=True) != 0


def walk_masks(program: SegmentProgram):
    """Static analysis shared by both builders: which class masks and
    literal-shift masks the walk needs."""
    span_classes: set = set()
    count_classes: set = set()
    literals: set = set()

    def collect(ops, reverse=False):
        for op in ops:
            if isinstance(op, Span):
                span_classes.add(op.class_id)
            elif isinstance(op, FixedSpan):
                count_classes.add(op.class_id)
            elif isinstance(op, Lit):
                # reverse-walk literals are stored reversed; the lit_ok map
                # is keyed by the forward spelling (match starting at l)
                literals.add(op.data[::-1] if reverse else op.data)
            elif isinstance(op, Optional_):
                collect(op.body, reverse)
            elif isinstance(op, Alt):
                for b in op.branches:
                    collect(b, reverse)
    collect(list(program.ops))
    if program.suffix_ops:
        collect(list(program.suffix_ops), reverse=True)
    if program.mid_ops:
        collect(list(program.mid_ops))
    if program.pivot is not None:
        count_classes.add(program.pivot.class_id)
    if program.pivot2 is not None:
        count_classes.add(program.pivot2.class_id)
    return span_classes, count_classes, literals


def build_extract_core(program: SegmentProgram):
    """Returns core(rows u8 [B,L], lens i32 [B,1]) ->
    (ok bool [B,1], cap_off i32 [B,C], cap_len i32 [B,C]).

    Pure jnp on the block it is given — usable directly under jit (XLA
    fuses the per-op reductions) or as a Pallas kernel body (the [B, L]
    tile stays VMEM-resident across ALL ops)."""

    ncaps = max(program.num_caps, 1)
    intervals = [c.intervals() for c in program.classes]
    comp_intervals = [c.negated().intervals() for c in program.classes]
    top_ops = list(program.ops)
    suffix_ops = list(program.suffix_ops) if program.suffix_ops else None
    pivot = program.pivot
    pivot2 = program.pivot2
    mid_ops = list(program.mid_ops) if program.mid_ops else None
    mid_end_caps = list(program.mid_end_caps)
    split_caps = list(program.split_caps)
    span_classes, count_classes, literals = walk_masks(program)
    if mid_ops is not None:
        mid_lit = next(op for op in mid_ops if isinstance(op, Lit))
        mid_fixed = len(mid_lit.data)

    def core(rows: jnp.ndarray, lens: jnp.ndarray):
        B, L = rows.shape
        i32 = jnp.int32
        # 2D iota: required inside Pallas/Mosaic, equivalent under XLA
        L32 = jnp.int32(L)
        # iota along lanes is row-constant, so Mosaic gives it a
        # sublane-REPLICATED layout; selects like `where(mask, pos, _)`
        # then try to relayout the i1 mask normal→replicated, which the
        # TPU backend rejects ("replicated in destination but not in
        # source").  Adding a data-dependent [B,1] zero column
        # de-replicates pos at the root; XLA folds the add elsewhere.
        pos = (jax.lax.broadcasted_iota(i32, (B, L), 1)
               + jnp.minimum(lens, 0))
        valid = pos < lens

        member: Dict[int, jnp.ndarray] = {}
        for cid in sorted(span_classes | count_classes):
            member[cid] = _membership(rows, intervals[cid],
                                      comp_intervals[cid]) & valid

        # Mosaic-layout discipline (see _membership): every i1 seed must be
        # data-dependent, or the Pallas compile trips an invalid replicated
        # relayout.  true/false columns derive from lens; lit chains start
        # at the first byte compare.
        true_col = lens >= 0              # always true, never replicated
        cur0 = jnp.minimum(lens, 0)       # always 0,   never replicated

        lit_ok: Dict[bytes, jnp.ndarray] = {}
        for lit in sorted(literals):
            data = np.frombuffer(lit, dtype=np.uint8)
            m = None
            for i, ch in enumerate(data):
                shifted = rows if i == 0 else jnp.concatenate(
                    [rows[:, i:], jnp.zeros((B, i), rows.dtype)], axis=1)
                t = shifted == ch
                m = t if m is None else (m & t)
            lit_ok[lit] = m if m is not None else (rows == rows)

        def emit(ops, st: _WalkState, active) -> None:
            """Apply ops to st for rows where `active` (bool [B,1])."""
            for op in ops:
                if isinstance(op, Lit):
                    k = len(op.data)
                    hit = _any_row((pos == st.cur) & lit_ok[op.data])
                    new_ok = (st.ok != 0) & hit & (st.cur + k <= lens)
                    st.ok = jnp.where(active, new_ok.astype(i32), st.ok)
                    st.cur = jnp.where(active,
                                       jnp.minimum(st.cur + k, L32), st.cur)
                elif isinstance(op, Span):
                    m = member[op.class_id]
                    cand = jnp.where(~m & (pos >= st.cur), pos, L32)
                    end = jnp.min(cand, axis=1, keepdims=True)
                    end = jnp.maximum(jnp.minimum(end, lens), st.cur)
                    run = end - st.cur
                    new_ok = (st.ok != 0) & (run >= op.min_len)
                    if op.max_len != INF:
                        new_ok = new_ok & (run <= op.max_len)
                    st.ok = jnp.where(active, new_ok.astype(i32), st.ok)
                    st.cur = jnp.where(active, end, st.cur)
                elif isinstance(op, FixedSpan):
                    new_ok = (st.ok != 0) & (st.cur + op.n <= lens)
                    if op.n > 0:
                        inside = (pos >= st.cur) & (pos < st.cur + op.n)
                        cnt = jnp.sum((member[op.class_id] & inside)
                                      .astype(i32), axis=1, keepdims=True)
                        new_ok = new_ok & (cnt == op.n)
                    st.ok = jnp.where(active, new_ok.astype(i32), st.ok)
                    st.cur = jnp.where(active,
                                       jnp.minimum(st.cur + op.n, L32), st.cur)
                elif isinstance(op, CapStart):
                    st.cap_start[op.cap_id] = jnp.where(
                        active, st.cur, st.cap_start[op.cap_id])
                elif isinstance(op, CapEnd):
                    start = st.cap_start[op.cap_id]
                    st.cap_off[op.cap_id] = jnp.where(
                        active, start, st.cap_off[op.cap_id])
                    st.cap_len[op.cap_id] = jnp.where(
                        active, st.cur - start, st.cap_len[op.cap_id])
                elif isinstance(op, Optional_):
                    before = st.copy()
                    emit(op.body, st, active)
                    take = active & (st.ok != 0)
                    # greedy preference: keep the body where it matched,
                    # revert (skip the group) where it failed
                    merged = _WalkState(st.cur, st.ok, 0, init_caps=False)
                    merged.select(take, st, before)
                    st.cur, st.ok = merged.cur, merged.ok
                    st.cap_off, st.cap_len = merged.cap_off, merged.cap_len
                    st.cap_start = merged.cap_start
                elif isinstance(op, Alt):
                    before = st.copy()
                    chosen_any = cur0         # all-zero i32, data-dependent
                    result = before.copy()
                    remaining = active & (st.ok != 0)
                    for branch in op.branches:
                        trial = before.copy()
                        emit(branch, trial, remaining)
                        chosen = remaining & (trial.ok != 0)
                        merged = _WalkState(result.cur, result.ok, 0,
                                            init_caps=False)
                        merged.select(chosen, trial, result)
                        result = merged
                        chosen_any = chosen_any | chosen.astype(i32)
                        remaining = remaining & ~chosen
                    st.cur = jnp.where(active, result.cur, before.cur)
                    st.ok = jnp.where(active, chosen_any, before.ok)
                    st.cap_off = result.cap_off
                    st.cap_len = result.cap_len
                    st.cap_start = result.cap_start
                else:  # pragma: no cover
                    raise AssertionError(op)

        def emit_reverse(ops, st: _WalkState, active, floor) -> None:
            """Right-to-left walk: st.cur is the EXCLUSIVE end boundary and
            moves toward 0.  Ops arrive pre-reversed (literal bytes too);
            the original CapEnd (seen first) records the group's right edge
            into cap_start, and CapStart closes it."""
            for op in ops:
                if isinstance(op, Lit):
                    k = len(op.data)
                    # match the (already reversed) literal ENDING at cur:
                    # forward bytes start at cur-k
                    fwd = op.data[::-1]
                    start = st.cur - k
                    hit = _any_row((pos == start) & lit_ok[fwd]) & (start >= 0)
                    st.ok = jnp.where(active,
                                      ((st.ok != 0) & hit).astype(i32), st.ok)
                    st.cur = jnp.where(active, jnp.maximum(start, 0), st.cur)
                elif isinstance(op, Span):
                    m = member[op.class_id]
                    # last non-member strictly below cur → run starts after it
                    cand = jnp.where(~m & (pos < st.cur), pos, jnp.int32(-1))
                    start = jnp.max(cand, axis=1, keepdims=True) + 1
                    if op.max_len != INF:
                        # bounded-maximal: a finite repeat takes at most
                        # max_len — the bytes below the clamp belong to
                        # whatever precedes (pivot or earlier suffix ops),
                        # whose own checks cascade a genuine mismatch
                        start = jnp.maximum(start, st.cur - op.max_len)
                    # the suffix may not reach below the pivot's minimal end:
                    # bytes under the floor belong to the prefix + pivot
                    start = jnp.maximum(start, floor)
                    start = jnp.minimum(jnp.maximum(start, 0), st.cur)
                    run = st.cur - start
                    new_ok = (st.ok != 0) & (run >= op.min_len)
                    st.ok = jnp.where(active, new_ok.astype(i32), st.ok)
                    st.cur = jnp.where(active, start, st.cur)
                elif isinstance(op, FixedSpan):
                    start = st.cur - op.n
                    new_ok = (st.ok != 0) & (start >= 0)
                    if op.n > 0:
                        inside = (pos >= start) & (pos < st.cur)
                        cnt = jnp.sum((member[op.class_id] & inside)
                                      .astype(i32), axis=1, keepdims=True)
                        new_ok = new_ok & (cnt == op.n)
                    st.ok = jnp.where(active, new_ok.astype(i32), st.ok)
                    st.cur = jnp.where(active, jnp.maximum(start, 0), st.cur)
                elif isinstance(op, CapEnd):
                    # right edge of the group (encountered first in reverse)
                    st.cap_start[op.cap_id] = jnp.where(
                        active, st.cur, st.cap_start[op.cap_id])
                elif isinstance(op, CapStart):
                    end = st.cap_start[op.cap_id]
                    st.cap_off[op.cap_id] = jnp.where(
                        active, st.cur, st.cap_off[op.cap_id])
                    st.cap_len[op.cap_id] = jnp.where(
                        active, end - st.cur, st.cap_len[op.cap_id])
                elif isinstance(op, Optional_):
                    before = st.copy()
                    emit_reverse(op.body, st, active, floor)
                    take = active & (st.ok != 0)
                    merged = _WalkState(st.cur, st.ok, 0, init_caps=False)
                    merged.select(take, st, before)
                    st.cur, st.ok = merged.cur, merged.ok
                    st.cap_off, st.cap_len = merged.cap_off, merged.cap_len
                    st.cap_start = merged.cap_start
                elif isinstance(op, Alt):
                    before = st.copy()
                    chosen_any = cur0         # all-zero i32, data-dependent
                    result = before.copy()
                    remaining = active & (st.ok != 0)
                    for branch in op.branches:
                        trial = before.copy()
                        emit_reverse(branch, trial, remaining, floor)
                        chosen = remaining & (trial.ok != 0)
                        merged = _WalkState(result.cur, result.ok, 0,
                                            init_caps=False)
                        merged.select(chosen, trial, result)
                        result = merged
                        chosen_any = chosen_any | chosen.astype(i32)
                        remaining = remaining & ~chosen
                    st.cur = jnp.where(active, result.cur, before.cur)
                    st.ok = jnp.where(active, chosen_any, before.ok)
                    st.cap_off = result.cap_off
                    st.cap_len = result.cap_len
                    st.cap_start = result.cap_start
                else:  # pragma: no cover
                    raise AssertionError(op)

        all_rows = true_col
        st = _WalkState(cur0, true_col.astype(i32), ncaps)
        emit(top_ops, st, all_rows)

        if pivot2 is not None:
            # double-pivot: prefix | pivot1 | MID-LITERAL | pivot2 | suffix.
            # Locate the boundary literal inside the gap with a min/max
            # reduce, then verify both pivot regions by masked counts
            # (soundness conditions enforced by _try_double_pivot).
            fwd_starts = {k: st.cap_start[k] for k in split_caps}
            rst = st.copy()
            rst.cur = lens
            floor = (st.cur + pivot.min_len + mid_fixed + pivot2.min_len)
            emit_reverse(suffix_ops, rst, all_rows, floor)
            lo1 = st.cur                  # pivot1 start
            hi2 = rst.cur                 # pivot2 exclusive end
            p_lo = lo1 + pivot.min_len
            p_hi = hi2 - mid_fixed - pivot2.min_len
            feasible = (lit_ok[mid_lit.data] & (pos >= p_lo)
                        & (pos <= p_hi))
            if pivot.lazy:                # both lazy: first occurrence
                cand = jnp.where(feasible, pos, L32)
                p = jnp.min(cand, axis=1, keepdims=True)
                found = p < L32
            else:                         # both greedy: last occurrence
                cand = jnp.where(feasible, pos, jnp.int32(-1))
                p = jnp.max(cand, axis=1, keepdims=True)
                found = p >= 0
            p = jnp.clip(p, 0, L32)
            # middle ops run on the shared forward state at cur = p: the
            # literal advances the cursor, cap markers record edges
            st.cur = jnp.where(found, p, lo1)
            st.ok = st.ok & found.astype(i32)
            emit(mid_ops, st, all_rows)
            lo2 = st.cur                  # pivot2 start (= p + |L|)
            run1 = p - lo1
            inside1 = (pos >= lo1) & (pos < p)
            cnt1 = jnp.sum((member[pivot.class_id] & inside1).astype(i32),
                           axis=1, keepdims=True)
            run2 = hi2 - lo2
            inside2 = (pos >= lo2) & (pos < hi2)
            cnt2 = jnp.sum((member[pivot2.class_id] & inside2).astype(i32),
                           axis=1, keepdims=True)
            ok = ((st.ok != 0) & (rst.ok != 0) & found & (hi2 >= lo2)
                  & (cnt1 == run1) & (run1 >= pivot.min_len)
                  & (cnt2 == run2) & (run2 >= pivot2.min_len))
            final = rst
            # caps closed in prefix already live in rst (copied after the
            # prefix walk); caps closed in the MIDDLE were recorded into st
            # after that copy — pull them over
            for k in mid_end_caps:
                final.cap_off[k] = st.cap_off[k]
                final.cap_len[k] = st.cap_len[k]
            # split caps: open in prefix/middle (forward left edge), close
            # in the suffix (reverse right edge)
            for k in split_caps:
                left = jnp.where(
                    found, st.cap_start[k], fwd_starts[k])
                final.cap_off[k] = left
                final.cap_len[k] = rst.cap_start[k] - left
            off = jnp.concatenate(final.cap_off, axis=1)
            length = jnp.concatenate(final.cap_len, axis=1)
            length = jnp.where(ok, length, -1)
            off = jnp.where(ok, off, 0)
            return ok, off, length

        if pivot is not None:
            # snapshot the forward left edges of split captures BEFORE the
            # reverse walk (its CapEnd reuses cap_start for right edges)
            fwd_starts = {k: st.cap_start[k] for k in split_caps}
            # reverse walk from the line end shares the capture state
            rst = st.copy()
            rst.cur = lens
            emit_reverse(suffix_ops, rst, all_rows, st.cur + pivot.min_len)
            # pivot covers [st.cur, rst.cur): must be all pivot-class bytes
            # within the span's length bounds (masked sum — no gathers)
            lo = st.cur
            hi = rst.cur
            run = hi - lo
            inside = (pos >= lo) & (pos < hi)
            cnt = jnp.sum((member[pivot.class_id] & inside).astype(i32),
                          axis=1, keepdims=True)
            ok = (st.ok != 0) & (rst.ok != 0) & (hi >= lo) & (cnt == run)
            ok = ok & (run >= pivot.min_len)
            if pivot.max_len != INF:
                ok = ok & (run <= pivot.max_len)
            # merge captures: split groups open where the FORWARD walk put
            # their CapStart and close at the reverse walk's right edge
            final = rst
            for k in split_caps:
                final.cap_off[k] = fwd_starts[k]
                final.cap_len[k] = rst.cap_start[k] - fwd_starts[k]
            off = jnp.concatenate(final.cap_off, axis=1)
            length = jnp.concatenate(final.cap_len, axis=1)
            length = jnp.where(ok, length, -1)
            off = jnp.where(ok, off, 0)
            return ok, off, length

        ok = (st.ok != 0) & (st.cur == lens)
        off = jnp.concatenate(st.cap_off, axis=1)
        length = jnp.concatenate(st.cap_len, axis=1)
        length = jnp.where(ok, length, -1)
        off = jnp.where(ok, off, 0)
        return ok, off, length

    return core


def build_extract_fn(program: SegmentProgram):
    """Returns jit-able f(rows u8 [B,L], lengths i32 [B]) ->
    (ok bool [B], cap_off i32 [B,C], cap_len i32 [B,C])."""
    core = build_extract_core(program)

    def extract(rows: jnp.ndarray, lengths: jnp.ndarray):
        ok, off, length = core(rows, lengths.astype(jnp.int32)[:, None])
        return ok[:, 0], off, length

    return extract


_donation_cached = None


def donation_supported() -> bool:
    """Buffer donation is real on TPU/GPU — XLA reuses the donated input
    HBM for outputs instead of allocating fresh buffers per dispatch (the
    loongstream ring's device-side half).  On CPU jit ignores donation
    with a per-call warning, so the donating variant is never built
    there."""
    global _donation_cached
    if _donation_cached is None:
        try:
            _donation_cached = jax.default_backend() in ("tpu", "gpu")
        except Exception:  # noqa: BLE001 — no backend ⇒ no donation
            _donation_cached = False
    return _donation_cached


class ExtractKernel:
    """Owns the jitted extraction function for one compiled program.

    jit caches per (B, L) geometry internally; callers should quantise shapes
    (see ops/device_batch.py) to bound the number of compilations.
    """

    def __init__(self, program: SegmentProgram):
        from ..compile_watch import watched_jit
        self.program = program
        self._fn = watched_jit(build_extract_fn(program), "extract")
        self._fn_donated = None

    def __call__(self, rows, lengths) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        ok, off, length = self._fn(rows, lengths)
        return ok, off, length

    def donated_call(self, rows, lengths
                     ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Streaming-path dispatch: inputs are per-dispatch host staging
        buffers (batch-ring slots), so their device copies are transient —
        donating them lets XLA alias that HBM for the outputs.  NOT safe
        for callers that re-use a device-resident input across calls (the
        bench kernel loop): those stay on __call__."""
        if not donation_supported():
            return self._fn(rows, lengths)
        if self._fn_donated is None:
            from ..compile_watch import watched_jit
            self._fn_donated = watched_jit(build_extract_fn(self.program),
                                           "extract",
                                           donate_argnums=(0, 1))
        return self._fn_donated(rows, lengths)

    @property
    def num_caps(self) -> int:
        return self.program.num_caps
