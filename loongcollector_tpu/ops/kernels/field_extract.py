"""Batched field extraction: Tier-1 segment programs on device.

Replaces the reference's hottest loop — per-event boost::regex_match with
capture-group extraction (ProcessorParseRegexNative.cpp:186-253) — with a
fully vectorised computation over a [B, L] byte tensor.

TPU-first formulation: NO gathers and NO sequential scans.  Per-element
gathers (LUT lookups, take_along_axis) and lax.scan/cummin chains are
TPU-hostile; every data-dependent query in the cursor walk is instead a
masked reduction over the length axis, which XLA fuses into tight VPU
loops:

    membership   m_c[b,l]        interval compares (elementwise)
    greedy end   min_l { l : ¬m_c[b,l] ∧ l ≥ cur[b] }        (min-reduce)
    run count    Σ_l   { m_c[b,l] ∧ cur ≤ l < cur+n }        (sum-reduce)
    literal ok   any_l { l = cur[b] ∧ lit_c[b,l] }           (or-reduce)

with lit_c precomputed by statically-shifted compares.  The cursor walk is
a dependency chain of ~#segments such reductions — each one pass over the
[B, L] tile.  Everything is static-shape, jit-compiled once per
(program, B, L) geometry; the batch builder quantises B and L into buckets
to avoid recompilation storms (SURVEY.md §7 hard parts).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..regex.program import (INF, CapEnd, CapStart, FixedSpan, Lit,
                             SegmentProgram, Span)


def _membership(rows: jnp.ndarray, intervals, complement_intervals) -> jnp.ndarray:
    """bool [B, L] membership via the cheaper of (intervals, ~complement)."""
    negate = len(complement_intervals) < len(intervals)
    if negate:
        intervals = complement_intervals
    m = jnp.zeros(rows.shape, dtype=bool)
    for lo, hi in intervals:
        if lo == hi:
            m = m | (rows == lo)
        else:
            m = m | ((rows >= lo) & (rows <= hi))
    return ~m if negate else m


def build_extract_fn(program: SegmentProgram):
    """Returns jit-able f(rows u8 [B,L], lengths i32 [B]) ->
    (ok bool [B], cap_off i32 [B,C], cap_len i32 [B,C])."""

    ncaps = max(program.num_caps, 1)
    # freeze python-side structures used at trace time
    intervals = [c.intervals() for c in program.classes]
    comp_intervals = [c.negated().intervals() for c in program.classes]
    ops = list(program.ops)
    span_classes = {op.class_id for op in ops if isinstance(op, Span)}
    count_classes = {op.class_id for op in ops if isinstance(op, FixedSpan)}
    literals = sorted({op.data for op in ops if isinstance(op, Lit)})

    def extract(rows: jnp.ndarray, lengths: jnp.ndarray):
        B, L = rows.shape
        i32 = jnp.int32
        pos = jnp.broadcast_to(jnp.arange(L, dtype=i32)[None, :], (B, L))
        valid = pos < lengths[:, None]                     # [B, L]

        # memberships, masked to the live span of each row
        member: Dict[int, jnp.ndarray] = {}
        for cid in sorted(span_classes | count_classes):
            member[cid] = _membership(rows, intervals[cid], comp_intervals[cid]) & valid

        # literal-match-at-position maps: lit_ok[b,l] ⇔ rows[b, l:l+k] == lit
        lit_ok: Dict[bytes, jnp.ndarray] = {}
        for lit in literals:
            data = np.frombuffer(lit, dtype=np.uint8)
            m = jnp.ones((B, L), dtype=bool)
            for i, ch in enumerate(data):
                if i == 0:
                    shifted = rows
                else:
                    # static shift: compare rows[:, l+i] at position l
                    shifted = jnp.concatenate(
                        [rows[:, i:], jnp.zeros((B, i), rows.dtype)], axis=1)
                m = m & (shifted == ch)
            lit_ok[lit] = m

        cur = jnp.zeros(B, i32)
        ok = jnp.ones(B, bool)
        cap_off = [jnp.zeros(B, i32) for _ in range(ncaps)]
        cap_len = [jnp.full(B, -1, i32) for _ in range(ncaps)]
        cap_start = [None] * ncaps
        L32 = jnp.int32(L)

        for op in ops:
            if isinstance(op, Lit):
                k = len(op.data)
                ok = ok & (cur + k <= lengths)
                hit = jnp.any((pos == cur[:, None]) & lit_ok[op.data], axis=1)
                ok = ok & hit
                cur = jnp.minimum(cur + k, L32)
            elif isinstance(op, Span):
                m = member[op.class_id]
                cand = jnp.where(~m & (pos >= cur[:, None]), pos, L32)
                end = jnp.min(cand, axis=1)
                end = jnp.minimum(end, lengths)   # run cannot pass end of row
                end = jnp.maximum(end, cur)
                run = end - cur
                ok = ok & (run >= op.min_len)
                if op.max_len != INF:
                    ok = ok & (run <= op.max_len)
                cur = end
            elif isinstance(op, FixedSpan):
                ok = ok & (cur + op.n <= lengths)
                if op.n > 0:
                    inside = (pos >= cur[:, None]) & (pos < (cur + op.n)[:, None])
                    cnt = jnp.sum((member[op.class_id] & inside).astype(i32), axis=1)
                    ok = ok & (cnt == op.n)
                cur = jnp.minimum(cur + op.n, L32)
            elif isinstance(op, CapStart):
                cap_start[op.cap_id] = cur
            elif isinstance(op, CapEnd):
                cap_off[op.cap_id] = cap_start[op.cap_id]
                cap_len[op.cap_id] = cur - cap_start[op.cap_id]
            else:  # pragma: no cover
                raise AssertionError(op)

        ok = ok & (cur == lengths)
        off = jnp.stack(cap_off, axis=1)
        length = jnp.stack(cap_len, axis=1)
        length = jnp.where(ok[:, None], length, -1)
        off = jnp.where(ok[:, None], off, 0)
        return ok, off, length

    return extract


class ExtractKernel:
    """Owns the jitted extraction function for one compiled program.

    jit caches per (B, L) geometry internally; callers should quantise shapes
    (see ops/device_batch.py) to bound the number of compilations.
    """

    def __init__(self, program: SegmentProgram):
        self.program = program
        self._fn = jax.jit(build_extract_fn(program))

    def __call__(self, rows, lengths) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        ok, off, length = self._fn(rows, lengths)
        return ok, off, length

    @property
    def num_caps(self) -> int:
        return self.program.num_caps
