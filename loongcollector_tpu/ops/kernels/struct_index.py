"""Structural-index device twin (loongstruct stage 1 on the accelerator).

One dispatch indexes a whole batch-ring slot: classify every byte of a
[B, L] row tensor into structural bitmaps — in-string, structural chars,
escaped positions, unescaped quotes — exactly mirroring the native
`lct_struct_index` word masks (differentially asserted in
tests/test_struct_index.py and scripts/struct_equivalence.py).

Formulation notes (the codesign lesson from the in-memory-matching paper:
pick the layout the substrate likes):

* the native plane resolves escapes with simdjson's odd-length
  backslash-run carry trick, word by word.  Here the whole row is one
  tensor, so the same semantics — a position is "escaped" iff it is NOT a
  backslash and the backslash run immediately before it has odd length —
  falls out of an associative max-scan (`last non-backslash position`)
  plus elementwise parity, with no sequential carry at all;
* the in-string mask is the inclusive prefix-XOR of unescaped quotes
  (opening quote inside, closing quote outside), i.e. a cumulative-sum
  parity along the length axis;
* masks pack to 16-bit words (int32-safe on every backend; the native
  uint64 words view as four such words on little-endian hosts).

The kernel is a single jitted function per (mode, B, L) geometry —
`StructIndexKernel.index_batch` packs a columnar group through the same
`ops.device_batch` length buckets the streaming plane uses and counts one
dispatch per slot (asserted single-invocation in the device test).  The
numpy twin below is the no-JAX fallback tier and the reference for both.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

MODE_JSON = "json"
MODE_DELIM = "delim"

_JSON_STRUCT = (0x7B, 0x7D, 0x5B, 0x5D, 0x3A, 0x2C)  # { } [ ] : ,
_WS = (0x20, 0x09, 0x0A, 0x0D)
_BS = 0x5C
_QUOTE = 0x22


def _pack16(bits, xp):
    """bool [B, L] -> int32 [B, ceil(L/16)] little-endian bit words."""
    B, L = bits.shape
    W = (L + 15) // 16
    pad = W * 16 - L
    if pad:
        bits = xp.concatenate(
            [bits, xp.zeros((B, pad), dtype=bool)], axis=1)
    weights = (xp.ones((), dtype=xp.int32) << xp.arange(16, dtype=xp.int32))
    return xp.sum(bits.reshape(B, W, 16).astype(xp.int32) * weights, axis=2)


def _index_core(rows, lengths, mode: str, sep: int, xp, scan_max):
    """Shared mask math: rows u8 [B, L], lengths i32 [B] ->
    (in_string, structural, escaped, quote) bool [B, L]."""
    B, L = rows.shape
    pos = xp.arange(L, dtype=xp.int32)[None, :] + xp.zeros(
        (B, 1), dtype=xp.int32)
    valid = pos < lengths.astype(xp.int32)[:, None]
    quote = (rows == _QUOTE) & valid
    if mode == MODE_JSON:
        bs = (rows == _BS) & valid
        # last non-backslash position at or before i (associative max-scan)
        lnb = scan_max(xp.where(~bs, pos, xp.int32(-1)))
        # run of backslashes ending at i-1 has length (i-1) - lnb(i-1);
        # odd run ⇒ the (non-backslash) byte at i is escaped
        run_prev = xp.concatenate(
            [xp.zeros((B, 1), dtype=xp.int32),
             (pos - lnb)[:, :-1]], axis=1)
        escaped = (~bs) & ((run_prev % 2) == 1) & valid
        st = xp.zeros((B, L), dtype=bool)
        for c in _JSON_STRUCT:
            st = st | (rows == c)
        st = st & valid
    else:
        escaped = xp.zeros((B, L), dtype=bool)
        st = (rows == sep) & valid
    q_real = quote & ~escaped
    in_string = (xp.cumsum(q_real.astype(xp.int32), axis=1) % 2) == 1
    in_string = in_string & valid
    structural = st & ~in_string
    return in_string, structural, escaped, q_real


def struct_index_numpy(rows: np.ndarray, lengths: np.ndarray,
                       mode: str = MODE_JSON, sep: int = 0x2C
                       ) -> Tuple[np.ndarray, ...]:
    """Numpy twin: packed int32 [B, W16] masks (in_string, structural,
    escaped, quote) — the degraded-tier index and the device reference."""
    rows = np.asarray(rows, dtype=np.uint8)
    lengths = np.asarray(lengths, dtype=np.int32)

    def scan_max(a):
        return np.maximum.accumulate(a, axis=1)

    masks = _index_core(rows, lengths, mode, sep, np, scan_max)
    return tuple(_pack16(m, np) for m in masks)


def unpack16(words, L: int) -> np.ndarray:
    """int32 [B, W16] -> bool [B, L] (inverse of the kernel packing)."""
    words = np.asarray(words)
    bits = (words[:, :, None] >> np.arange(16)) & 1
    return bits.reshape(words.shape[0], -1)[:, :L].astype(bool)


def native_masks_as_words16(mask_u64: np.ndarray) -> np.ndarray:
    """uint64 [n, W] native masks -> int32 [n, W*4] 16-bit words (the
    device packing), for differential comparison on little-endian hosts."""
    u16 = mask_u64.view(np.uint16).reshape(mask_u64.shape[0], -1)
    return u16.astype(np.int32)


def build_index_fn(mode: str, sep: int):
    """Returns jit-able f(rows u8 [B,L], lengths i32 [B]) -> 4 packed
    int32 [B, W16] masks.  Pure jnp — one fused dispatch per geometry."""
    import jax.numpy as jnp
    from jax.lax import associative_scan

    def scan_max(a):
        return associative_scan(jnp.maximum, a, axis=1)

    def index(rows, lengths):
        masks = _index_core(rows, lengths.astype(jnp.int32), mode, sep,
                            jnp, scan_max)
        return tuple(_pack16(m, jnp) for m in masks)

    return index


class StructIndexKernel:
    """Owns the jitted structural-index function for one mode.

    jit caches per (B, L) geometry; `index_batch` quantises shapes through
    ops.device_batch buckets so a batch-ring slot is ONE dispatch (the
    device test asserts dispatch_count).  `donated_call` mirrors the
    loongstream donated-buffer contract: ring-slot staging buffers are
    transient, so their device copies are donated to the outputs.
    """

    def __init__(self, mode: str = MODE_JSON, sep: int = 0x2C):
        from ..compile_watch import watched_jit
        self.mode = mode
        self.sep = sep
        self._fn = watched_jit(build_index_fn(mode, sep), "struct_index")
        self._fn_donated = None
        self.dispatch_count = 0

    def __call__(self, rows, lengths):
        self.dispatch_count += 1
        return self._fn(rows, lengths)

    def donated_call(self, rows, lengths):
        from .field_extract import donation_supported
        if not donation_supported():
            return self(rows, lengths)
        if self._fn_donated is None:
            from ..compile_watch import watched_jit
            self._fn_donated = watched_jit(
                build_index_fn(self.mode, self.sep), "struct_index",
                donate_argnums=(0, 1))
        self.dispatch_count += 1
        return self._fn_donated(rows, lengths)

    def index_batch(self, arena: np.ndarray, offsets: np.ndarray,
                    lengths: np.ndarray):
        """Pack a columnar group into a device batch (the loongstream slot
        geometry) and index it in one dispatch.  Returns (masks tuple of
        numpy int32 [n, W16], L) — rows beyond n are padding."""
        import jax

        from ..device_batch import pack_rows, pick_length_bucket
        n = len(offsets)
        L = pick_length_bucket(int(lengths.max()) if n else 1)
        if L is None:
            return None
        batch = pack_rows(arena, offsets.astype(np.int64),
                          np.asarray(lengths, dtype=np.int32), L)
        out = self.donated_call(batch.rows, batch.lengths)
        out = jax.device_get(out)
        return tuple(np.asarray(m)[:n] for m in out), L


# ---------------------------------------------------------------------------
# Span emission from the index (quote-mode delimiter).
#
# Vectorised over the whole batch for the CLEAN subset — rows whose quotes
# all delimit whole fields (RFC4180 shape: quote at a field edge, no
# doubled quotes, even parity).  Everything else is flagged deviant and
# handled by the caller's counted per-row fallback; the native fused walk
# (`lct_delim_struct_parse`) handles every shape without fallback.
# ---------------------------------------------------------------------------


def emit_delim_spans(arena: np.ndarray, offsets: np.ndarray,
                     lengths: np.ndarray, quote_bits: np.ndarray,
                     sep_bits: np.ndarray, F: int):
    """arena u8; offsets i64 / lengths i32 [n]; quote_bits / sep_bits
    bool [n, L] row-local (sep_bits = structural mask: separators outside
    the quote-parity in-string interpretation).  Returns (cap_off [n,F]
    i32, cap_len [n,F] i32, nfields [n] i32, deviant bool [n])."""
    n, L = quote_bits.shape
    lengths = np.asarray(lengths, dtype=np.int32)
    offsets = np.asarray(offsets, dtype=np.int64)
    cap_off = np.zeros((n, F), dtype=np.int32)
    cap_len = np.full((n, F), -1, dtype=np.int32)

    # deviance: odd quote parity, or any quote not adjacent to a field
    # boundary (row edge / real separator), or more fields than F (the
    # join rule rewrites bytes, which the span-only path cannot express)
    qcount = quote_bits.sum(axis=1)
    row_idx = np.arange(n, dtype=np.int64)
    last = np.maximum(lengths.astype(np.int64) - 1, 0)
    prev_sep = np.zeros_like(quote_bits)
    prev_sep[:, 1:] = sep_bits[:, :-1]
    next_sep = np.zeros_like(quote_bits)
    next_sep[:, :-1] = sep_bits[:, 1:]
    at_start = np.zeros_like(quote_bits)
    at_start[:, 0] = True
    at_end = np.zeros_like(quote_bits)
    at_end[row_idx, last] = lengths > 0
    boundary_ok = at_start | at_end | prev_sep | next_sep
    deviant = (qcount % 2 == 1) | (quote_bits & ~boundary_ok).any(axis=1)

    scount = sep_bits.sum(axis=1).astype(np.int32)
    nfields = np.where(lengths >= 0, scount + 1, 0).astype(np.int32)
    deviant = deviant | (nfields > F)

    # k-th separator position per row (k < F-1), via the CSR over nonzero
    srow, spos = np.nonzero(sep_bits)
    starts = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(scount, out=starts[1:])
    edges = np.full((n, F + 1), -1, dtype=np.int64)
    edges[:, 0] = 0
    for k in range(1, F):
        has = scount >= k
        idx = starts[:-1][has] + (k - 1)
        edges[has, k] = spos[idx] + 1 if len(srow) else -1
    # exclusive end per field: next separator or row end
    for k in range(F):
        start = edges[:, k]
        have = (start >= 0) & (k < nfields)
        nxt = np.where((k + 1 <= F - 1) & (edges[:, k + 1] > 0),
                       edges[:, k + 1] - 1, lengths.astype(np.int64))
        end = np.where(k == nfields - 1, lengths.astype(np.int64), nxt)
        start = np.where(have, start, 0)
        end = np.maximum(np.where(have, end, 0), start)
        # quoted-field strip: first byte is a quote (cleanliness has
        # already guaranteed the matching closing quote at the far edge)
        first_q = np.zeros(n, dtype=bool)
        nonempty = have & (end > start)
        if nonempty.any():
            first_q[nonempty] = quote_bits[row_idx[nonempty],
                                           start[nonempty]]
        strip = first_q & (end - start >= 2)
        start = start + strip
        end = end - strip
        cap_off[:, k] = np.where(have, offsets + start, 0).astype(np.int32)
        cap_len[:, k] = np.where(have, end - start, -1).astype(np.int32)
    return cap_off, cap_len, nfields, np.asarray(deviant, dtype=bool)
