"""Pallas-fused Tier-1 field extraction.

The XLA-path kernel (field_extract.py) expresses the segment walk as ~#ops
masked reductions over the full [B, L] tensor; whether they collapse into
one HBM pass depends on XLA's fuser.  This wrapper removes that bet: the
batch is gridded into [bB, L] row blocks, each block is DMA'd into VMEM
ONCE, and the ENTIRE program — membership masks, literal shift-compares,
forward walk, pivot check, reverse walk — runs on the resident tile.  HBM
traffic drops from O(#ops · B · L) worst-case to exactly one read of the
rows plus the tiny span outputs, which is the round-2 VERDICT's ask
("turn ~30 passes into 1").

The kernel BODY is the same `build_extract_core` walk used by the XLA path,
so every differential-fuzz guarantee transfers; the suite runs both paths
against each other (tests/test_pallas_kernel.py).

Reference hot loop being replaced: ProcessorParseRegexNative.cpp:186-253.
Mosaic constraints honoured (pallas_guide.md): 2D iota, [B,1] state
columns, u8 tiles with sublane-32 blocks, lane dim = L (multiple of 128
via device_batch LENGTH_BUCKETS), scalar-free control flow.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ..regex.program import SegmentProgram
from .field_extract import build_extract_core, walk_masks

# VMEM working-set budget per block: the u8 tile + per-class/per-literal
# bool masks + a few i32 temps, all [bB, L].
_VMEM_BUDGET = 8 * 1024 * 1024


def _pick_block_rows(B: int, L: int, n_masks: int) -> int:
    """Largest power-of-two row block whose working set fits the budget.

    Working set ≈ bB·L·(1 u8 + n_masks bool + ~8 i32-equivalent temps).
    Both B (≥256) and the result are powers of two, so the block always
    divides the batch exactly — no ragged edge to mask.
    """
    per_row = L * (1 + n_masks + 32)
    bB = 512
    while bB > 32 and bB * per_row > _VMEM_BUDGET:
        bB //= 2
    return min(bB, B)


def build_extract_fn_pallas(program: SegmentProgram,
                            interpret: Optional[bool] = None):
    """Returns f(rows u8 [B,L], lengths i32 [B]) ->
    (ok bool [B], cap_off i32 [B,C], cap_len i32 [B,C]).

    interpret=None auto-selects: compiled Mosaic on TPU, interpreter
    elsewhere (CPU tests / differential fuzzing)."""
    core = build_extract_core(program)
    ncaps = max(program.num_caps, 1)
    span_c, count_c, lits = walk_masks(program)
    n_masks = len(span_c | count_c) + len(lits)

    def kernel(rows_ref, len_ref, ok_ref, off_ref, cl_ref):
        rows = rows_ref[...]
        lens = len_ref[...]
        ok, off, length = core(rows, lens)
        ok_ref[...] = ok.astype(jnp.int32)
        off_ref[...] = off
        cl_ref[...] = length

    def extract(rows: jnp.ndarray, lengths: jnp.ndarray):
        B, L = rows.shape
        use_interpret = interpret
        if use_interpret is None:
            use_interpret = jax.default_backend() != "tpu"
        bB = _pick_block_rows(B, L, n_masks)
        grid = (B // bB,)
        row_block = pl.BlockSpec((bB, L), lambda i: (i, 0))
        col1 = pl.BlockSpec((bB, 1), lambda i: (i, 0))
        cap_block = pl.BlockSpec((bB, ncaps), lambda i: (i, 0))
        ok2, off, length = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[row_block, col1],
            out_specs=[col1, cap_block, cap_block],
            out_shape=[
                jax.ShapeDtypeStruct((B, 1), jnp.int32),
                jax.ShapeDtypeStruct((B, ncaps), jnp.int32),
                jax.ShapeDtypeStruct((B, ncaps), jnp.int32),
            ],
            interpret=use_interpret,
        )(rows, lengths.astype(jnp.int32)[:, None])
        return ok2[:, 0] != 0, off, length

    from ..compile_watch import watched_jit
    return watched_jit(extract, "extract_pallas", static_argnums=())


class PallasExtractKernel:
    """Drop-in sibling of ExtractKernel running the fused Pallas path."""

    def __init__(self, program: SegmentProgram,
                 interpret: Optional[bool] = None):
        self.program = program
        self._fn = build_extract_fn_pallas(program, interpret=interpret)

    def __call__(self, rows, lengths
                 ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        return self._fn(rows, lengths)

    @property
    def num_caps(self) -> int:
        return self.program.num_caps
