"""loongresident: single-dispatch pipeline fusion — the AOT stage compiler.

`BENCH_TPU_LAST_GOOD.json` shows the kernel at 128 GB/s while
`pipeline_e2e_MBps` sits at 2.0: with every device-capable stage running
its own pack → H2D → dispatch → materialise cycle, an N-stage pipeline
pays N synchronous device round trips per batch.  ParPaRaw's whole
contribution is never leaving the device between phases; the DFA
processing literature composes automata passes into one resident
execution.  This module does the same for a pipeline's consecutive
device-capable stages:

* **StageSpec / StageCond** — the declarative resident form of one stage
  (Tier-1 extract, fused multi-accept scan, structural index, filter keep
  mask).  A filter condition over a field the in-program extract stage
  just captured binds to that stage's DEVICE-RESIDENT span columns
  (``("capture", producer, cap)``) — no host bounce, no re-pack between
  stages.

* **FusedProgramKernel** — ONE jitted program per (stage list, B, L)
  geometry composed from the existing kernel cores
  (``build_extract_fn`` / ``build_fused_scan_fn`` / ``build_index_fn`` /
  ``build_dfa_match_fn`` / ``build_dfa_span_match_fn``): inputs packed
  once, inter-stage columns stay in HBM, every stage's outputs
  materialise together in one D2H.  ``donated_call`` mirrors the
  loongstream donated-buffer contract.

* **FusedDispatch** — the dispatch handle riding the EXISTING machinery:
  batch-ring slots (no allocator churn), the DevicePlane byte budget with
  the never-sleep-owning-budget drain rule, ≤ depth chunks in flight
  (loongstream window), WidthAutoTuner floors keyed per fused program
  (``("fused", sig)`` pseudo-lane buckets; a real chip lane's per-chip
  floors win on mesh hosts), chip-lane placement via the engine's
  ``_LanePlacedKernel``.  Per-chunk fault isolation DEMOTES a failing
  chunk to the per-stage dispatch path (each member stage's own kernel,
  separate dispatches) — events are never lost; demotions are counted
  (``fused_demotions_total``) and alarmed once per program.

* **Program cache** — content-addressed like the DFA cache: in-process
  LRU keyed by the sha256 of the stage identity list, plus
  ``<data_dir>/fused_cache/`` plan records persisting the stage list and
  the observed (B, L) geometries so a restart skips plan construction
  (``fused_program_cache_{hit,miss}_total``) and can AOT-warm the jit
  geometries (``LOONG_FUSED_WARM=1``).

Chaos point ``device_plane.fused_dispatch`` faults the materialise edge:
ERROR demotes that one chunk to the per-stage path, DELAY exercises the
ring deadline.  ``stage_fusion_status()`` feeds the /debug/status
``stage_fusion`` section and ``bench.py`` ``extra.stage_fusion``.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import chaos
from . import chip_lanes, xprof
from .chip_lanes import ChipLaneFault, lane_gated
from .device_batch import (LENGTH_BUCKETS, MAX_BATCH, pad_batch,
                           pick_length_bucket)
from .device_plane import mem_note_alloc, mem_note_free
from .device_stream import auto_tuner, batch_ring, h2d_gated, stream_depth

FP_FUSED_DISPATCH = chaos.register_point("device_plane.fused_dispatch")

CACHE_VERSION = 1
ENV_FUSED = "LOONG_FUSED"
ENV_WARM = "LOONG_FUSED_WARM"
ENV_CACHE = "LOONG_FUSED_CACHE"

#: flat-output width per stage kind
_STAGE_WIDTH = {"extract": 3, "scan": 1, "struct_index": 4, "keep": 1}


def fusion_enabled() -> bool:
    """Stage fusion routing: ``LOONG_FUSED=1`` forces, ``=0`` disables;
    unset → auto, ON exactly when the engines' own routing default is the
    device tier (an accelerator backend).  In host mode the per-stage
    native walkers already skip the round trips fusion exists to remove,
    so fusing there would only FORCE device dispatches the router proved
    slower."""
    env = os.environ.get(ENV_FUSED)
    if env is not None:
        return env != "0"
    try:
        from .regex.engine import _native_host_mode
        return not _native_host_mode()
    except Exception:  # noqa: BLE001 — no backend ⇒ no fusion
        return False


# ---------------------------------------------------------------------------
# stage model
# ---------------------------------------------------------------------------


class StageCond:
    """One boolean condition of a 'keep' stage (a filter Include/Exclude
    entry in resident form).

    kind: ``match`` (DFA full-match over the source rows), ``extract_ok``
    (Tier-1 program ok bit over the source rows), ``span_match`` (DFA
    full-match over a PRIOR stage's capture span, device-resident —
    ``binding=(producer_stage_idx, cap_idx)``).  ``staged`` is the
    condition's own kernel for the per-stage demotion path."""

    __slots__ = ("kind", "payload", "binding", "negate", "staged", "ident")

    def __init__(self, kind: str, payload, ident,
                 binding: Optional[Tuple[int, int]] = None,
                 negate: bool = False, staged: Optional[Callable] = None):
        self.kind = kind
        self.payload = payload
        self.binding = binding
        self.negate = negate
        self.staged = staged
        self.ident = ident


class StageSpec:
    """Declarative resident form of one device-capable pipeline stage.

    kind: ``extract`` (Tier-1 segment program → ok + capture spans),
    ``scan`` (fused multi-accept automaton → tag bitmask), ``struct_index``
    (structural bitmaps), ``keep`` (filter mask over StageConds).

    ``ident`` is the canonical content identity (pattern strings, mode)
    the program cache hashes; ``staged`` is the stage's OWN kernel (the
    existing per-stage dispatch path) used when a chunk demotes;
    ``terminal`` marks stages that rebuild the row population (multiline
    classify) and therefore must end a fused run."""

    __slots__ = ("kind", "payload", "ident", "staged", "terminal", "label")

    def __init__(self, kind: str, payload, ident, staged=None,
                 terminal: bool = False, label: str = ""):
        self.kind = kind
        self.payload = payload
        self.ident = ident
        self.staged = staged
        self.terminal = terminal
        self.label = label or kind

    @property
    def width(self) -> int:
        return _STAGE_WIDTH[self.kind]


def build_fused_fn(specs: Sequence[StageSpec]):
    """Compose the member stages' kernel cores into ONE jit-able
    f(rows u8 [B,L], lengths i32 [B]) -> flat tuple of stage outputs.
    Inter-stage values (capture spans feeding span-bound conditions) are
    jnp values — XLA keeps them in HBM; nothing crosses back to the host
    until the caller materialises the flat tuple once."""
    from .kernels.dfa_scan import (build_dfa_match_fn,
                                   build_dfa_span_match_fn,
                                   build_fused_scan_fn)
    from .kernels.field_extract import build_extract_fn
    from .kernels.struct_index import build_index_fn

    stage_fns: List = []
    for spec in specs:
        if spec.kind == "extract":
            stage_fns.append(build_extract_fn(spec.payload))
        elif spec.kind == "scan":
            stage_fns.append(build_fused_scan_fn(spec.payload))
        elif spec.kind == "struct_index":
            mode, sep = spec.payload
            stage_fns.append(build_index_fn(mode, sep))
        elif spec.kind == "keep":
            fns = []
            for cond in spec.payload:
                if cond.kind == "match":
                    fns.append(build_dfa_match_fn(cond.payload))
                elif cond.kind == "span_match":
                    fns.append(build_dfa_span_match_fn(cond.payload))
                elif cond.kind == "extract_ok":
                    fns.append(build_extract_fn(cond.payload))
                else:  # pragma: no cover
                    raise AssertionError(cond.kind)
            stage_fns.append(fns)
        else:  # pragma: no cover
            raise AssertionError(spec.kind)

    def fused(rows, lengths):
        stage_outs: List[Tuple] = []
        flat: List = []
        for spec, fn in zip(specs, stage_fns):
            if spec.kind == "extract":
                outs = tuple(fn(rows, lengths))
            elif spec.kind == "scan":
                outs = (fn(rows, lengths),)
            elif spec.kind == "struct_index":
                outs = tuple(fn(rows, lengths))
            else:  # keep
                keep = None
                for cond, cfn in zip(spec.payload, fn):
                    if cond.kind == "match":
                        # absent named-source rows (length -1) never
                        # match — the staged path's ``ok & src.present``
                        ok = cfn(rows, lengths) & (lengths >= 0)
                    elif cond.kind == "extract_ok":
                        ok = cfn(rows, lengths)[0] & (lengths >= 0)
                    else:  # span_match: prior stage's device-resident spans
                        prod, cap = cond.binding
                        _p_ok, p_off, p_len = stage_outs[prod]
                        ok = cfn(rows, lengths, p_off[:, cap], p_len[:, cap])
                    if cond.negate:
                        ok = ~ok
                    keep = ok if keep is None else (keep & ok)
                outs = (keep,)
            stage_outs.append(outs)
            flat.extend(outs)
        return tuple(flat)

    return fused


# ---------------------------------------------------------------------------
# the compiled program
# ---------------------------------------------------------------------------


class FusedProgramKernel:
    """Owns the jitted fused program for one stage list.

    jit caches per (B, L) geometry internally; the dispatcher quantises
    shapes through the device_batch buckets and the tuner's per-program
    floors so each geometry compiles once.  ``dispatch_count`` counts
    fused dispatches — the single-dispatch-per-batch-slot acceptance
    assertion reads it directly."""

    def __init__(self, specs: Sequence[StageSpec], signature: str):
        from .compile_watch import watched_jit
        self.specs = list(specs)
        self.signature = signature
        self._fn = watched_jit(build_fused_fn(self.specs), "fused_program")
        self._fn_donated = None
        self._donated_lock = threading.Lock()
        self._lane_kernels: Dict[int, object] = {}
        self._kernel_override = None
        self.dispatch_count = 0
        self.demotions = 0
        self.lane_respills = 0
        self.roundtrip_ms_total = 0.0
        self.idle_attr_ms = 0.0
        self.geometries: set = set()
        self._geom_dirty = False
        self.layout: List[Tuple[int, int]] = []
        i = 0
        for spec in self.specs:
            self.layout.append((i, spec.width))
            i += spec.width
        self.n_outputs = i

    # -- dispatch entry points ---------------------------------------------

    def __call__(self, rows, lengths):
        self.dispatch_count += 1
        return self._fn(rows, lengths)

    def donated_call(self, rows, lengths):
        """Streaming-path variant (see ExtractKernel.donated_call): the
        batch-ring staging buffers are transient, so their device copies
        are donated and XLA reuses that HBM for the outputs."""
        from .kernels.field_extract import donation_supported
        if not donation_supported():
            return self.__call__(rows, lengths)
        self.dispatch_count += 1
        return self._donated_fn()(rows, lengths)

    def _donated_fn(self):
        if self._fn_donated is None:
            with self._donated_lock:
                if self._fn_donated is None:
                    from .compile_watch import watched_jit
                    self._fn_donated = watched_jit(
                        build_fused_fn(self.specs), "fused_program",
                        donate_argnums=(0, 1))
        return self._fn_donated

    def set_kernel_override(self, kern) -> None:
        """Test/bench hook (mirrors RegexEngine.set_device_kernel_override):
        route this program's fused dispatches through ``kern`` — e.g. a
        LatencyInjectedKernel wrapping the jitted program to model a
        remote chip.  None restores normal selection."""
        self._kernel_override = kern

    def for_lane(self, lane):
        """Chip-lane placement (loongmesh): the fused program executes on
        the dispatching worker's home chip through the same placed-kernel
        wrapper the engines use."""
        k = self._lane_kernels.get(lane.index)
        if k is None:
            from .regex.engine import _LanePlacedKernel
            k = _LanePlacedKernel(self, lane)
            self._lane_kernels[lane.index] = k
        return k

    # -- per-stage demotion path -------------------------------------------

    def staged_run(self, rows: np.ndarray, lengths: np.ndarray) -> List:
        """The existing per-stage dispatch path over one packed chunk:
        each member stage's OWN kernel runs as its own dispatch and its
        outputs materialise before the next stage needs them (span-bound
        conditions read the producer's materialised captures).  This is
        the fault-isolation target — dispatch count N instead of 1,
        answers identical; the host pulls between stages here are the
        demotion tier by design."""
        outs: List[Tuple[np.ndarray, ...]] = []
        lens_np = np.asarray(lengths)
        for spec in self.specs:
            if spec.kind in ("extract", "scan", "struct_index"):
                raw = spec.staged(rows, lengths)
                if not isinstance(raw, (tuple, list)):
                    raw = (raw,)
                # demotion tier by design: per-stage dispatches with
                # materialised hand-off IS the per-stage fallback path
                # loonglint: disable=host-bounce
                outs.append(tuple(np.asarray(a) for a in raw))
            else:  # keep
                keep: Optional[np.ndarray] = None
                for cond in spec.payload:
                    if cond.kind == "match":
                        # loonglint: disable=host-bounce
                        ok = np.asarray(cond.staged(rows, lengths)) \
                            & (lens_np >= 0)
                    elif cond.kind == "extract_ok":
                        # loonglint: disable=host-bounce
                        ok = np.asarray(cond.staged(rows, lengths)[0]) \
                            & (lens_np >= 0)
                    else:
                        prod, cap = cond.binding
                        _ok, p_off, p_len = outs[prod]
                        # loonglint: disable=host-bounce
                        ok = np.asarray(cond.staged(
                            rows, lengths, p_off[:, cap], p_len[:, cap]))
                    if cond.negate:
                        ok = ~ok
                    keep = ok if keep is None else (keep & ok)
                outs.append((keep,))
        return outs

    # -- geometry ledger ----------------------------------------------------

    def note_geometry(self, B: int, L: int) -> None:
        if (B, L) not in self.geometries:
            self.geometries.add((B, L))
            self._geom_dirty = True
            _persist_plan(self)

    def warm(self) -> int:
        """AOT-compile the persisted geometries (restart warm start): the
        first data batch of a known shape then hits a ready executable.
        Warms the DONATED variant where donation is real — that is the
        jit the steady-state dispatch path actually runs — else the
        plain one.  Returns the number of geometries compiled."""
        from .kernels.field_extract import donation_supported
        fn = self._donated_fn() if donation_supported() else self._fn
        n = 0
        for B, L in sorted(self.geometries):
            rows = np.zeros((B, L), dtype=np.uint8)
            lens = np.zeros(B, dtype=np.int32)
            fn(rows, lens)
            n += 1
        return n

    def status(self) -> dict:
        return {
            "signature": self.signature,
            "stages": [s.label for s in self.specs],
            "dispatches": self.dispatch_count,
            "demotions": self.demotions,
            "lane_respills": self.lane_respills,
            "geometries": sorted(f"{b}x{l}" for b, l in self.geometries),
            "roundtrip_ms_total": round(self.roundtrip_ms_total, 3),
            "idle_while_backlogged_attr_ms": round(self.idle_attr_ms, 3),
        }


# ---------------------------------------------------------------------------
# content-addressed program cache (mem LRU + <data_dir>/fused_cache/)
# ---------------------------------------------------------------------------

_mem_cache: "OrderedDict[str, FusedProgramKernel]" = OrderedDict()
_mem_cache_lock = threading.Lock()
_MEM_CACHE_MAX = 64
_cache_dir: Optional[str] = None


def set_cache_dir(path: Optional[str]) -> None:
    """Application startup hook (mirrors fuse.set_cache_dir): fused plan
    records persist under ``<data_dir>/fused_cache/``."""
    global _cache_dir
    _cache_dir = path


def _resolved_cache_dir() -> Optional[str]:
    env = os.environ.get(ENV_CACHE)
    if env:
        return env
    return _cache_dir


def program_signature(specs: Sequence[StageSpec]) -> str:
    blob = json.dumps([CACHE_VERSION] + [_jsonable(s.ident) for s in specs],
                      ensure_ascii=False)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:20]


def _jsonable(ident):
    if isinstance(ident, (list, tuple)):
        return [_jsonable(x) for x in ident]
    return ident


def _plan_path(dirname: str, signature: str) -> str:
    return os.path.join(dirname, "fused_cache",
                        f"v{CACHE_VERSION}_{signature}.json")


def _persist_plan(program: FusedProgramKernel) -> None:
    dirname = _resolved_cache_dir()
    if not dirname or not program._geom_dirty:
        return
    program._geom_dirty = False
    path = _plan_path(dirname, program.signature)
    doc = {
        "version": CACHE_VERSION,
        "stages": [_jsonable(s.ident) for s in program.specs],
        "geometries": sorted([b, l] for b, l in program.geometries),
    }
    tmp = path + f".tmp.{os.getpid()}"
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def _load_plan(signature: str, specs: Sequence[StageSpec]) -> Optional[dict]:
    dirname = _resolved_cache_dir()
    if not dirname:
        return None
    try:
        with open(_plan_path(dirname, signature), "r",
                  encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if doc.get("version") != CACHE_VERSION:
        return None
    # hash-collision / stale-content guard, like the DFA cache: the stage
    # identity list as given must match the stored plan exactly
    if doc.get("stages") != [_jsonable(s.ident) for s in specs]:
        return None
    return doc


def get_fused_program(specs: Sequence[StageSpec]) -> FusedProgramKernel:
    """The two-level content-addressed cache: in-process LRU (hot-reloads
    reuse compiled programs) and the on-disk plan record (restarts skip
    plan construction and recover the geometry set for AOT warm)."""
    signature = program_signature(specs)
    with _mem_cache_lock:
        got = _mem_cache.get(signature)
        if got is not None:
            _mem_cache.move_to_end(signature)
    if got is not None:
        _count("fused_program_cache_hit_total")
        return got
    plan = _load_plan(signature, specs)
    program = FusedProgramKernel(specs, signature)
    if plan is not None:
        _count("fused_program_cache_hit_total")
        program.geometries = {(int(b), int(l))
                              for b, l in plan.get("geometries", [])}
        if os.environ.get(ENV_WARM) == "1":
            try:
                program.warm()
            except Exception:  # noqa: BLE001 — warm is best-effort
                pass
    else:
        _count("fused_program_cache_miss_total")
        program._geom_dirty = True
        _persist_plan(program)
    with _mem_cache_lock:
        # first-wins on a concurrent miss: every caller must share ONE
        # kernel object or per-program dispatch/demotion accounting (and
        # the jit cache) splits across losers — the aggregator-base
        # lazy-init race shape.  Construction above is cheap (jit
        # compiles lazily at first call), so a discarded loser wastes
        # closures, not a compile.
        existing = _mem_cache.get(signature)
        if existing is not None:
            program = existing
        else:
            _mem_cache[signature] = program
        _mem_cache.move_to_end(signature)
        while len(_mem_cache) > _MEM_CACHE_MAX:
            _mem_cache.popitem(last=False)
    return program


# ---------------------------------------------------------------------------
# metrics / status / alarm
# ---------------------------------------------------------------------------

_metrics_rec = None
_metrics_lock = threading.Lock()
_alarmed_programs: set = set()


def _metrics():
    global _metrics_rec
    if _metrics_rec is None:
        with _metrics_lock:
            if _metrics_rec is None:
                from ..monitor.metrics import MetricsRecord
                _metrics_rec = MetricsRecord(
                    category="component",
                    labels={"component": "loongresident"})
    return _metrics_rec


def _count(name: str, delta: int = 1) -> None:
    try:
        _metrics().counter(name).add(delta)
    except Exception:  # noqa: BLE001 — stats must never break dispatch
        pass


def _note_demotion(program: FusedProgramKernel, reason: str) -> None:
    """A chunk fell off the fused program to the per-stage path: counted
    always, alarmed once per program — silent demotion would hide exactly
    the round-trip regression this layer exists to remove."""
    program.demotions += 1
    _count("fused_demotions_total")
    with _metrics_lock:
        if program.signature in _alarmed_programs:
            return
        _alarmed_programs.add(program.signature)
    try:
        from ..monitor.alarms import AlarmManager, AlarmType
        AlarmManager.instance().send_alarm(
            AlarmType.FUSED_DEMOTED,
            f"fused pipeline program {program.signature} demoted a chunk "
            f"to per-stage dispatch ({reason}); stages="
            f"{[s.label for s in program.specs]}")
    except Exception:  # noqa: BLE001
        pass


def stage_fusion_status() -> dict:
    """The /debug/status ``stage_fusion`` section and bench.py
    ``extra.stage_fusion`` source: per-program dispatch/demotion rows plus
    the cache counters."""
    with _mem_cache_lock:
        programs = [p.status() for p in _mem_cache.values()]
    doc = {"enabled": fusion_enabled(), "programs": programs}
    try:
        rec = _metrics()
        for name in ("fused_program_cache_hit_total",
                     "fused_program_cache_miss_total",
                     "fused_demotions_total", "fused_dispatch_total",
                     "fused_lane_respill_total"):
            doc[name] = int(rec.counter(name).value)
    except Exception:  # noqa: BLE001
        pass
    return doc


def reset_for_testing() -> None:
    """Clear the in-process program cache and one-shot alarm state (tests
    must not inherit another test's dispatch counters or cache hits).
    Metrics records persist — process-lifetime instruments."""
    global _cache_dir
    with _mem_cache_lock:
        _mem_cache.clear()
    with _metrics_lock:
        _alarmed_programs.clear()
    _cache_dir = None


# ---------------------------------------------------------------------------
# the dispatch handle
# ---------------------------------------------------------------------------


class FusedBatchResult:
    """Assembled per-stage outputs in original row order.

    ``stages[i]`` for stage kind: extract → (ok bool [n], cap_off i32
    [n, C] ARENA-ABSOLUTE, cap_len i32 [n, C]); scan → (tags uint32 [n],);
    keep → (keep bool [n],); struct_index → (in_string, structural,
    escaped, quote) bool [n, Lmax]."""

    __slots__ = ("stages", "n")

    def __init__(self, stages: List[Tuple[np.ndarray, ...]], n: int):
        self.stages = stages
        self.n = n


class FusedDispatch:
    """One group's fused parse in flight (the PendingParse of the fused
    plane).  ``dispatch()`` packs chunks into leased batch-ring slots and
    submits the ONE fused program per chunk under the DevicePlane budget
    with ≤ depth chunks in flight; ``result()`` materialises in order and
    assembles per-stage outputs.  Fault isolation mirrors PendingParse:
    an injected ``device_plane.fused_dispatch`` (or h2d/submit) fault, a
    chip-lane fault, or a real kernel failure costs that ONE chunk a
    demotion to the per-stage dispatch path — never events, never ring
    order.  Every path releases the chunk's slot, budget and lane bytes."""

    __slots__ = ("program", "arena", "offsets", "lengths", "depth",
                 "_pending", "_stage_bufs", "_struct_parts", "_result",
                 "_n", "_idle_ms0", "_plane")

    def __init__(self, program: FusedProgramKernel, arena: np.ndarray,
                 offsets: np.ndarray, lengths: np.ndarray,
                 depth: Optional[int] = None):
        self.program = program
        self.arena = arena
        self.offsets = np.asarray(offsets, dtype=np.int64)
        self.lengths = np.asarray(lengths, dtype=np.int32)
        self.depth = max(1, depth if depth is not None else stream_depth())
        self._n = len(self.offsets)
        # [(chunk_idx, DeviceBatch, BatchSlot, DeviceFuture, lane)]
        self._pending: List = []
        self._stage_bufs = self._alloc_stage_bufs()
        self._struct_parts: Dict[int, List] = {}
        self._result: Optional[FusedBatchResult] = None
        from .device_plane import DevicePlane
        self._plane = DevicePlane.instance()
        self._idle_ms0 = \
            self._plane.utilization()["idle_while_backlogged_ms"]

    # -- assembly buffers ---------------------------------------------------

    def _alloc_stage_bufs(self) -> List:
        n = self._n
        bufs: List = []
        for spec in self.program.specs:
            if spec.kind == "extract":
                C = max(spec.payload.num_caps, 1)
                bufs.append((np.zeros(n, dtype=bool),
                             np.zeros((n, C), dtype=np.int32),
                             np.full((n, C), -1, dtype=np.int32)))
            elif spec.kind == "scan":
                bufs.append((np.zeros(n, dtype=np.uint32),))
            elif spec.kind == "keep":
                bufs.append((np.zeros(n, dtype=bool),))
            else:  # struct_index: ragged per-chunk widths, assembled late
                bufs.append(None)
        return bufs

    # -- dispatch -----------------------------------------------------------

    @property
    def done(self) -> bool:
        return self._result is not None

    def dispatch(self) -> "FusedDispatch":
        ring = batch_ring()
        tuner = auto_tuner()
        program = self.program
        lane = chip_lanes.current_lane()
        lane_count = chip_lanes.router().lane_count() if lane is not None \
            else 0
        max_bucket = LENGTH_BUCKETS[-1]
        device_idx = np.arange(self._n)
        try:
            for start in range(0, self._n, MAX_BATCH):
                chunk = device_idx[start:start + MAX_BATCH]
                if lane is not None and not lane.breaker.allow_probe():
                    # lane OPEN (or the half-open probe is in flight): the
                    # chip is sick — this chunk demotes to the per-stage
                    # path on the base kernels until the probe re-closes
                    # it.  Events still flow, counted as lane respill.
                    lane.note_respill(len(chunk))
                    program.lane_respills += 1
                    _count("fused_lane_respill_total")
                    self._staged_into(chunk)
                    continue
                while len(self._pending) >= self.depth:
                    self._drain_one()
                while lane is not None \
                        and lane.over_share(self._plane, lane_count) \
                        and self._pending:
                    self._drain_one()
                override = program._kernel_override
                if override is not None:
                    def call(r, l, _o=override, _p=program):
                        _p.dispatch_count += 1
                        return _o(r, l)
                elif lane is None:
                    call = program.donated_call
                else:
                    call = lane_gated(lane,
                                      program.for_lane(lane).donated_call)
                d_off = self.offsets[chunk]
                d_len = self.lengths[chunk]
                L = pick_length_bucket(int(d_len.max()) if len(d_len)
                                       else 1) or max_bucket
                lane_key = lane.index if lane is not None \
                    else f"fused:{program.signature[:8]}"
                B = pad_batch(len(chunk),
                              min_batch=tuner.min_batch_for(L, lane_key))
                program.note_geometry(B, L)
                slot = ring.lease(B, L)
                try:
                    batch = slot.pack(self.arena, d_off, d_len,
                                      lane=lane_key)
                    fut = self._plane.submit(
                        h2d_gated(call), (batch.rows, batch.lengths),
                        batch.rows.nbytes, on_wait=self._drain_if_pending)
                except BaseException:
                    slot.release()
                    raise
                _count("fused_dispatch_total")
                xprof.note_dispatch(fut, "fused", f"{B}x{L}",
                                    slot.pack_t0, slot.pack_dur)
                # loongxprof device-memory ledger: while this chunk is in
                # flight its inter-stage columns live device-side (that
                # residency is the whole point of fusion) — accounted at
                # the input-bytes proxy the plane budget already uses,
                # credited back when the chunk settles
                mem_note_alloc("resident_columns", batch.rows.nbytes)
                if lane is not None:
                    lane.note_pack(B, batch.n_real)
                    lane.note_dispatch(batch.rows.nbytes)
                self._pending.append((chunk, batch, slot, fut, lane))
        except BaseException:
            # a failed pack/submit must not strand the budget, ring slots
            # or lane accounting held by already-submitted chunks
            for _c, b, slot, fut, ln in self._pending:
                fut.release()
                mem_note_free("resident_columns", b.rows.nbytes)
                if ln is not None:
                    ln.note_done(b.rows.nbytes)
                    ln.breaker.on_inconclusive()
                slot.release()
            self._pending.clear()
            raise
        return self

    def _drain_if_pending(self) -> bool:
        if not self._pending:
            return False
        self._drain_one()
        return True

    # -- materialisation ----------------------------------------------------

    def _drain_one(self) -> None:
        chunk, batch, slot, fut, lane = self._pending.pop(0)
        program = self.program
        t0 = time.perf_counter()
        try:
            try:
                chaos.faultpoint(FP_FUSED_DISPATCH)
                flat = fut.result()
                if lane is not None:
                    lane.breaker.on_success()
            except ChipLaneFault:
                # injected single-chip fault: feed the lane breaker and
                # demote THIS chunk to the per-stage path on the base
                # kernels — the other chips' lanes never notice
                fut.release()
                lane.breaker.on_failure()
                lane.note_fault()
                lane.note_respill(int(batch.n_real))
                program.lane_respills += 1
                _count("fused_lane_respill_total")
                flat = self._staged_flat(batch, lane)
            except chaos.ChaosFault:
                # injected fused-dispatch (or h2d/submit) fault: the slot
                # still holds the packed rows — demote this ONE chunk to
                # the existing per-stage dispatch path, keep ring order
                fut.release()
                _note_demotion(program, "chaos fault at materialise")
                flat = self._staged_flat(batch, lane)
            except Exception as e:  # noqa: BLE001
                # real kernel failure (Mosaic/mesh/runtime): cost must be
                # dispatch count, never liveness — demote the chunk; a
                # failure on the per-stage path too propagates (that path
                # is the proven one)
                fut.release()
                if lane is not None:
                    lane.breaker.on_failure()
                    lane.note_fault()
                _note_demotion(program, f"kernel failure: {e!r}")
                flat = self._staged_flat(batch, lane)
            self._assemble(chunk, batch, flat)
            program.roundtrip_ms_total += (time.perf_counter() - t0) * 1e3
        finally:
            mem_note_free("resident_columns", batch.rows.nbytes)
            if lane is not None:
                lane.note_done(batch.rows.nbytes)
            slot.release()

    def _staged_flat(self, batch, lane) -> List[np.ndarray]:
        """Per-stage re-run of a demoted chunk (already packed in its
        slot).  The half-open probe outcome must reach the breaker: a
        clean per-stage run closes it, a failing one is inconclusive."""
        try:
            outs = self.program.staged_run(batch.rows, batch.lengths)
        except BaseException:
            if lane is not None:
                lane.breaker.on_inconclusive()
            raise
        if lane is not None:
            lane.breaker.on_success()
        return [a for tup in outs for a in tup]

    def _staged_into(self, chunk: np.ndarray) -> None:
        """Pre-dispatch demotion (lane OPEN): pack into a ring slot and
        run the per-stage path synchronously."""
        ring = batch_ring()
        d_len = self.lengths[chunk]
        L = pick_length_bucket(int(d_len.max()) if len(d_len) else 1) \
            or LENGTH_BUCKETS[-1]
        B = pad_batch(len(chunk))
        slot = ring.lease(B, L)
        try:
            batch = slot.pack(self.arena, self.offsets[chunk], d_len)
            flat = [a for tup in
                    self.program.staged_run(batch.rows, batch.lengths)
                    for a in tup]
            self._assemble(chunk, batch, flat)
        finally:
            slot.release()

    def _assemble(self, chunk: np.ndarray, batch, flat) -> None:
        n_real = batch.n_real
        flat = [np.asarray(a) for a in flat]
        for si, spec in enumerate(self.program.specs):
            start, width = self.program.layout[si]
            outs = flat[start:start + width]
            if spec.kind == "extract":
                ok_b, off_b, len_b = self._stage_bufs[si]
                ok_b[chunk] = outs[0][:n_real]
                # row-relative -> arena-absolute via the pack origins
                off_b[chunk] = (outs[1][:n_real]
                                + batch.origins[:n_real, None])
                len_b[chunk] = outs[2][:n_real]
            elif spec.kind == "scan":
                self._stage_bufs[si][0][chunk] = \
                    outs[0][:n_real].astype(np.uint32)
            elif spec.kind == "keep":
                self._stage_bufs[si][0][chunk] = \
                    np.asarray(outs[0][:n_real], dtype=bool)
            else:  # struct_index: keep packed words per chunk, unpack late
                self._struct_parts.setdefault(si, []).append(
                    (chunk, [o[:n_real] for o in outs], batch.rows.shape[1]))

    def result(self) -> FusedBatchResult:
        if self._result is not None:
            return self._result
        try:
            while self._pending:
                self._drain_one()
        except BaseException:
            for _c, b, slot, fut, ln in self._pending:
                try:
                    fut.result()
                except Exception:  # noqa: BLE001 — releasing, not consuming
                    pass
                if ln is not None:
                    ln.note_done(b.rows.nbytes)
                    ln.breaker.on_inconclusive()
                slot.release()
            self._pending.clear()
            raise
        stages: List[Tuple[np.ndarray, ...]] = []
        for si, spec in enumerate(self.program.specs):
            if spec.kind == "struct_index":
                stages.append(self._finish_struct(si))
            else:
                stages.append(self._stage_bufs[si])
        idle_now = self._plane.utilization()["idle_while_backlogged_ms"]
        self.program.idle_attr_ms += max(0.0, idle_now - self._idle_ms0)
        self._result = FusedBatchResult(stages, self._n)
        self.arena = None
        return self._result

    def _finish_struct(self, si: int) -> Tuple[np.ndarray, ...]:
        from .kernels.struct_index import unpack16
        parts = self._struct_parts.get(si, [])
        Lmax = max((L for _c, _m, L in parts), default=0)
        out = tuple(np.zeros((self._n, Lmax), dtype=bool) for _ in range(4))
        for chunk, masks, L in parts:
            for mi in range(4):
                out[mi][chunk, :L] = unpack16(masks[mi], L)
        return out
