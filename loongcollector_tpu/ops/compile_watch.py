"""compile_watch: shared jit-compile observability for every kernel family.

Before this module, only the fused-DFA cache counted its compiles
(``fuse_compile_total``) and only the fused-pipeline program cache
counted hits/misses — five of the seven kernel families compiled
invisibly, and the WidthAutoTuner's bucket-churn failure mode (a
flapping length bucket forcing a fresh XLA compile per flap) burned
silently.

``watched_jit(fn, family, **jit_kwargs)`` wraps ``jax.jit`` with the
per-geometry first-call proxy the repo already uses everywhere: jax
caches compiled executables per input shape, so the FIRST call of a
wrapper at a new geometry pays trace+compile (timed, counted as a cache
miss) and every later call at that geometry is a cache hit.  The wall
time recorded for a compile includes that first execution — it is the
first-dispatch cost the bench's warm-up window hides, which is exactly
the number ``extra.compile`` wants.

Per family this records:

  * ``jit_compile_total`` / ``jit_cache_hit_total`` counters and a
    ``jit_compile_ms`` histogram (labels: component=compile_watch,
    family=<family>) — fusion parity for the whole kernel vocabulary;
  * per-geometry compile counts + last compile wall-ms
    (``compile_status()``, the /debug/status ``compile`` section);
  * a one-shot ``RECOMPILE_STORM`` alarm when compiles inside the
    sliding window exceed the threshold, naming the churning family and
    its most recent geometry.  One alarm per episode: the flag re-arms
    only after the window drains empty (the storm ended).

The steady-state call path is one set-membership probe + one counter
add on top of the jitted call — the same order of cost as the
``dispatch_count += 1`` the kernel classes already pay.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

#: sliding storm window and the compile count inside it that trips the
#: alarm (≈ compiles/minute).  Module-level so tests (and operators via
#: monkeypatch) can tighten them; read at every compile note.
STORM_WINDOW_S = 60.0
STORM_COMPILES = 12


class _FamilyState:
    __slots__ = ("compiles", "cache_hits", "compile_ms_total",
                 "geometries", "recent", "alarmed", "episodes")

    def __init__(self) -> None:
        self.compiles = 0
        self.cache_hits = 0
        self.compile_ms_total = 0.0
        # geometry -> {"compiles": n, "last_ms": wall}
        self.geometries: Dict[str, dict] = {}
        # (perf_counter, geometry) of recent compiles, window-evicted
        self.recent: deque = deque()
        self.alarmed = False          # one alarm per storm episode
        self.episodes = 0


_lock = threading.Lock()
_families: Dict[str, _FamilyState] = {}
_records: Dict[str, object] = {}


def _family(name: str) -> _FamilyState:
    st = _families.get(name)
    if st is None:
        with _lock:
            st = _families.setdefault(name, _FamilyState())
    return st


def _record(family: str):
    rec = _records.get(family)
    if rec is None:
        with _lock:
            rec = _records.get(family)
            if rec is None:
                from ..monitor.metrics import MetricsRecord
                rec = MetricsRecord(category="component",
                                    labels={"component": "compile_watch",
                                            "family": family})
                _records[family] = rec
    return rec


def _compile_histogram(family: str):
    from ..monitor.metrics import shared_histogram
    return shared_histogram("jit_compile_ms",
                            labels={"component": "compile_watch",
                                    "family": family})


def _geometry_of(args: tuple, kwargs: dict) -> str:
    """Render the call geometry the way jax's executable cache keys it,
    best effort: array shapes, static scalars verbatim."""
    parts: List[str] = []
    for a in args:
        shape = getattr(a, "shape", None)
        if shape is not None:
            parts.append("x".join(map(str, shape)) or "scalar")
        elif isinstance(a, (int, float, bool, str, bytes)):
            parts.append(repr(a))
        else:
            parts.append(type(a).__name__)
    for k in sorted(kwargs):
        a = kwargs[k]
        shape = getattr(a, "shape", None)
        parts.append(f"{k}=" + ("x".join(map(str, shape))
                                if shape is not None else repr(a)))
    return ",".join(parts)


def _note_hit(family: str) -> None:
    st = _family(family)
    with _lock:
        st.cache_hits += 1
    try:
        _record(family).counter("jit_cache_hit_total").add(1)
    except Exception:  # noqa: BLE001 — stats must never break dispatch
        pass


def _note_compile(family: str, geometry: str, wall_ms: float) -> None:
    now = time.perf_counter()
    alarm_doc: Optional[Tuple[int, int]] = None
    with _lock:
        st = _families.setdefault(family, _FamilyState())
        st.compiles += 1
        st.compile_ms_total += wall_ms
        row = st.geometries.setdefault(geometry,
                                       {"compiles": 0, "last_ms": 0.0})
        row["compiles"] += 1
        row["last_ms"] = round(wall_ms, 3)
        # sliding-window storm detection: evict aged compiles first — an
        # empty window is the episode boundary that re-arms the alarm
        horizon = now - STORM_WINDOW_S
        while st.recent and st.recent[0][0] < horizon:
            st.recent.popleft()
        if not st.recent:
            st.alarmed = False
        st.recent.append((now, geometry))
        if len(st.recent) >= STORM_COMPILES and not st.alarmed:
            st.alarmed = True
            st.episodes += 1
            alarm_doc = (len(st.recent),
                         len({g for _t, g in st.recent}))
    try:
        rec = _record(family)
        rec.counter("jit_compile_total").add(1)
        rec.counter("jit_compile_ms_total").add(int(wall_ms))
        _compile_histogram(family).observe(wall_ms)
    except Exception:  # noqa: BLE001
        pass
    if alarm_doc is not None:
        _send_storm_alarm(family, geometry, *alarm_doc)


def _send_storm_alarm(family: str, geometry: str, n_compiles: int,
                      n_geometries: int) -> None:
    """Outside _lock (the loonglint blocking-under-lock rule): the alarm
    manager takes its own lock and mirrors into the flight ring."""
    try:
        from ..monitor.alarms import AlarmLevel, AlarmManager, AlarmType
        AlarmManager.instance().send_alarm(
            AlarmType.RECOMPILE_STORM,
            f"jit recompile storm: family={family} recompiled "
            f"{n_compiles} times across {n_geometries} geometries in "
            f"{STORM_WINDOW_S:.0f}s; churning geometry {geometry}",
            level=AlarmLevel.ERROR,
            details={"family": family, "geometry": geometry,
                     "compiles_in_window": str(n_compiles),
                     "distinct_geometries": str(n_geometries)})
    except Exception:  # noqa: BLE001 — alarms must never break dispatch
        pass


class WatchedFn:
    """A jitted callable under compile accounting.  The per-geometry
    seen-set is per wrapper (matching jax's per-jit executable cache);
    the counters aggregate per FAMILY, so a kernel class re-instantiated
    per program still rolls up under one name."""

    __slots__ = ("_fn", "family", "_seen", "_seen_lock")

    def __init__(self, fn, family: str):
        self._fn = fn
        self.family = family
        self._seen: set = set()
        self._seen_lock = threading.Lock()

    def __call__(self, *args, **kwargs):
        geometry = _geometry_of(args, kwargs)
        if geometry in self._seen:           # steady state: one probe
            _note_hit(self.family)
            return self._fn(*args, **kwargs)
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        wall_ms = (time.perf_counter() - t0) * 1000.0
        with self._seen_lock:
            first = geometry not in self._seen
            self._seen.add(geometry)
        if first:
            _note_compile(self.family, geometry, wall_ms)
        else:
            # a concurrent first call beat us to the compile: jax's
            # cache made this a hit, count it as one
            _note_hit(self.family)
        return out

    # pass-throughs some call sites use on the raw jitted fn
    def __getattr__(self, name):
        return getattr(self._fn, name)


def watched_jit(fn, family: str, **jit_kwargs) -> WatchedFn:
    """``jax.jit(fn, **jit_kwargs)`` under compile accounting — the only
    sanctioned way to jit a kernel under ops/ (loonglint: unwatched-jit)."""
    import jax
    return WatchedFn(jax.jit(fn, **jit_kwargs), family)


# ---------------------------------------------------------------------------
# status / reset


def compile_status() -> Dict[str, dict]:
    """Per-family compile ledger — the /debug/status ``compile`` section
    and the bench ``extra.compile`` source."""
    with _lock:
        out: Dict[str, dict] = {}
        for name in sorted(_families):
            st = _families[name]
            out[name] = {
                "compiles": st.compiles,
                "cache_hits": st.cache_hits,
                "compile_ms_total": round(st.compile_ms_total, 3),
                "storm_episodes": st.episodes,
                "geometries": {g: dict(row)
                               for g, row in sorted(st.geometries.items())},
            }
        return out


def reset_for_testing() -> None:
    with _lock:
        _families.clear()
