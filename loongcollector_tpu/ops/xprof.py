"""loongxprof: the device-execution timeline plane (off by default).

The four observability planes before this one (loongtrace / loongprof /
loongledger / loongslo) stop at the host: ``device.roundtrip`` is one
opaque stopwatch span.  This plane decomposes every device dispatch into
its legs —

  * ``h2d``      — host pack into the leased batch-ring slot (the H2D
    staging work; for the sharded plane, the per-shard device_put);
  * ``submit``   — the async kernel dispatch call itself;
  * ``exec``     — dispatch return → first output ready (the device
    execution window the host observes);
  * ``d2h``      — materialisation of the outputs into host numpy;

correlated by a **dispatch id** minted at `DevicePlane.submit` and
threaded through `DeviceFuture`, so the Chrome-trace exporter
(trace/export.py) can line device legs up under the host spans that
caused them.

Contract (mirrors chaos/plane.py and trace/tracer.py, which established
the idiom):

  * Disabled (the production default) every hook is ONE module-global
    read and an immediate return — `scripts/xprof_overhead.py` gates the
    cost against a plain no-op baseline (≤5% paired-min, like the
    trace/prof/ledger/slo gates).
  * Enabled, per-(program, geometry, leg) segment histograms feed the
    normal metrics tree (``device_segment_seconds``), so the dispatch
    decomposition is scrapable from /metrics without pulling the full
    timeline.
  * The timeline's *structure* (programs, geometries, leg names — never
    timestamps) is canonically serializable through
    ``trace.export.canonicalize``, so two runs of the same seeded storm
    compare byte-identical like the tracer does.

Activation: programmatic ``enable()`` / scoped ``active()`` for tests,
or ``LOONG_XPROF=1`` via ``install_from_env()`` at application start.
"""

from __future__ import annotations

import contextlib
import itertools
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

ENV_ENABLE = "LOONG_XPROF"

_DISPATCH_CAP = 50_000        # bounded like the tracer's span ring
_MAX_LEGS_PER_DISPATCH = 16   # submit/h2d/exec/d2h plus retries/annexes

#: the decomposition legs in pipeline order (export + bench ordering)
LEGS = ("h2d", "submit", "exec", "d2h")


class DispatchRecord:
    """One device dispatch's decomposition: identity, program, geometry,
    and the timed legs (start offsets are relative to the timeline
    epoch — perf_counter based, the same clock the tracer's spans use)."""

    __slots__ = ("id", "nbytes", "program", "geometry", "legs", "closed")

    def __init__(self, xid: int, nbytes: int):
        self.id = xid
        self.nbytes = nbytes
        self.program: Optional[str] = None
        self.geometry: Optional[str] = None
        # [(leg, start_s_rel_epoch, dur_s, attrs)]
        self.legs: List[Tuple[str, float, float, dict]] = []
        self.closed = False

    def leg_durations(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for leg, _t0, dur, _a in self.legs:
            out[leg] = out.get(leg, 0.0) + dur
        return out


class DeviceTimeline:
    """Process-wide dispatch-decomposition store.  All mutation is
    lock-cheap: one lock, short critical sections, bounded buffers."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._records: Dict[int, DispatchRecord] = {}
        self._order: List[int] = []
        self._ids = itertools.count(1)
        self._dropped = 0
        self._closed_total = 0
        #: perf_counter epoch — every leg start is stored relative to this
        self.epoch = time.perf_counter()

    # -- recording ----------------------------------------------------------

    def begin(self, nbytes: int) -> int:
        xid = next(self._ids)
        rec = DispatchRecord(xid, nbytes)
        with self._lock:
            if len(self._order) < _DISPATCH_CAP:
                self._records[xid] = rec
                self._order.append(xid)
            else:
                self._dropped += 1
        return xid

    def annotate(self, xid: int, program: Optional[str] = None,
                 geometry: Optional[str] = None) -> None:
        with self._lock:
            rec = self._records.get(xid)
            if rec is None:
                return
            if program is not None:
                rec.program = program
            if geometry is not None:
                rec.geometry = geometry

    def leg(self, xid: int, name: str, t_start: float, dur_s: float,
            **attrs) -> None:
        """Record one timed leg.  ``t_start`` is an absolute
        perf_counter() reading; it is stored relative to the epoch."""
        with self._lock:
            rec = self._records.get(xid)
            if rec is None or len(rec.legs) >= _MAX_LEGS_PER_DISPATCH:
                return
            rec.legs.append((name, t_start - self.epoch, dur_s, attrs))

    def close(self, xid: int) -> None:
        """Dispatch settled (materialised): fold its legs into the
        per-(program, geometry, leg) decomposition histograms.  Program
        and geometry are known by now — the dispatching caller annotates
        between submit and materialise."""
        with self._lock:
            rec = self._records.get(xid)
            if rec is None or rec.closed:
                return
            rec.closed = True
            self._closed_total += 1
            legs = list(rec.legs)
            program = rec.program or "unattributed"
            geometry = rec.geometry or "-"
        for leg, _t0, dur, _a in legs:
            _segment_histogram(program, geometry, leg).observe(dur)

    # -- retrieval ----------------------------------------------------------

    def dispatches(self) -> List[DispatchRecord]:
        with self._lock:
            return [self._records[x] for x in self._order]

    def decomposition(self) -> Dict[str, dict]:
        """Per-(program, geometry) leg totals — the compact /debug and
        bench view (full distributions live in the metric histograms)."""
        out: Dict[str, dict] = {}
        for rec in self.dispatches():
            key = f"{rec.program or 'unattributed'}:{rec.geometry or '-'}"
            row = out.setdefault(key, {
                "dispatches": 0, "closed": 0, "nbytes": 0,
                "legs_ms": {}, "legs_count": {}})
            row["dispatches"] += 1
            row["closed"] += 1 if rec.closed else 0
            row["nbytes"] += rec.nbytes
            for leg, dur in rec.leg_durations().items():
                row["legs_ms"][leg] = round(
                    row["legs_ms"].get(leg, 0.0) + dur * 1000.0, 3)
                row["legs_count"][leg] = row["legs_count"].get(leg, 0) + 1
        return out

    def stats(self) -> dict:
        with self._lock:
            return {"dispatches": len(self._order),
                    "closed": self._closed_total,
                    "dropped": self._dropped}


# ---------------------------------------------------------------------------
# decomposition histograms: one shared instrument per (program, geometry,
# leg) — bounded by the batch/length bucketing upstream


def _segment_histogram(program: str, geometry: str, leg: str):
    from ..monitor.metrics import shared_histogram
    return shared_histogram("device_segment_seconds",
                            labels={"component": "xprof",
                                    "program": program,
                                    "geometry": geometry,
                                    "leg": leg})


# ---------------------------------------------------------------------------
# module-level plane (the chaos/plane.py shape): one global, one branch


_timeline: Optional[DeviceTimeline] = None

_tls = threading.local()


def is_active() -> bool:
    return _timeline is not None


def active_timeline() -> Optional[DeviceTimeline]:
    """THE disabled-path hook: call sites read this once; None means the
    plane is off and nothing else may run."""
    return _timeline


def enable() -> DeviceTimeline:
    global _timeline
    t = DeviceTimeline()
    _timeline = t
    return t


def disable() -> None:
    global _timeline
    _timeline = None


@contextlib.contextmanager
def active():
    """Scoped activation for tests: ``with xprof.active() as t: ...``."""
    t = enable()
    try:
        yield t
    finally:
        disable()


def install_from_env(env=os.environ) -> bool:
    """LOONG_XPROF=1 activates the device timeline at application
    start."""
    raw = env.get(ENV_ENABLE)
    if not raw or raw.strip().lower() in ("0", "false", "no", "off"):
        return False
    enable()
    return True


# -- hot-path hooks: each is one global read + branch when disabled ---------


def begin_dispatch(nbytes: int) -> int:
    """Mint a dispatch id (DevicePlane.submit).  Disabled: a single
    branch, returns 0 (the null id every other hook short-circuits on)."""
    t = _timeline
    if t is None:
        return 0
    return t.begin(nbytes)


def leg(xid: int, name: str, t_start: float, dur_s: float, **attrs) -> None:
    """Record one timed leg for dispatch ``xid``.  Disabled (or null id):
    a single branch."""
    t = _timeline
    if t is None or not xid:
        return
    t.leg(xid, name, t_start, dur_s, **attrs)


def annotate(xid: int, program: Optional[str] = None,
             geometry: Optional[str] = None) -> None:
    t = _timeline
    if t is None or not xid:
        return
    t.annotate(xid, program=program, geometry=geometry)


def close_dispatch(xid: int) -> None:
    t = _timeline
    if t is None or not xid:
        return
    t.close(xid)


def note_dispatch(fut, program: str, geometry: str,
                  pack_t0: Optional[float] = None,
                  pack_dur: Optional[float] = None) -> None:
    """One-call convenience for the dispatch loops (PendingParse,
    FusedDispatch, DeviceStream): attribute the future's dispatch to a
    program + geometry and attach the pack/H2D leg the caller timed.
    Disabled: a single branch."""
    t = _timeline
    if t is None:
        return
    xid = getattr(fut, "dispatch_id", 0)
    if not xid:
        return
    t.annotate(xid, program=program, geometry=geometry)
    if pack_dur is not None and pack_t0 is not None:
        t.leg(xid, "h2d", pack_t0, pack_dur)


# -- current-dispatch TLS: lets code running INSIDE the submitted kernel
#    (ShardedKernel._dispatch runs under plane.submit's kernel call)
#    attach legs to the enclosing dispatch --------------------------------


def set_current_dispatch(xid: int) -> None:
    _tls.xid = xid


def current_dispatch() -> int:
    """The dispatch id of the enclosing plane.submit, 0 outside one.
    Disabled: a single branch."""
    t = _timeline
    if t is None:
        return 0
    return getattr(_tls, "xid", 0)


# -- status ----------------------------------------------------------------


def status() -> Optional[dict]:
    """The /debug/status ``xprof`` section; None while the plane is
    off (section absent, matching the other gated planes)."""
    t = _timeline
    if t is None:
        return None
    doc = t.stats()
    doc["decomposition"] = t.decomposition()
    return doc
