"""Device batch assembly: variable-length events → fixed-geometry tensors.

The hard part of putting a log parser on fixed-shape hardware (SURVEY.md §5.7,
§7): events have arbitrary lengths, XLA wants static shapes.  Strategy:

* row width L is quantised into LENGTH_BUCKETS; an event group picks the
  smallest bucket ≥ its longest event (overlong events are separated out for
  the CPU fallback path);
* batch size B is rounded up to a power of two (≥ MIN_BATCH) with zero-length
  padding rows, so each compiled kernel geometry (program, B, L) is reused;
* packing the arena into [B, L] rows is one vectorised numpy gather — the
  host-side analogue of the reference's single pread into the arena
  (reader/LogFileReader.cpp:1518); spans returned by the kernel are
  row-relative and are mapped back to arena offsets by adding row origins.

loongcolumn contract: ``pack_rows`` consumes (arena, offsets, lengths)
SPAN COLUMNS directly — the exact arrays a ``ColumnarLogs`` group carries
— with NO per-row Python list or bytes intermediary anywhere on the H2D
path (the native gather or the clipped index-matrix fallback read the
arena in place).  The loonglint ``hot-path-materialize`` checker enforces
this for all of ``ops/``: building row objects or lists here would
reintroduce exactly the per-event churn the columnar plane removed.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

LENGTH_BUCKETS = (128, 256, 512, 1024, 2048, 4096)
MIN_BATCH = 256
MAX_BATCH = 65536


def pick_length_bucket(max_len: int) -> Optional[int]:
    for b in LENGTH_BUCKETS:
        if max_len <= b:
            return b
    return None  # overlong → CPU fallback


def pad_batch(n: int, min_batch: Optional[int] = None,
              multiple_of: int = 1) -> int:
    """Power-of-two batch size ≥ n, capped at MAX_BATCH (callers must chunk
    inputs larger than MAX_BATCH).  ``min_batch`` lowers the floor below
    the static MIN_BATCH — the width auto-tuner
    (ops/device_stream.WidthAutoTuner) passes its per-length-bucket floor
    here so sparse traffic stops paying 256-row tensors for 8 real rows.

    ``multiple_of`` (loongmesh) rounds the result up to a shard multiple —
    the engine passes ``ShardedKernel.batch_multiple`` so mesh dispatches
    arrive shard-aligned and never pay a host-side realign copy.  A
    power-of-two mesh divides any pow2 B ≥ its size, so this only adds
    rows for odd mesh widths."""
    b = min_batch if min_batch else MIN_BATCH
    while b < n:
        b *= 2
    b = min(b, MAX_BATCH)
    if multiple_of > 1:
        b = max(b, multiple_of)
        if b % multiple_of:
            b += multiple_of - (b % multiple_of)
        if b > MAX_BATCH:
            # the MAX_BATCH cap outranks alignment: take the largest
            # in-cap multiple that still fits n, else plain MAX_BATCH
            # (the sharded kernel's private pad fallback realigns the
            # rare odd-width remainder)
            floor_mult = (MAX_BATCH // multiple_of) * multiple_of
            b = floor_mult if floor_mult >= n else MAX_BATCH
    return b


@dataclass
class DeviceBatch:
    """A packed batch plus the bookkeeping to map results back."""

    rows: np.ndarray          # uint8 [B, L]
    lengths: np.ndarray       # int32 [B] (0 for padding rows)
    origins: np.ndarray       # int32 [B] arena offset of each row's byte 0
    n_real: int               # number of non-padding rows


def pack_rows(arena: np.ndarray, offsets: np.ndarray, lengths: np.ndarray,
              L: int, B: Optional[int] = None,
              out: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]] = None
              ) -> DeviceBatch:
    """Gather per-event byte rows out of the flat arena.

    arena: uint8 [N]; offsets/lengths: int32 [n].  Events longer than L must
    be filtered out by the caller beforehand.

    ``out=(rows, lengths, origins)`` packs into pre-allocated [B, L]/[B]
    buffers instead of allocating — the streaming batch-ring path
    (ops/device_stream.BatchSlot) reuses the same host pages every
    generation, so the H2D staging side never churns the allocator.
    """
    n = len(offsets)
    if B is None:
        B = pad_batch(n)
    assert n <= B
    offsets = np.asarray(offsets, dtype=np.int64)
    lengths32 = np.asarray(lengths, dtype=np.int32)
    out_rows = None
    if out is not None:
        out_rows, out_lengths, out_origins = out
        assert out_rows.shape == (B, L), (out_rows.shape, B, L)

    from ..native import pack_rows as native_pack
    rows = native_pack(arena, offsets, lengths32, L, B, out=out_rows)
    if rows is None:
        # numpy fallback: index matrix [n, L], clipped so OOB reads land on
        # a valid byte, then tail-zeroed for deterministic padding
        idx = offsets[:, None] + np.arange(L, dtype=np.int64)[None, :]
        np.clip(idx, 0, len(arena) - 1 if len(arena) else 0, out=idx)
        body = arena[idx] if len(arena) else np.zeros((n, L), np.uint8)
        mask = np.arange(L, dtype=np.int32)[None, :] < lengths32[:, None]
        body &= mask.astype(np.uint8) * np.uint8(255)
        if out_rows is not None:
            rows = out_rows
            rows[:n] = body
            rows[n:] = 0
        elif B > n:
            rows = np.concatenate([body, np.zeros((B - n, L), np.uint8)],
                                  axis=0)
        else:
            rows = body
    if out is not None:
        out_lengths[:n] = lengths32
        out_lengths[n:] = 0
        out_origins[:n] = offsets.astype(np.int32)
        out_origins[n:] = 0
        return DeviceBatch(rows=rows, lengths=out_lengths,
                           origins=out_origins, n_real=n)
    if B > n:
        lengths32 = np.concatenate([lengths32, np.zeros(B - n, np.int32)])
        origins = np.concatenate(
            [offsets.astype(np.int32), np.zeros(B - n, np.int32)])
    else:
        origins = offsets.astype(np.int32)
    return DeviceBatch(rows=rows, lengths=lengths32, origins=origins, n_real=n)


def split_by_length(offsets: np.ndarray, lengths: np.ndarray,
                    max_bucket: int = LENGTH_BUCKETS[-1]
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """Returns (device_idx, overlong_idx) index arrays."""
    lengths = np.asarray(lengths)
    over = lengths > max_bucket
    idx = np.arange(len(lengths))
    return idx[~over], idx[over]
